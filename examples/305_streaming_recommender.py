"""305 - Streaming Recommender: files -> hashed ids -> packed rows -> DLRM.

The end-to-end recommender input path (docs/RECOMMENDER.md): a
``FileSource`` streams clickstream CSV shards, each shard becomes one
micro-batch whose categorical columns are hashed to embedding-table ids
by ``HashIndexer`` (stateless murmur3 — no vocabulary to ship, stable
across processes), the ids and dense features pack into the
``recommender_dlrm`` wire rows via ``pack_rows``, and the batches train
the DLRM-lite zoo model through ``DistributedTrainer``. Run:
``python examples/305_*.py``.
"""
from __future__ import annotations

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.data.pipeline import FileSource
from mmlspark_tpu.embed.model import pack_rows
from mmlspark_tpu.feature.value_indexer import HashIndexer
from mmlspark_tpu.models.zoo import build_model
from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
from mmlspark_tpu.parallel.trainer import DistributedTrainer

TABLES = (("user", 64), ("item", 128))
DENSE = 4            # price, position, hour, dwell
ROWS_PER_SHARD = 32  # one CSV file = one micro-batch


def _write_clickstream(root: str, shards: int = 6) -> str:
    """Synthetic clickstream shards: ``user,item,price,position,hour,
    dwell,clicked`` — the stand-in for a day of event logs."""
    rng = np.random.default_rng(305)
    for s in range(shards):
        lines = ["user,item,price,position,hour,dwell,clicked"]
        for _ in range(ROWS_PER_SHARD):
            u = f"u{rng.integers(0, 500):03d}"
            i = f"sku-{rng.integers(0, 2000):04d}"
            dense = rng.normal(size=DENSE)
            # clicks correlate with the first dense feature so the
            # model has signal to learn
            y = int(dense[0] + rng.normal(0.0, 0.5) > 0)
            lines.append(",".join([u, i] + [f"{v:.4f}" for v in dense]
                                  + [str(y)]))
        with open(os.path.join(root, f"events-{s:02d}.csv"), "w") as fh:
            fh.write("\n".join(lines) + "\n")
    return root


def _shard_to_batch(record: dict) -> dict:
    """One streamed file -> one packed train batch.

    CSV text -> Frame -> ``HashIndexer`` per categorical column
    (``numBuckets`` = the table's row count incl. the pad row, so real
    ids land in ``[1, rows)``) -> ``pack_rows`` wire format
    ``[dense | user id | item id]``.
    """
    rows = record["bytes"].decode().strip().split("\n")[1:]
    cols = list(zip(*(r.split(",") for r in rows)))
    frame = Frame.from_dict({
        "user": list(cols[0]),
        "item": list(cols[1]),
    })
    for (name, buckets) in TABLES:
        frame = HashIndexer(inputCol=name, outputCol=f"{name}_id",
                            numBuckets=buckets).transform(frame)
    dense = np.stack([np.asarray(c, np.float32)
                      for c in cols[2:2 + DENSE]], axis=1)
    ids = [frame.column(f"{name}_id").astype(np.int64)[:, None]
           for name, _ in TABLES]
    y = np.asarray(cols[-1], np.float32)
    return {"x": pack_rows(dense, ids), "y": y}


def main(data_dir: str | None = None) -> dict:
    data_dir = data_dir or tempfile.mkdtemp(prefix="clickstream-")
    _write_clickstream(data_dir)

    ds = (FileSource(data_dir)
          .map(_shard_to_batch)
          .repeat(4))

    mesh = make_mesh(MeshSpec(data=-1))   # all devices, data-parallel
    module = build_model("recommender_dlrm", dense_dim=DENSE,
                         tables=TABLES, embed_dim=8, slots=1,
                         bottom=(16,), top=(16,))["module"]

    def loss_fn(params, batch, rng):
        logits = module.apply(params, batch["x"])
        return optax.sigmoid_binary_cross_entropy(
            logits[:, 0], batch["y"]).mean()

    opt = optax.adam(1e-2)
    trainer = DistributedTrainer(loss_fn, opt, mesh=mesh)
    width = DENSE + len(TABLES)
    init_fn = lambda: module.init(  # noqa: E731
        jax.random.PRNGKey(0), jnp.zeros((1, width), jnp.float32))
    state = trainer.init(init_fn)

    losses = []
    for host_batch in ds:
        batch = trainer.put_batch(host_batch)
        state, m = trainer.train_step(state, batch, jax.random.PRNGKey(0))
        losses.append(float(jax.device_get(m["loss"])))

    out = {"batches": len(losses), "loss_first": losses[0],
           "loss_last": losses[-1]}
    print(f"305 streaming recommender: {out}")
    return out


if __name__ == "__main__":
    main()
