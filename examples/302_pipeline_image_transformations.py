"""302 - Pipeline Image Transformations.

Mirrors ``notebooks/samples/302 - Pipeline Image Transformations.ipynb``:
read an image directory into a frame, chain declarative ImageTransformer
stages (resize -> crop -> grayscale -> blur -> threshold), and unroll the
result to a feature vector.
"""
from __future__ import annotations

import tempfile

import numpy as np

from _datasets import image_dir
from mmlspark_tpu.image.transformer import ImageTransformer, UnrollImage
from mmlspark_tpu.io.readers import read_images


def main() -> dict:
    root = tempfile.mkdtemp()
    image_dir(root, n=12)
    frame = read_images(root, recursive=True)

    tr = (ImageTransformer(inputCol="image", outputCol="transformed")
          .resize(32, 32)
          .center_crop(24, 24)
          .color_format("bgr2gray")
          .blur(3, 3)
          .threshold(64, 255, "binary"))
    out = tr.transform(frame)
    unrolled = UnrollImage(inputCol="transformed",
                           outputCol="features").transform(out)
    feats = np.asarray(unrolled.column("features"))
    # thresholded grayscale: every pixel is 0 or 255
    values = set(np.unique(feats).tolist())
    result = {"n_images": int(feats.shape[0]), "dim": int(feats.shape[1]),
              "pixel_values": sorted(values)}
    print(f"302 image transforms: {result}")
    return result


if __name__ == "__main__":
    main()
