"""ComputePerInstanceStatistics: per-row evaluation metrics.

Re-expression of
``compute-per-instance-statistics/src/main/scala/ComputePerInstanceStatistics.scala:36-92``:

- classification: per-row ``log_loss`` with eps=1e-15 clipping and the
  unseen-label penalty ``-log(eps)`` when the true-label index falls outside
  the probability vector (reference ``:64-90``);
- regression: per-row ``L1_loss`` and ``L2_loss``.

Column discovery rides the same score metadata as ComputeModelStatistics.
"""
from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import ColumnSchema, DType, ScoreKind, find_score_column, find_score_value_kind
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.core.params import StringParam

EPSILON = 1e-15  # reference epsilon (ComputePerInstanceStatistics.scala:67)


@register_stage
class ComputePerInstanceStatistics(Transformer):
    labelCol = StringParam("labelCol", "label column override", "")

    def transform(self, frame: Frame) -> Frame:
        schema = frame.schema
        label = self.labelCol or find_score_column(schema, ScoreKind.TRUE_LABELS) \
            or ("label" if "label" in schema else None)
        if label is None:
            raise ValueError("cannot discover label column")
        kind = find_score_value_kind(schema) or ScoreKind.CLASSIFICATION

        if kind == ScoreKind.REGRESSION:
            scores = find_score_column(schema, ScoreKind.SCORES)
            if scores is None:
                raise ValueError("no scores column for regression")

            def l1(p):
                return np.abs(np.asarray(p[scores], np.float64)
                              - np.asarray(p[label], np.float64))

            def l2(p):
                d = np.asarray(p[scores], np.float64) \
                    - np.asarray(p[label], np.float64)
                return d * d

            out = frame.with_column(ColumnSchema("L1_loss", DType.FLOAT64), l1)
            return out.with_column(ColumnSchema("L2_loss", DType.FLOAT64), l2)

        probs_col = find_score_column(schema, ScoreKind.SCORED_PROBABILITIES)
        if probs_col is None:
            raise ValueError("no scored-probabilities column for log_loss")
        scored_labels = find_score_column(schema, ScoreKind.SCORED_LABELS)
        cmap = schema[label].categorical or (
            schema[scored_labels].categorical if scored_labels else None)

        def log_loss(p):
            from mmlspark_tpu.evaluate.compute_model_statistics import (
                map_labels_to_indices)
            probs = np.asarray(p[probs_col], np.float64)
            raw = p[label]
            if cmap is not None:
                # numeric labels need mapping too: levels [3,5,7] -> 0..2
                idx = map_labels_to_indices(raw, cmap)
            elif raw.dtype == np.object_:
                raise ValueError(
                    f"label column {label!r} holds strings but carries no "
                    "categorical metadata")
            else:
                idx = np.asarray(raw, np.float64).astype(np.int64)
            n, k = probs.shape
            out = np.full(n, -np.log(EPSILON))  # unseen-label penalty
            in_range = (idx >= 0) & (idx < k)
            rows = np.nonzero(in_range)[0]
            clipped = np.clip(probs[rows, idx[rows]], EPSILON, 1 - EPSILON)
            out[rows] = -np.log(clipped)
            return out

        return frame.with_column(ColumnSchema("log_loss", DType.FLOAT64), log_loss)
