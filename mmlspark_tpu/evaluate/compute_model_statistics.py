"""ComputeModelStatistics: evaluator-as-transformer with metadata discovery.

Re-expression of
``compute-model-statistics/src/main/scala/ComputeModelStatistics.scala:86-559``:
discovers which columns are labels/scores/probabilities from column metadata
stamped by TrainedClassifierModel (``getSchemaInfo`` ``:205-218``), then:

- classification: confusion matrix, accuracy/precision/recall (binary
  ``:449-459``; multiclass micro/macro per Sokolova–Lapalme ``:375-429``),
  AUC + ROC curve retained as the ``roc_curve`` attribute (``:431-447``);
- regression: mse/rmse/r2/mae (``:181-199``).

Metric names match the reference's Spark-metric spellings
(``ComputeModelStatistics.scala:26-59``). The observable API is the same:
metrics are *returned as a Frame*.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import Params, StringParam
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import (
    ScoreKind, find_score_column, find_score_value_kind,
)
from mmlspark_tpu.core.serialization import register_stage

# Spark-metric spellings (reference :26-37)
MSE, RMSE, R2, MAE = "mse", "rmse", "r2", "mae"
AUC, ACCURACY, PRECISION, RECALL = "AUC", "accuracy", "precision", "recall"
AUC_PR = "AUC_PR"
ALL_METRICS = "all"
CLASSIFICATION_METRICS = [ACCURACY, PRECISION, RECALL, AUC, AUC_PR,
                          "weighted_precision", "weighted_recall",
                          "weighted_f1"]
REGRESSION_METRICS = [MSE, RMSE, R2, MAE]


def map_labels_to_indices(arr: np.ndarray, cmap) -> np.ndarray:
    """Map raw label values (string OR numeric) to level indices; values
    outside the map get index ``num_levels`` (the unseen slot)."""
    return np.asarray(
        [cmap.get_index(v.item() if isinstance(v, np.generic) else v,
                        default=cmap.num_levels) for v in arr],
        dtype=np.int64)


def roc_curve(labels: np.ndarray, scores: np.ndarray) -> np.ndarray:
    """Binary ROC curve points (fpr, tpr) sorted by descending threshold."""
    order = np.argsort(-scores, kind="stable")
    labels = labels[order]
    tps = np.cumsum(labels)
    fps = np.cumsum(1 - labels)
    P = max(tps[-1] if len(tps) else 0, 1)
    N = max(fps[-1] if len(fps) else 0, 1)
    # keep last point per distinct score to get the staircase vertices
    distinct = np.r_[np.nonzero(np.diff(scores[order]))[0], len(labels) - 1] \
        if len(labels) else np.array([], dtype=int)
    fpr = np.r_[0.0, fps[distinct] / N]
    tpr = np.r_[0.0, tps[distinct] / P]
    return np.stack([fpr, tpr], axis=1)


def auc_from_roc(curve: np.ndarray) -> float:
    return float(np.trapezoid(curve[:, 1], curve[:, 0]))


def pr_curve(labels: np.ndarray, scores: np.ndarray) -> np.ndarray:
    """Binary precision-recall curve points (recall, precision) by
    descending threshold, with the (0, 1) anchor Spark's
    BinaryClassificationMetrics prepends — its areaUnderPR is the
    benchmark-pinned second metric column (benchmarkMetrics.csv)."""
    order = np.argsort(-scores, kind="stable")
    labels = labels[order]
    tps = np.cumsum(labels)
    fps = np.cumsum(1 - labels)
    P = max(tps[-1] if len(tps) else 0, 1)
    distinct = np.r_[np.nonzero(np.diff(scores[order]))[0], len(labels) - 1] \
        if len(labels) else np.array([], dtype=int)
    recall = np.r_[0.0, tps[distinct] / P]
    with np.errstate(divide="ignore", invalid="ignore"):
        prec = np.r_[1.0, tps[distinct] /
                     np.maximum(tps[distinct] + fps[distinct], 1)]
    return np.stack([recall, prec], axis=1)


# same trapezoid over (x, y) points; distinct name kept for call-site clarity
auc_from_pr = auc_from_roc


def confusion_matrix(y: np.ndarray, pred: np.ndarray, k: int) -> np.ndarray:
    cm = np.zeros((k, k), dtype=np.int64)
    np.add.at(cm, (y.astype(int), pred.astype(int)), 1)
    return cm


# -- device-path evaluators --------------------------------------------------
# Above ``evaluate.device_rows`` rows, metrics run as ONE fused jitted XLA
# program instead of driver numpy: the scored column stays columnar, the
# confusion matrix accumulates on device in a scan over fixed-size row
# chunks (donated accumulator — no second buffer), the AUC staircase runs
# in the same program, and the driver sees exactly ONE counted host sync
# per evaluate call (``evaluate.finalize``) fetching the k x k confusion
# plus two scalars — the everything-streams-to-device story applied to
# evaluation, where the reference funneled the whole scored RDD through
# driver-side Spark aggregations (``ComputeModelStatistics.scala:86-559``).
# Below the threshold the numpy path wins on latency (no transfer, no
# compile).

# rows per scan chunk: fixed so the chunk program shape is stable and the
# number of distinct compiled shapes grows with log-ish dataset size, not
# per dataset length
_EVAL_CHUNK = 4096


@functools.lru_cache(maxsize=8)
def _device_eval_jit(k: int, with_auc: bool):
    """Module-cached fused evaluator (a per-call jax.jit would recompile
    every transform — FindBestModel evaluates N candidates on one frame).

    Takes ``(acc, yy, pp, ss, ww)`` where ``acc`` is the DONATED flat
    confusion accumulator and the rest are ``(chunks, _EVAL_CHUNK)``
    row-padded columns (``ww`` 1 for real rows, 0 for padding)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fused(acc, yy, pp, ss, ww):
        # confusion: int32 scatter-add into k*k cells, accumulated across
        # chunks in a scan carry (exact counts; a one-hot matmul would be
        # O(n*k) HBM and float32-inexact past 2^24 per cell). Pad rows get
        # a deliberately out-of-range flat index — XLA scatter DROPS
        # out-of-bounds updates, so padding never lands a count.
        def body(cm, chunk):
            y, p, w = chunk
            flat = jnp.where(w > 0, y * k + p, k * k)
            return cm.at[flat].add(1), None
        # the carry stays flat all the way out: same shape/dtype as the
        # donated input, so XLA aliases the accumulator in place (the host
        # wrapper reshapes to k x k after the fetch)
        cm, _ = jax.lax.scan(body, acc, (yy, pp, ww))
        if not with_auc:
            return cm

        # AUC + areaUnderPR, numerically identical to the numpy
        # staircase+trapezoid path: sort by descending score, mark
        # distinct-threshold group ends, and accumulate each kept point's
        # trapezoid against the PREVIOUS kept point found with an
        # exclusive cummax over masked indices — no dynamic shapes, no
        # host round trip per threshold. Pad rows sort last (score -inf)
        # with weight 0: the cumulative counts never see them and their
        # lone group end contributes a zero-width trapezoid.
        s = jnp.where(ww.reshape(-1) > 0, ss.reshape(-1), -jnp.inf)
        w = ww.reshape(-1)
        n = s.shape[0]
        order = jnp.argsort(-s, stable=True)
        ws = w[order].astype(jnp.int32)
        ys = yy.reshape(-1)[order].astype(jnp.int32) * ws
        sss = s[order]
        # integer cumulative counts: exact up to 2^31 rows (float32
        # cumsums stop counting past 2^24 — exactly the large-n regime
        # this path is gated to)
        tps = jnp.cumsum(ys)
        fps = jnp.cumsum(ws - ys)
        P = jnp.maximum(tps[-1], 1).astype(jnp.float32)
        N = jnp.maximum(fps[-1], 1).astype(jnp.float32)
        mask = jnp.concatenate([sss[:-1] != sss[1:],
                                jnp.ones((1,), bool)])
        idx = jnp.arange(n)
        prev = jnp.concatenate([
            jnp.full((1,), -1),
            jax.lax.cummax(jnp.where(mask, idx, -1))[:-1]])
        has_prev = prev >= 0
        prev_c = jnp.maximum(prev, 0)

        def area(xcoord, ycoord, y_anchor):
            px = jnp.where(has_prev, xcoord[prev_c], 0.0)
            py = jnp.where(has_prev, ycoord[prev_c], y_anchor)
            return jnp.where(mask,
                             (xcoord - px) * (ycoord + py) * 0.5,
                             0.0).sum()

        tpsf, fpsf = tps.astype(jnp.float32), fps.astype(jnp.float32)
        fpr, tpr = fpsf / N, tpsf / P
        recall = tpsf / P
        prec = tpsf / jnp.maximum(tpsf + fpsf, 1.0)
        return cm, area(fpr, tpr, 0.0), area(recall, prec, 1.0)
    return fused


def _device_eval(y, pred, k: int, scores=None
                 ) -> Tuple[np.ndarray, Optional[Tuple[float, float]]]:
    """Run the fused device evaluator: confusion matrix always, plus
    (AUC, areaUnderPR) when binary ``scores`` are given. Exactly one
    counted host sync (``evaluate.finalize``) fetches every result
    together at the end; the confusion accumulator is donated to the
    program, so evaluation allocates no second copy of it."""
    import jax.numpy as jnp
    from mmlspark_tpu.observability import syncs
    n = len(y)
    chunks = max(1, -(-n // _EVAL_CHUNK))
    total = chunks * _EVAL_CHUNK
    shape = (chunks, _EVAL_CHUNK)
    yy = np.zeros((total,), np.int32)
    yy[:n] = np.asarray(y, np.int64)
    pp = np.zeros((total,), np.int32)
    pp[:n] = np.asarray(pred, np.int64)
    ww = np.zeros((total,), np.int32)
    ww[:n] = 1
    ss = np.zeros((total,), np.float32)
    with_auc = scores is not None
    if with_auc:
        ss[:n] = np.asarray(scores, np.float32)
    fused = _device_eval_jit(int(k), with_auc)
    acc = jnp.zeros((int(k) * int(k),), jnp.int32)
    out = fused(acc, jnp.asarray(yy.reshape(shape)),
                jnp.asarray(pp.reshape(shape)),
                jnp.asarray(ss.reshape(shape)),
                jnp.asarray(ww.reshape(shape)))
    # THE one host sync of the evaluate call: cm (+ both areas) together
    out = syncs.device_get(out, "evaluate.finalize")
    if with_auc:
        cm, a, pr = out
        return (np.asarray(cm).astype(np.int64).reshape(k, k),
                (float(a), float(pr)))
    return np.asarray(out).astype(np.int64).reshape(k, k), None


def binary_accuracy_precision_recall(cm: np.ndarray) -> Tuple[float, float, float]:
    """Reference getBinaryAccuracyPrecisionRecall (:449-459); positive class=1."""
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    total = cm.sum()
    acc = (tp + tn) / total if total else 0.0
    prec = tp / (tp + fp) if (tp + fp) else 0.0
    rec = tp / (tp + fn) if (tp + fn) else 0.0
    return float(acc), float(prec), float(rec)


def multiclass_metrics(cm: np.ndarray) -> Dict[str, float]:
    """Micro/macro averaged metrics per Sokolova–Lapalme (reference :375-429)."""
    k = cm.shape[0]
    total = cm.sum()
    tp = np.diag(cm).astype(np.float64)
    fp = cm.sum(axis=0) - tp
    fn = cm.sum(axis=1) - tp
    tn = total - tp - fp - fn
    with np.errstate(divide="ignore", invalid="ignore"):
        per_prec = np.where(tp + fp > 0, tp / (tp + fp), 0.0)
        per_rec = np.where(tp + fn > 0, tp / (tp + fn), 0.0)
    micro = float(tp.sum() / total) if total else 0.0
    # support-weighted averages — Spark MulticlassMetrics.weightedFMeasure,
    # the second benchmark-pinned column for multiclass datasets
    support = cm.sum(axis=1).astype(np.float64)
    wts = support / total if total else support
    with np.errstate(divide="ignore", invalid="ignore"):
        per_f1 = np.where(per_prec + per_rec > 0,
                          2 * per_prec * per_rec / (per_prec + per_rec), 0.0)
    return {
        "average_accuracy": float(((tp + tn) / total).mean()) if total else 0.0,
        "macro_averaged_precision": float(per_prec.mean()),
        "macro_averaged_recall": float(per_rec.mean()),
        "micro_averaged_precision": micro,
        "micro_averaged_recall": micro,
        "weighted_precision": float((per_prec * wts).sum()),
        "weighted_recall": float((per_rec * wts).sum()),
        "weighted_f1": float((per_f1 * wts).sum()),
        ACCURACY: micro,
    }


@register_stage
class ComputeModelStatistics(Transformer):
    evaluationMetric = StringParam(
        "evaluationMetric", "metric to evaluate models with", ALL_METRICS)
    labelCol = StringParam("labelCol", "label column override", "")
    scoresCol = StringParam("scoresCol", "scores column override", "")
    scoredLabelsCol = StringParam("scoredLabelsCol",
                                  "scored labels column override", "")

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self.roc_curve: Optional[np.ndarray] = None
        self.confusion_matrix: Optional[np.ndarray] = None

    def _post_load(self):
        self.roc_curve = None
        self.confusion_matrix = None

    def _discover(self, frame: Frame) -> Tuple[str, Optional[str], Optional[str], str]:
        """(label, scored_labels, scores/probabilities, kind) from metadata
        (reference getSchemaInfo :205-218)."""
        schema = frame.schema
        label = self.labelCol or find_score_column(schema, ScoreKind.TRUE_LABELS) \
            or ("label" if "label" in schema else None)
        if label is None:
            raise ValueError("cannot discover label column: no TRUE_LABELS "
                             "metadata and no labelCol override")
        kind = find_score_value_kind(schema) or ScoreKind.CLASSIFICATION
        scored_labels = self.scoredLabelsCol or find_score_column(
            schema, ScoreKind.SCORED_LABELS)
        scores = self.scoresCol or find_score_column(
            schema, ScoreKind.SCORED_PROBABILITIES) or find_score_column(
            schema, ScoreKind.SCORES)
        return label, scored_labels, scores, kind

    def transform(self, frame: Frame) -> Frame:
        self.roc_curve = None          # reset per-call so reuse never reads
        self.confusion_matrix = None   # a previous dataset's artifacts
        label, scored_labels, scores, kind = self._discover(frame)
        if kind == ScoreKind.REGRESSION:
            return self._regression(frame, label, scores)
        return self._classification(frame, label, scored_labels, scores)

    # evaluators are pass-through in schema terms; they RETURN a new frame
    def _regression(self, frame: Frame, label: str, scores: Optional[str]) -> Frame:
        if scores is None:
            raise ValueError("no scores column found for regression metrics")
        y = np.asarray(frame.column(label), dtype=np.float64)
        pred = np.asarray(frame.column(scores), dtype=np.float64)
        err = pred - y
        mse = float((err ** 2).mean()) if len(y) else 0.0
        ss_tot = float(((y - y.mean()) ** 2).sum()) if len(y) else 0.0
        metrics = {
            MSE: mse,
            RMSE: float(np.sqrt(mse)),
            R2: 1.0 - float((err ** 2).sum()) / ss_tot if ss_tot else 0.0,
            MAE: float(np.abs(err).mean()) if len(y) else 0.0,
        }
        return self._metrics_frame(metrics, REGRESSION_METRICS)

    def _classification(self, frame: Frame, label: str,
                        scored_labels: Optional[str],
                        scores: Optional[str]) -> Frame:
        if scored_labels is None:
            raise ValueError("no scored-labels column found for classification")
        y = self._label_indices(frame, label, scored_labels)
        pred = np.asarray(frame.column(scored_labels),
                          dtype=np.float64).astype(np.int64)
        k = int(max(y.max(initial=0), pred.max(initial=0))) + 1
        from mmlspark_tpu.utils import config as mmlconfig
        on_device = len(y) >= int(mmlconfig.get("evaluate.device_rows"))

        pos = None
        if k == 2 and scores is not None:
            sc = np.asarray(frame.column(scores))
            pos = sc[:, 1] if sc.ndim == 2 and sc.shape[1] >= 2 else sc.ravel()

        metrics: Dict[str, float] = {}
        if on_device:
            if pos is not None:
                # the full ROC staircase (n points) is not fetched to the
                # driver above the threshold; metric scalars come from the
                # fused jitted program — say so, because callers that
                # expect the roc_curve artifact get None here
                from mmlspark_tpu.utils.logging import get_logger
                get_logger("evaluate").info(
                    "device-path evaluation (%d rows >= "
                    "evaluate.device_rows): roc_curve artifact not "
                    "materialized; lower the threshold to retain it",
                    len(y))
            cm, auc_pair = _device_eval(y, pred, k, pos)
            self.confusion_matrix = cm
            metrics.update(self._metrics_from_cm(cm))
            if auc_pair is not None:
                metrics[AUC], metrics[AUC_PR] = auc_pair
        else:
            cm = confusion_matrix(y, pred, k)
            self.confusion_matrix = cm
            metrics.update(self._metrics_from_cm(cm))
            if pos is not None:
                curve = roc_curve(y, pos.astype(np.float64))
                self.roc_curve = curve
                metrics[AUC] = auc_from_roc(curve)
                metrics[AUC_PR] = auc_from_pr(
                    pr_curve(y, pos.astype(np.float64)))
        return self._metrics_frame(metrics, CLASSIFICATION_METRICS)

    @staticmethod
    def _metrics_from_cm(cm: np.ndarray) -> Dict[str, float]:
        """Confusion-derived metrics, shared by the fused-device and numpy
        paths (both hand over the same exact integer counts)."""
        if cm.shape[0] == 2:
            acc, prec, rec = binary_accuracy_precision_recall(cm)
            return {ACCURACY: acc, PRECISION: prec, RECALL: rec}
        mc = multiclass_metrics(cm)
        mc[PRECISION] = mc["micro_averaged_precision"]
        mc[RECALL] = mc["micro_averaged_recall"]
        return mc

    def _label_indices(self, frame: Frame, label: str,
                       scored_labels: str) -> np.ndarray:
        """Raw labels -> class indices, via the level map the trained model
        stamped on the label/scored-labels columns (TrainedClassifierModel).

        The map applies to NUMERIC labels too: levels [3, 5, 7] index to
        0..2, and scored_labels are indices — comparing raw values against
        indices would produce garbage metrics."""
        arr = frame.column(label)
        cmap = frame.schema[label].categorical \
            or frame.schema[scored_labels].categorical
        if cmap is None:
            if arr.dtype == np.object_:
                raise ValueError(
                    f"label column {label!r} holds strings but no categorical "
                    "level metadata is attached to map them to indices")
            return np.asarray(arr, dtype=np.float64).astype(np.int64)
        return map_labels_to_indices(arr, cmap)

    def _metrics_frame(self, metrics: Dict[str, float], order: List[str]) -> Frame:
        # Log through the MetricData contract, like the reference's
        # accuracy/ROC table logging (ComputeModelStatistics.scala:486-521).
        from mmlspark_tpu.core import metrics as metric_data
        for name, value in metrics.items():
            metric_data.create(name, value, model_uid=self.uid).log()
        if self.confusion_matrix is not None:
            k = self.confusion_matrix.shape[0]
            metric_data.create_table(
                "confusion_matrix", [str(i) for i in range(k)],
                self.confusion_matrix, model_uid=self.uid).log()
        if self.roc_curve is not None:
            metric_data.create_table(
                "roc_curve", ["fpr", "tpr"],
                self.roc_curve, model_uid=self.uid).log()
        want = self.evaluationMetric
        if want != ALL_METRICS:
            if want not in metrics:
                raise ValueError(f"metric {want!r} unavailable; have "
                                 f"{sorted(metrics)}")
            return Frame.from_dict({want: [metrics[want]]})
        ordered = {m: [metrics[m]] for m in order if m in metrics}
        for m, v in metrics.items():
            if m not in ordered:
                ordered[m] = [v]
        return Frame.from_dict(ordered)
