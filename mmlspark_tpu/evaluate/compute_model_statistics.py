"""ComputeModelStatistics: evaluator-as-transformer with metadata discovery.

Re-expression of
``compute-model-statistics/src/main/scala/ComputeModelStatistics.scala:86-559``:
discovers which columns are labels/scores/probabilities from column metadata
stamped by TrainedClassifierModel (``getSchemaInfo`` ``:205-218``), then:

- classification: confusion matrix, accuracy/precision/recall (binary
  ``:449-459``; multiclass micro/macro per Sokolova–Lapalme ``:375-429``),
  AUC + ROC curve retained as the ``roc_curve`` attribute (``:431-447``);
- regression: mse/rmse/r2/mae (``:181-199``).

Metric names match the reference's Spark-metric spellings
(``ComputeModelStatistics.scala:26-59``). The observable API is the same:
metrics are *returned as a Frame*.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import Params, StringParam
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import (
    ScoreKind, find_score_column, find_score_value_kind,
)
from mmlspark_tpu.core.serialization import register_stage

# Spark-metric spellings (reference :26-37)
MSE, RMSE, R2, MAE = "mse", "rmse", "r2", "mae"
AUC, ACCURACY, PRECISION, RECALL = "AUC", "accuracy", "precision", "recall"
AUC_PR = "AUC_PR"
ALL_METRICS = "all"
CLASSIFICATION_METRICS = [ACCURACY, PRECISION, RECALL, AUC, AUC_PR,
                          "weighted_precision", "weighted_recall",
                          "weighted_f1"]
REGRESSION_METRICS = [MSE, RMSE, R2, MAE]


def map_labels_to_indices(arr: np.ndarray, cmap) -> np.ndarray:
    """Map raw label values (string OR numeric) to level indices; values
    outside the map get index ``num_levels`` (the unseen slot)."""
    return np.asarray(
        [cmap.get_index(v.item() if isinstance(v, np.generic) else v,
                        default=cmap.num_levels) for v in arr],
        dtype=np.int64)


def roc_curve(labels: np.ndarray, scores: np.ndarray) -> np.ndarray:
    """Binary ROC curve points (fpr, tpr) sorted by descending threshold."""
    order = np.argsort(-scores, kind="stable")
    labels = labels[order]
    tps = np.cumsum(labels)
    fps = np.cumsum(1 - labels)
    P = max(tps[-1] if len(tps) else 0, 1)
    N = max(fps[-1] if len(fps) else 0, 1)
    # keep last point per distinct score to get the staircase vertices
    distinct = np.r_[np.nonzero(np.diff(scores[order]))[0], len(labels) - 1] \
        if len(labels) else np.array([], dtype=int)
    fpr = np.r_[0.0, fps[distinct] / N]
    tpr = np.r_[0.0, tps[distinct] / P]
    return np.stack([fpr, tpr], axis=1)


def auc_from_roc(curve: np.ndarray) -> float:
    return float(np.trapezoid(curve[:, 1], curve[:, 0]))


def pr_curve(labels: np.ndarray, scores: np.ndarray) -> np.ndarray:
    """Binary precision-recall curve points (recall, precision) by
    descending threshold, with the (0, 1) anchor Spark's
    BinaryClassificationMetrics prepends — its areaUnderPR is the
    benchmark-pinned second metric column (benchmarkMetrics.csv)."""
    order = np.argsort(-scores, kind="stable")
    labels = labels[order]
    tps = np.cumsum(labels)
    fps = np.cumsum(1 - labels)
    P = max(tps[-1] if len(tps) else 0, 1)
    distinct = np.r_[np.nonzero(np.diff(scores[order]))[0], len(labels) - 1] \
        if len(labels) else np.array([], dtype=int)
    recall = np.r_[0.0, tps[distinct] / P]
    with np.errstate(divide="ignore", invalid="ignore"):
        prec = np.r_[1.0, tps[distinct] /
                     np.maximum(tps[distinct] + fps[distinct], 1)]
    return np.stack([recall, prec], axis=1)


# same trapezoid over (x, y) points; distinct name kept for call-site clarity
auc_from_pr = auc_from_roc


def confusion_matrix(y: np.ndarray, pred: np.ndarray, k: int) -> np.ndarray:
    cm = np.zeros((k, k), dtype=np.int64)
    np.add.at(cm, (y.astype(int), pred.astype(int)), 1)
    return cm


# -- device-path evaluators --------------------------------------------------
# Above ``evaluate.device_rows`` rows, metrics run as jitted XLA programs
# instead of driver numpy: the scored column stays columnar and the driver
# only ever sees the k x k confusion and two scalars — the
# everything-streams-to-device story applied to evaluation, where the
# reference funneled the whole scored RDD through driver-side Spark
# aggregations (``ComputeModelStatistics.scala:86-559``). Below the
# threshold the numpy path wins on latency (no transfer, no compile).

@functools.lru_cache(maxsize=1)
def _device_confusion_jit():
    """Module-cached jit (a per-call jax.jit would recompile every
    transform — FindBestModel evaluates N candidates on one frame)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=2)
    def cm(yy, pp, kk):
        # int32 scatter-add into k*k cells: O(n) memory and exact counts
        # (a one-hot matmul would be O(n*k) HBM and float32-inexact past
        # 2^24 per cell)
        flat = yy.astype(jnp.int32) * kk + pp.astype(jnp.int32)
        return jnp.zeros((kk * kk,), jnp.int32).at[flat].add(1) \
            .reshape(kk, kk)
    return cm


def _device_confusion(y, pred, k: int) -> np.ndarray:
    import jax
    import jax.numpy as jnp
    out = _device_confusion_jit()(jnp.asarray(y, np.int32),
                                  jnp.asarray(pred, np.int32), int(k))
    from mmlspark_tpu.observability import syncs
    return np.asarray(
        syncs.device_get(out, "evaluate.confusion")).astype(np.int64)


@functools.lru_cache(maxsize=1)
def _device_auc_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def both(yy, ss):
        n = yy.shape[0]
        order = jnp.argsort(-ss, stable=True)
        ys = yy[order].astype(jnp.int32)
        sss = ss[order]
        # integer cumulative counts: exact up to 2^31 rows (float32
        # cumsums stop counting past 2^24 — exactly the large-n regime
        # this path is gated to)
        tps = jnp.cumsum(ys)
        fps = jnp.cumsum(1 - ys)
        P = jnp.maximum(tps[-1], 1).astype(jnp.float32)
        N = jnp.maximum(fps[-1], 1).astype(jnp.float32)
        mask = jnp.concatenate([sss[:-1] != sss[1:],
                                jnp.ones((1,), bool)])
        idx = jnp.arange(n)
        prev = jnp.concatenate([
            jnp.full((1,), -1),
            jax.lax.cummax(jnp.where(mask, idx, -1))[:-1]])
        has_prev = prev >= 0
        prev_c = jnp.maximum(prev, 0)

        def area(xcoord, ycoord, y_anchor):
            px = jnp.where(has_prev, xcoord[prev_c], 0.0)
            py = jnp.where(has_prev, ycoord[prev_c], y_anchor)
            return jnp.where(mask,
                             (xcoord - px) * (ycoord + py) * 0.5,
                             0.0).sum()

        tpsf, fpsf = tps.astype(jnp.float32), fps.astype(jnp.float32)
        fpr, tpr = fpsf / N, tpsf / P
        recall = tpsf / P
        prec = tpsf / jnp.maximum(tpsf + fpsf, 1.0)
        return area(fpr, tpr, 0.0), area(recall, prec, 1.0)
    return both


def _device_auc_aucpr(y, scores) -> Tuple[float, float]:
    """ROC-AUC and areaUnderPR as ONE fixed-shape jitted program,
    numerically identical to the numpy staircase+trapezoid path: sort by
    descending score, mark distinct-threshold group ends, and accumulate
    each kept point's trapezoid against the PREVIOUS kept point found
    with an exclusive cummax over masked indices — no dynamic shapes, no
    host round trip per threshold."""
    import jax
    import jax.numpy as jnp
    a, pr = _device_auc_jit()(jnp.asarray(np.asarray(y, np.int32)),
                              jnp.asarray(np.asarray(scores, np.float32)))
    from mmlspark_tpu.observability import syncs
    # one counted sync: (a, pr) fetched together, not two round trips
    a, pr = syncs.device_get((a, pr), "evaluate.auc")
    return float(a), float(pr)


def binary_accuracy_precision_recall(cm: np.ndarray) -> Tuple[float, float, float]:
    """Reference getBinaryAccuracyPrecisionRecall (:449-459); positive class=1."""
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    total = cm.sum()
    acc = (tp + tn) / total if total else 0.0
    prec = tp / (tp + fp) if (tp + fp) else 0.0
    rec = tp / (tp + fn) if (tp + fn) else 0.0
    return float(acc), float(prec), float(rec)


def multiclass_metrics(cm: np.ndarray) -> Dict[str, float]:
    """Micro/macro averaged metrics per Sokolova–Lapalme (reference :375-429)."""
    k = cm.shape[0]
    total = cm.sum()
    tp = np.diag(cm).astype(np.float64)
    fp = cm.sum(axis=0) - tp
    fn = cm.sum(axis=1) - tp
    tn = total - tp - fp - fn
    with np.errstate(divide="ignore", invalid="ignore"):
        per_prec = np.where(tp + fp > 0, tp / (tp + fp), 0.0)
        per_rec = np.where(tp + fn > 0, tp / (tp + fn), 0.0)
    micro = float(tp.sum() / total) if total else 0.0
    # support-weighted averages — Spark MulticlassMetrics.weightedFMeasure,
    # the second benchmark-pinned column for multiclass datasets
    support = cm.sum(axis=1).astype(np.float64)
    wts = support / total if total else support
    with np.errstate(divide="ignore", invalid="ignore"):
        per_f1 = np.where(per_prec + per_rec > 0,
                          2 * per_prec * per_rec / (per_prec + per_rec), 0.0)
    return {
        "average_accuracy": float(((tp + tn) / total).mean()) if total else 0.0,
        "macro_averaged_precision": float(per_prec.mean()),
        "macro_averaged_recall": float(per_rec.mean()),
        "micro_averaged_precision": micro,
        "micro_averaged_recall": micro,
        "weighted_precision": float((per_prec * wts).sum()),
        "weighted_recall": float((per_rec * wts).sum()),
        "weighted_f1": float((per_f1 * wts).sum()),
        ACCURACY: micro,
    }


@register_stage
class ComputeModelStatistics(Transformer):
    evaluationMetric = StringParam(
        "evaluationMetric", "metric to evaluate models with", ALL_METRICS)
    labelCol = StringParam("labelCol", "label column override", "")
    scoresCol = StringParam("scoresCol", "scores column override", "")
    scoredLabelsCol = StringParam("scoredLabelsCol",
                                  "scored labels column override", "")

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self.roc_curve: Optional[np.ndarray] = None
        self.confusion_matrix: Optional[np.ndarray] = None

    def _post_load(self):
        self.roc_curve = None
        self.confusion_matrix = None

    def _discover(self, frame: Frame) -> Tuple[str, Optional[str], Optional[str], str]:
        """(label, scored_labels, scores/probabilities, kind) from metadata
        (reference getSchemaInfo :205-218)."""
        schema = frame.schema
        label = self.labelCol or find_score_column(schema, ScoreKind.TRUE_LABELS) \
            or ("label" if "label" in schema else None)
        if label is None:
            raise ValueError("cannot discover label column: no TRUE_LABELS "
                             "metadata and no labelCol override")
        kind = find_score_value_kind(schema) or ScoreKind.CLASSIFICATION
        scored_labels = self.scoredLabelsCol or find_score_column(
            schema, ScoreKind.SCORED_LABELS)
        scores = self.scoresCol or find_score_column(
            schema, ScoreKind.SCORED_PROBABILITIES) or find_score_column(
            schema, ScoreKind.SCORES)
        return label, scored_labels, scores, kind

    def transform(self, frame: Frame) -> Frame:
        self.roc_curve = None          # reset per-call so reuse never reads
        self.confusion_matrix = None   # a previous dataset's artifacts
        label, scored_labels, scores, kind = self._discover(frame)
        if kind == ScoreKind.REGRESSION:
            return self._regression(frame, label, scores)
        return self._classification(frame, label, scored_labels, scores)

    # evaluators are pass-through in schema terms; they RETURN a new frame
    def _regression(self, frame: Frame, label: str, scores: Optional[str]) -> Frame:
        if scores is None:
            raise ValueError("no scores column found for regression metrics")
        y = np.asarray(frame.column(label), dtype=np.float64)
        pred = np.asarray(frame.column(scores), dtype=np.float64)
        err = pred - y
        mse = float((err ** 2).mean()) if len(y) else 0.0
        ss_tot = float(((y - y.mean()) ** 2).sum()) if len(y) else 0.0
        metrics = {
            MSE: mse,
            RMSE: float(np.sqrt(mse)),
            R2: 1.0 - float((err ** 2).sum()) / ss_tot if ss_tot else 0.0,
            MAE: float(np.abs(err).mean()) if len(y) else 0.0,
        }
        return self._metrics_frame(metrics, REGRESSION_METRICS)

    def _classification(self, frame: Frame, label: str,
                        scored_labels: Optional[str],
                        scores: Optional[str]) -> Frame:
        if scored_labels is None:
            raise ValueError("no scored-labels column found for classification")
        y = self._label_indices(frame, label, scored_labels)
        pred = np.asarray(frame.column(scored_labels),
                          dtype=np.float64).astype(np.int64)
        k = int(max(y.max(initial=0), pred.max(initial=0))) + 1
        from mmlspark_tpu.utils import config as mmlconfig
        on_device = len(y) >= int(mmlconfig.get("evaluate.device_rows"))
        cm = (_device_confusion(y, pred, k) if on_device
              else confusion_matrix(y, pred, k))
        self.confusion_matrix = cm

        metrics: Dict[str, float] = {}
        if k == 2:
            acc, prec, rec = binary_accuracy_precision_recall(cm)
            metrics.update({ACCURACY: acc, PRECISION: prec, RECALL: rec})
            if scores is not None:
                sc = np.asarray(frame.column(scores))
                pos = sc[:, 1] if sc.ndim == 2 and sc.shape[1] >= 2 else sc.ravel()
                if on_device:
                    # the full ROC staircase (n points) is not fetched to
                    # the driver above the threshold; metric scalars come
                    # from the jitted program — say so, because callers
                    # that expect the roc_curve artifact get None here
                    from mmlspark_tpu.utils.logging import get_logger
                    get_logger("evaluate").info(
                        "device-path evaluation (%d rows >= "
                        "evaluate.device_rows): roc_curve artifact not "
                        "materialized; lower the threshold to retain it",
                        len(y))
                    metrics[AUC], metrics[AUC_PR] = _device_auc_aucpr(
                        y, pos)
                else:
                    curve = roc_curve(y, pos.astype(np.float64))
                    self.roc_curve = curve
                    metrics[AUC] = auc_from_roc(curve)
                    metrics[AUC_PR] = auc_from_pr(
                        pr_curve(y, pos.astype(np.float64)))
        else:
            mc = multiclass_metrics(cm)
            metrics.update(mc)
            metrics[PRECISION] = mc["micro_averaged_precision"]
            metrics[RECALL] = mc["micro_averaged_recall"]
        return self._metrics_frame(metrics, CLASSIFICATION_METRICS)

    def _label_indices(self, frame: Frame, label: str,
                       scored_labels: str) -> np.ndarray:
        """Raw labels -> class indices, via the level map the trained model
        stamped on the label/scored-labels columns (TrainedClassifierModel).

        The map applies to NUMERIC labels too: levels [3, 5, 7] index to
        0..2, and scored_labels are indices — comparing raw values against
        indices would produce garbage metrics."""
        arr = frame.column(label)
        cmap = frame.schema[label].categorical \
            or frame.schema[scored_labels].categorical
        if cmap is None:
            if arr.dtype == np.object_:
                raise ValueError(
                    f"label column {label!r} holds strings but no categorical "
                    "level metadata is attached to map them to indices")
            return np.asarray(arr, dtype=np.float64).astype(np.int64)
        return map_labels_to_indices(arr, cmap)

    def _metrics_frame(self, metrics: Dict[str, float], order: List[str]) -> Frame:
        # Log through the MetricData contract, like the reference's
        # accuracy/ROC table logging (ComputeModelStatistics.scala:486-521).
        from mmlspark_tpu.core import metrics as metric_data
        for name, value in metrics.items():
            metric_data.create(name, value, model_uid=self.uid).log()
        if self.confusion_matrix is not None:
            k = self.confusion_matrix.shape[0]
            metric_data.create_table(
                "confusion_matrix", [str(i) for i in range(k)],
                self.confusion_matrix, model_uid=self.uid).log()
        if self.roc_curve is not None:
            metric_data.create_table(
                "roc_curve", ["fpr", "tpr"],
                self.roc_curve, model_uid=self.uid).log()
        want = self.evaluationMetric
        if want != ALL_METRICS:
            if want not in metrics:
                raise ValueError(f"metric {want!r} unavailable; have "
                                 f"{sorted(metrics)}")
            return Frame.from_dict({want: [metrics[want]]})
        ordered = {m: [metrics[m]] for m in order if m in metrics}
        for m, v in metrics.items():
            if m not in ordered:
                ordered[m] = [v]
        return Frame.from_dict(ordered)
