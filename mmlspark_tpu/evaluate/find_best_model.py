"""FindBestModel: model selection over a list of fitted transformers.

Re-expression of ``find-best-model/src/main/scala/FindBestModel.scala:68-162``:
scores the dataset with each candidate, evaluates the chosen metric,
dispatches higher-vs-lower-is-better by metric, and retains the best model,
its scored dataset, its ROC curve, and a table of all models' metrics.

Candidates are evaluated embarrassingly-parallel in the reference sense (a
sequential loop there, ``:135-143``); each candidate's device scoring is
already batched XLA here.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import AnyParam, StringParam
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.evaluate.compute_model_statistics import (
    ACCURACY, AUC, ALL_METRICS, CLASSIFICATION_METRICS, MAE, MSE, PRECISION,
    R2, RECALL, RMSE, ComputeModelStatistics,
)

LOWER_IS_BETTER = {MSE, RMSE, MAE}
# derived, not hand-listed: a metric added to the evaluator must be
# rankable here without anyone remembering a second list
HIGHER_IS_BETTER = set(CLASSIFICATION_METRICS) | {R2}


@register_stage
class FindBestModel(Estimator):
    models = AnyParam("models", "candidate fitted transformers to compare")
    evaluationMetric = StringParam(
        "evaluationMetric", "metric used to rank candidates", ACCURACY)

    def fit(self, frame: Frame) -> "BestModel":
        candidates: List[Transformer] = self.get("models")
        if not candidates:
            raise ValueError("FindBestModel requires a non-empty `models` list")
        metric = self.evaluationMetric
        if metric == ALL_METRICS:
            raise ValueError("evaluationMetric must be a single metric")
        lower = metric in LOWER_IS_BETTER
        if not lower and metric not in HIGHER_IS_BETTER:
            raise ValueError(f"unknown metric {metric!r}")

        # Featurize ONCE per distinct featurization: candidates whose
        # featurizeModel fingerprints identically (typical when several
        # learners were trained by TrainClassifier on the same data) share
        # a single featurize pass, so N-candidate selection costs ~one
        # pass over the data plus N cheap scoring heads — the reference
        # re-ran the whole pipeline per candidate
        # (``FindBestModel.scala:135-143``).
        from mmlspark_tpu.core.serialization import stage_fingerprint
        featurized_cache: dict = {}

        def score(cand):
            featurizer = (cand.get("featurizeModel", None)
                          if hasattr(cand, "transform_featurized") else None)
            if featurizer is None:
                return cand.transform(frame)
            fp = stage_fingerprint(featurizer)
            if fp not in featurized_cache:
                featurized_cache[fp] = featurizer.transform(frame)
            return cand.transform_featurized(featurized_cache[fp])

        rows = []
        best = None  # (value, model, scored, roc)
        for cand in candidates:
            scored = score(cand)
            ev = ComputeModelStatistics()
            all_metrics = {k: v[0] for k, v in ev.transform(scored).collect().items()}
            if metric not in all_metrics:
                raise ValueError(
                    f"metric {metric!r} unavailable for model {cand.uid} "
                    f"(have {sorted(all_metrics)})")
            value = float(all_metrics[metric])
            rows.append({"model_uid": cand.uid,
                         **{k: float(v) for k, v in all_metrics.items()}})
            better = (best is None or
                      (value < best[0] if lower else value > best[0]))
            if better:
                best = (value, cand, scored, ev.roc_curve)

        model = BestModel()
        model.set_params(bestModel=best[1])
        model._state = {"best_metric": best[0], "metric_name": metric}
        model.scored_dataset = best[2]
        model.roc_curve = best[3]
        model.all_model_metrics = Frame.from_rows(rows)
        return model


@register_stage
class BestModel(Model):
    bestModel = AnyParam("bestModel", "the winning transformer")

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid, **kwargs)
        self._post_load()

    def _post_load(self):
        self.scored_dataset: Optional[Frame] = None
        self.roc_curve = None
        self.all_model_metrics: Optional[Frame] = None

    def transform(self, frame: Frame) -> Frame:
        return self.get("bestModel").transform(frame)
