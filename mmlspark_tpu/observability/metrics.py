"""Metrics registry: counters, gauges, fixed-bucket histograms.

The process-wide accumulation half of the telemetry layer: cold paths
(downloads, retries, checkpoint saves, quarantines) count unconditionally —
an int add under a lock — while hot per-step paths gate on
:func:`metrics_enabled` (``observability.metrics``) so a disabled run pays
nothing per step. Two export formats:

- :meth:`MetricsRegistry.prometheus_text` — the Prometheus text exposition
  (``# TYPE`` headers, ``_bucket{le=...}`` / ``_sum`` / ``_count`` for
  histograms), names sanitized to the Prometheus charset;
- :meth:`MetricsRegistry.to_dict` / :meth:`to_json` — a JSON dump for the
  event log or ad-hoc inspection.

Histogram buckets are FIXED at creation (cumulative ``le`` semantics, a
``+Inf`` slot implied) — no dynamic resizing, so ``observe`` is O(buckets)
with no allocation.
"""
from __future__ import annotations

import bisect
import json
import re
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

from mmlspark_tpu.utils import config

# Prometheus histogram defaults, widened to cover sub-ms XLA steps through
# multi-second compile-bound ones.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def metrics_enabled() -> bool:
    """Gate for HOT-path collection (per-step histograms/gauges). Cold-path
    counters do not consult this — they are a lock + int add."""
    return bool(config.get("observability.metrics"))


def sanitize(name: str) -> str:
    """Dotted registry name -> Prometheus-charset metric name."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def nearest_rank(sorted_vals: Sequence[float], p: float) -> float:
    """Nearest-rank percentile over RAW sorted samples — the one
    implementation behind the report's and bench's client-side p50/p99
    (histogram-backed percentiles go through
    :func:`percentile_from_buckets` instead)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[i])


def percentile_from_buckets(cumulative: Dict[Any, Any], p: float) -> float:
    """Bucket-interpolation percentile from a cumulative ``{le: count}``
    mapping (Prometheus ``le`` semantics, ``+Inf`` slot included) — the
    one estimator behind :meth:`Histogram.percentile`, the serve summary,
    the SLO engine and ``top``. ``p`` is in [0, 100].

    Linear interpolation within the bucket containing the target rank;
    a rank that lands in the ``+Inf`` overflow bucket clamps to the
    highest finite bound (there is no upper edge to interpolate to) —
    callers that must distinguish a clamp from a real value use
    :func:`percentile_from_buckets_ex`, which reports it explicitly.
    Empty histograms return 0.0.
    """
    return percentile_from_buckets_ex(cumulative, p)[0]


def percentile_from_buckets_ex(cumulative: Dict[Any, Any],
                               p: float) -> Tuple[float, bool]:
    """:func:`percentile_from_buckets` plus an explicit CLIPPED flag.

    Returns ``(value, clipped)``: ``clipped`` is True when the target
    rank lands in the ``+Inf`` overflow bucket, i.e. the returned value
    is the highest finite bound acting as a floor, NOT an estimate — the
    true percentile is somewhere above it and unbounded. Benchgate uses
    this to refuse clipped-vs-clipped latency comparisons as parity
    (a deadline-saturated p99 says "at least this bad", never "equal").
    """
    finite = []
    inf_count: Optional[float] = None
    for le, c in cumulative.items():
        if isinstance(le, str) and le.strip().lstrip("+") in ("Inf", "inf"):
            inf_count = float(c)
        else:
            f = float(le)
            if f == float("inf"):
                inf_count = float(c)
            else:
                finite.append((f, float(c)))
    finite.sort()
    total = inf_count if inf_count is not None else (
        finite[-1][1] if finite else 0.0)
    if total <= 0:
        return 0.0, False
    rank = max(0.0, min(100.0, float(p))) / 100.0 * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in finite:
        if cum >= rank:
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span > 0 else 1.0
            return prev_bound + (bound - prev_bound) * frac, False
        prev_bound, prev_cum = bound, cum
    # the rank lives in the +Inf overflow: the clamp is a floor
    return (finite[-1][0] if finite else 0.0), bool(finite)


def escape_label_value(value: str) -> str:
    """Escape a label value per the Prometheus text exposition format:
    backslash, double-quote, and newline must be escaped inside the
    ``label="..."`` quotes or the line is unparsable (a trace_id or model
    name with a quote would corrupt the whole /metrics page)."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


class Counter:
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) must be >= 0")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Cumulative fixed-bucket histogram (Prometheus ``le`` semantics)."""

    __slots__ = ("name", "buckets", "_lock", "_counts", "_sum", "_count",
                 "_exemplar")

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(
                f"histogram {name}: buckets must be non-empty and ascending")
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._exemplar: Optional[Dict[str, Any]] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                # last-exemplar-wins: one correlating id (a serve
                # trace_id) per histogram is enough to jump from a bad
                # latency to the exact request timeline
                self._exemplar = {"trace_id": str(exemplar),
                                  "value": float(v)}

    @property
    def exemplar(self) -> Optional[Dict[str, Any]]:
        """``{"trace_id": ..., "value": ...}`` of the most recent observe
        that carried one (tail sampling records slow-request trace_ids
        here), or None."""
        return self._exemplar

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, p: float) -> float:
        """Bucket-interpolated percentile (``p`` in [0, 100]) — see
        :func:`percentile_from_buckets` for the estimator contract."""
        return percentile_from_buckets(self.cumulative(), p)

    def cumulative(self) -> Dict[str, int]:
        """``{le: cumulative count}`` including the ``+Inf`` bucket."""
        out: Dict[str, int] = {}
        with self._lock:
            running = 0
            for b, c in zip(self.buckets, self._counts):
                running += c
                out[repr(b)] = running
            out["+Inf"] = running + self._counts[-1]
        return out


class MetricsRegistry:
    """Typed name -> instrument map; instruments are created on first use
    and re-registration with a different type is an error (a counter named
    like an existing gauge is a bug, not a new metric)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(name, Histogram, buckets or DEFAULT_BUCKETS)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            items = sorted(self._metrics.items())
        out: Dict[str, Any] = {}
        for name, m in items:
            if isinstance(m, Counter):
                out[name] = {"type": "counter", "value": m.value}
            elif isinstance(m, Gauge):
                out[name] = {"type": "gauge", "value": m.value}
            else:
                out[name] = {"type": "histogram", "count": m.count,
                             "sum": m.sum, "buckets": m.cumulative()}
                if m.exemplar is not None:
                    out[name]["exemplar"] = dict(m.exemplar)
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def prometheus_text(self) -> str:
        """Prometheus text-format exposition of every registered metric."""
        lines = []
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            pname = sanitize(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {_fmt(m.value)}")
            else:
                lines.append(f"# TYPE {pname} histogram")
                for le, c in m.cumulative().items():
                    esc = escape_label_value(le)
                    lines.append(f'{pname}_bucket{{le="{esc}"}} {c}')
                lines.append(f"{pname}_sum {_fmt(m.sum)}")
                lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry all instrumentation reports to."""
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str,
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _REGISTRY.histogram(name, buckets)
