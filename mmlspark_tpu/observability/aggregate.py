"""Fleet-wide metrics aggregation: one labeled view over N replicas.

Telemetry so far stops at the process boundary — every replica exposes
its own ``/metrics``, event logs are per-pid sidecars, and the process
registry has no labels (in-process fleet replicas all add into the SAME
counters). This module builds the fleet view:

- :class:`FleetScraper` pulls readiness + stats from every replica —
  in-process replicas through ``Server.stats()``/``health()`` (their
  per-instance twins ARE the per-replica series; the shared process
  registry cannot be), ``HttpReplica`` targets through ``GET /metrics``
  (Prometheus text, parsed) and ``GET /readyz`` — each target behind its
  own :class:`~mmlspark_tpu.reliability.breaker.CircuitBreaker` so a
  hung replica cannot stall the scrape loop, with an injectable clock so
  tests drive breaker cooldowns deterministically;
- :class:`AggregatedRegistry` holds the merged result: every series
  carries a ``replica="r0"`` label (plus ``model``/``kind`` for the HBM
  ledger) and exports as one Prometheus exposition or a JSON dump;
- :func:`merge_event_logs` merges multi-process JSONL event logs for
  ``mmlspark-tpu report`` (per-pid sidecars; the report's span
  reconstruction already dedupes on ``(pid, span_id)``).

The scraper is the data source for the SLO engine
(:mod:`~mmlspark_tpu.observability.slo`) and the ``mmlspark-tpu top``
dashboard; :meth:`FleetScraper.slo_sample` is the bridge.
"""
from __future__ import annotations

import glob as _glob
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.observability import memory as devmem
from mmlspark_tpu.reliability.breaker import CircuitBreaker, CircuitOpen
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("observability.aggregate")

# server.stats() keys that are monotonic counts (everything else numeric
# is exported as a gauge)
_COUNTER_KEYS = frozenset((
    "admitted", "shed", "expired", "completed", "failed",
    "registry.evictions", "registry.compiles", "registry.compile_cache_hits",
))

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: _LabelKey) -> str:
    return ",".join(f'{k}="{metrics.escape_label_value(v)}"'
                    for k, v in key)


class AggregatedRegistry:
    """Labeled series store + Prometheus/JSON export.

    The process :class:`~mmlspark_tpu.observability.metrics.MetricsRegistry`
    is intentionally label-free (hot-path cost); this one exists for the
    scraped fleet view where every sample already paid its collection
    cost on the replica.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> {"type": t, "series": {label_key: sample}}
        self._metrics: Dict[str, Dict[str, Any]] = {}

    def set_value(self, name: str, labels: Dict[str, str], value: float,
                  mtype: str = "gauge") -> None:
        if mtype not in ("gauge", "counter"):
            raise ValueError(f"mtype must be gauge|counter, got {mtype!r}")
        with self._lock:
            m = self._metrics.setdefault(name, {"type": mtype, "series": {}})
            m["series"][_label_key(labels)] = float(value)

    def set_histogram(self, name: str, labels: Dict[str, str],
                      buckets: Dict[str, float], sum_: float, count: float,
                      exemplar: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            m = self._metrics.setdefault(
                name, {"type": "histogram", "series": {}})
            m["series"][_label_key(labels)] = {
                "buckets": dict(buckets), "sum": float(sum_),
                "count": float(count),
                **({"exemplar": dict(exemplar)} if exemplar else {})}

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            items = sorted(self._metrics.items())
            out: Dict[str, Any] = {}
            for name, m in items:
                series = []
                for key, sample in sorted(m["series"].items()):
                    entry: Dict[str, Any] = {"labels": dict(key)}
                    if m["type"] == "histogram":
                        entry.update(sample)
                    else:
                        entry["value"] = sample
                    series.append(entry)
                out[name] = {"type": m["type"], "series": series}
            return out

    def prometheus_text(self) -> str:
        """One exposition page for the whole fleet: every series labeled
        (``replica=``, ``model=``/``kind=`` ...), one ``# TYPE`` header
        per metric name."""
        lines: List[str] = []
        with self._lock:
            items = sorted((n, dict(m, series=dict(m["series"])))
                           for n, m in self._metrics.items())
        for name, m in items:
            pname = metrics.sanitize(name)
            lines.append(f"# TYPE {pname} {m['type']}")
            for key, sample in sorted(m["series"].items()):
                ls = _label_str(key)
                if m["type"] == "histogram":
                    for le, c in sample["buckets"].items():
                        esc = metrics.escape_label_value(le)
                        sep = "," if ls else ""
                        lines.append(
                            f'{pname}_bucket{{{ls}{sep}le="{esc}"}} '
                            f"{metrics._fmt(c)}")
                    lines.append(
                        f"{pname}_sum{{{ls}}} {metrics._fmt(sample['sum'])}")
                    lines.append(
                        f"{pname}_count{{{ls}}} "
                        f"{metrics._fmt(sample['count'])}")
                else:
                    lines.append(f"{pname}{{{ls}}} {metrics._fmt(sample)}")
        return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus_text(text: str) -> Dict[str, Any]:
    """Parse a Prometheus text exposition back into
    ``{name: {"type", "value"}}`` scalars and
    ``{name: {"type": "histogram", "buckets", "sum", "count"}}``
    histograms — the inverse of ``MetricsRegistry.prometheus_text`` (the
    subset this framework emits: no labels other than ``le``).
    Malformed lines are skipped, not fatal."""
    out: Dict[str, Any] = {}
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        try:
            lhs, value = line.rsplit(None, 1)
            v = float(value)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        name = lhs
        if "{" in lhs and lhs.endswith("}"):
            name, _, rest = lhs.partition("{")
            for part in rest[:-1].split(","):
                if "=" in part:
                    lk, _, lv = part.partition("=")
                    labels[lk.strip()] = lv.strip().strip('"')
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    types.get(name[:-len(suffix)]) == "histogram":
                base = name[:-len(suffix)]
                break
        if base != name or types.get(base) == "histogram":
            h = out.setdefault(base, {"type": "histogram", "buckets": {},
                                      "sum": 0.0, "count": 0.0})
            if name.endswith("_bucket"):
                h["buckets"][labels.get("le", "+Inf")] = v
            elif name.endswith("_sum"):
                h["sum"] = v
            elif name.endswith("_count"):
                h["count"] = v
        else:
            out[name] = {"type": types.get(name, "gauge"), "value": v}
    return out


def merge_cumulative(dicts: Iterable[Dict[str, float]]) -> Dict[str, float]:
    """Sum cumulative ``{le: count}`` histograms across replicas (bucket
    edges are shared fleet-wide — all replicas run the same config)."""
    merged: Dict[str, float] = {}
    for d in dicts:
        for le, c in d.items():
            merged[le] = merged.get(le, 0.0) + float(c)
    return merged


def merge_event_logs(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Load + merge several JSONL event logs (per-pid sidecars, one per
    process) into one ts-ordered stream. Span dedupe is NOT done here —
    the report's pid-keyed reconstruction already handles that."""
    from mmlspark_tpu.observability import report as _report
    merged: List[Dict[str, Any]] = []
    for p in paths:
        merged.extend(_report.load_events(p))
    merged.sort(key=lambda e: float(e.get("ts", 0.0)))
    return merged


def expand_event_paths(paths: Sequence[str],
                       pattern: Optional[str] = None) -> List[str]:
    """Expand explicit paths plus an optional glob into a sorted,
    de-duplicated path list (the CLI's ``report events... --glob`` form)."""
    out: List[str] = []
    for p in paths or ():
        if any(ch in str(p) for ch in "*?["):
            out.extend(sorted(_glob.glob(str(p))))
        else:
            out.append(str(p))
    if pattern:
        out.extend(sorted(_glob.glob(str(pattern))))
    seen: Dict[str, None] = {}
    for p in out:
        seen.setdefault(p, None)
    return list(seen)


class FleetScraper:
    """Poll every replica for readiness + metrics and merge the result.

    ``replicas`` may be a :class:`~mmlspark_tpu.serve.fleet.Fleet`, a
    :class:`~mmlspark_tpu.serve.router.Router`, or a plain list of
    replica objects (anything with ``name`` + ``health()``; in-process
    replicas additionally expose ``.server``, HTTP ones ``.addr``).

    Every target is scraped through its own circuit breaker
    (``scrape.<name>``): a replica that times out or refuses repeatedly
    trips open and is skipped (marked ``circuit_open`` in the snapshot)
    until the cooldown's half-open probe — the scrape loop never blocks
    the dashboard on one dead host. ``clock`` injects time for both the
    snapshot timestamps and the breaker cooldowns.
    """

    def __init__(self, replicas: Any, *, clock: Optional[Callable] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 timeout_s: float = 2.0):
        router = getattr(replicas, "router", None)   # Fleet
        if router is not None:
            self.router: Optional[Any] = router
            reps = [h.replica for h in router._handles.values()]
        elif hasattr(replicas, "_handles"):          # Router
            self.router = replicas
            reps = [h.replica for h in replicas._handles.values()]
        else:
            self.router = None
            reps = list(replicas)
        self.replicas = reps
        self.clock = clock or events.wall
        self.timeout_s = float(timeout_s)
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = breaker_reset_s
        self._breakers = {
            r.name: CircuitBreaker(f"scrape.{r.name}",
                                   failure_threshold=breaker_failures,
                                   reset_timeout_s=breaker_reset_s,
                                   clock=self.clock)
            for r in reps}
        self.registry = AggregatedRegistry()
        self._last: Optional[Dict[str, Any]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one replica -------------------------------------------------------
    def _scrape_http(self, replica: Any) -> Dict[str, Any]:
        import urllib.request
        base = replica.addr
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=self.timeout_s) as resp:
            parsed = parse_prometheus_text(
                resp.read().decode("utf-8", "replace"))
        try:
            with urllib.request.urlopen(base + "/readyz",
                                        timeout=self.timeout_s) as resp:
                ready = resp.status == 200
            live = True
        except Exception as e:
            status = getattr(e, "code", None)
            if status is None:
                raise
            ready, live = False, True  # answered, just not ready
        stats: Dict[str, float] = {}
        for key in ("admitted", "shed", "expired", "completed", "failed",
                    "queue_depth", "inflight"):
            m = parsed.get(f"serving_{key}")
            if m is not None and "value" in m:
                stats[key] = m["value"]
        latency = parsed.get("serving_total_ms")
        if latency is not None and latency.get("type") == "histogram":
            stats["p50_ms"] = round(metrics.percentile_from_buckets(
                latency["buckets"], 50), 3)
            stats["p99_ms"] = round(metrics.percentile_from_buckets(
                latency["buckets"], 99), 3)
        # prefix-digest advertisement (metrics-adjacent JSON endpoint;
        # serve/http.py). Best-effort: an older replica without the
        # route is still a healthy scrape target.
        try:
            import json as _json
            with urllib.request.urlopen(base + "/affinity",
                                        timeout=self.timeout_s) as resp:
                adv = _json.loads(resp.read().decode("utf-8", "replace"))
            for model, d in (adv.get("digests") or {}).items():
                stats[f"generate.{model}.kv.resident_chains"] = \
                    d.get("chains") or []
                stats[f"generate.{model}.kv.kv_dtype"] = \
                    str(d.get("kv_dtype") or "")
                stats[f"generate.{model}.kv.block_tokens"] = \
                    d.get("block_tokens")
        except Exception as e:
            logger.debug("affinity scrape skipped for %s: %s", base, e)
        return {"ready": ready, "live": live,
                "state": "ready" if ready else "draining",
                "stats": stats, "latency": latency, "metrics": parsed}

    def _scrape_inproc(self, replica: Any) -> Dict[str, Any]:
        server = replica.server
        health = replica.health()
        stats = server.stats()
        lat = server.latency
        latency = {"type": "histogram", "buckets": lat.cumulative(),
                   "sum": lat.sum, "count": lat.count}
        if lat.exemplar is not None:
            latency["exemplar"] = dict(lat.exemplar)
        return {"ready": bool(health.get("ready")),
                "live": bool(health.get("live")),
                "state": str(health.get("state", "")),
                "stats": stats, "latency": latency}

    def _scrape_one(self, replica: Any) -> Dict[str, Any]:
        if hasattr(replica, "server"):
            return self._scrape_inproc(replica)
        if hasattr(replica, "addr"):
            return self._scrape_http(replica)
        health = replica.health()  # minimal duck-typed fallback
        return {"ready": bool(health.get("ready")),
                "live": bool(health.get("live")),
                "state": str(health.get("state", "")),
                "stats": {}, "latency": None}

    def _refresh_replicas(self) -> None:
        """Re-read the router's handle set so replicas added/removed by
        the autopilot's scale lever appear in the very next scrape (the
        founding list used to be frozen at construction). Breakers are
        created lazily for new names and kept for departed ones, so a
        re-added name resumes its breaker history."""
        if self.router is None:
            return
        reps = [h.replica for h in self.router._handles.values()]
        self.replicas = reps
        for r in reps:
            if r.name not in self._breakers:
                self._breakers[r.name] = CircuitBreaker(
                    f"scrape.{r.name}",
                    failure_threshold=self._breaker_failures,
                    reset_timeout_s=self._breaker_reset_s,
                    clock=self.clock)

    # -- the scrape --------------------------------------------------------
    def scrape(self) -> Dict[str, Any]:
        """One full pass over every replica -> merged snapshot. Never
        raises: per-replica failures are recorded in the snapshot (and
        fed to that replica's breaker)."""
        t0 = events.perf()
        self._refresh_replicas()
        snap: Dict[str, Any] = {"ts": float(self.clock()), "replicas": {}}
        totals: Dict[str, float] = {}
        latencies: List[Dict[str, float]] = []
        for replica in self.replicas:
            name = replica.name
            breaker = self._breakers[name]
            try:
                one = breaker.call(self._scrape_one, replica)
            except CircuitOpen:
                one = {"ready": False, "live": False, "state": "unknown",
                       "stats": {}, "latency": None,
                       "error": "circuit_open"}
            except Exception as e:
                one = {"ready": False, "live": False, "state": "unknown",
                       "stats": {}, "latency": None,
                       "error": f"{type(e).__name__}: {e}"}
            one["breaker"] = breaker.state
            snap["replicas"][name] = one
            for k, v in one["stats"].items():
                if isinstance(v, (int, float)):
                    totals[k] = totals.get(k, 0.0) + float(v)
            if one.get("latency"):
                latencies.append(one["latency"])
        if latencies:
            merged = merge_cumulative(l["buckets"] for l in latencies)
            totals["p50_ms"] = round(
                metrics.percentile_from_buckets(merged, 50), 3)
            totals["p99_ms"] = round(
                metrics.percentile_from_buckets(merged, 99), 3)
            snap["latency"] = {
                "buckets": merged,
                "sum": sum(l["sum"] for l in latencies),
                "count": sum(l["count"] for l in latencies)}
        if self.router is not None:
            rs = self.router.stats()
            totals["failovers"] = float(rs.get("failovers", 0))
            totals["all_shed"] = float(rs.get("all_shed", 0))
            snap["router"] = rs
        self._publish_digests(snap)
        snap["fleet"] = totals
        snap["memory"] = devmem.get_ledger().snapshot()
        self._last = snap
        self._update_registry(snap)
        dt_ms = (events.perf() - t0) * 1e3
        metrics.histogram("fleet.scrape_ms").observe(dt_ms)
        snap["scrape_ms"] = round(dt_ms, 3)
        return snap

    @property
    def last(self) -> Optional[Dict[str, Any]]:
        return self._last

    def _publish_digests(self, snap: Dict[str, Any]) -> None:
        """Fleet-wide prefix-digest pull (docs/SERVING.md "fleet as one
        cache"): each replica's ``generate.<model>.kv.resident_chains``
        summary — a structured stats value the numeric totals above
        skip — is published into the router's shared
        :class:`~mmlspark_tpu.serve.affinity.AffinityState`, which is
        what the router scores generate picks against. A no-op without
        a router or with affinity disabled."""
        aff = getattr(self.router, "affinity", None)
        if aff is None:
            return
        tail = ".kv.resident_chains"
        for name, one in snap["replicas"].items():
            stats = one.get("stats") or {}
            for k, v in stats.items():
                if not (k.startswith("generate.") and k.endswith(tail)
                        and isinstance(v, list)):
                    continue
                model = k[len("generate."):-len(tail)]
                aff.update_digest(
                    name, model, v,
                    kv_dtype=stats.get(f"generate.{model}.kv.kv_dtype"),
                    block_tokens=stats.get(
                        f"generate.{model}.kv.block_tokens"),
                    ts=snap["ts"])
        snap["affinity"] = aff.snapshot()

    def _update_registry(self, snap: Dict[str, Any]) -> None:
        reg = self.registry
        reg.clear()
        for name, one in snap["replicas"].items():
            labels = {"replica": name}
            reg.set_value("fleet.replica_ready", labels,
                          1.0 if one["ready"] else 0.0)
            reg.set_value("fleet.replica_live", labels,
                          1.0 if one["live"] else 0.0)
            for k, v in one["stats"].items():
                if not isinstance(v, (int, float)):
                    continue
                mtype = "counter" if k in _COUNTER_KEYS else "gauge"
                reg.set_value(f"serving.{k}", labels, v, mtype)
            lat = one.get("latency")
            if lat:
                reg.set_histogram("serving.total_ms", labels,
                                  lat["buckets"], lat["sum"], lat["count"],
                                  exemplar=lat.get("exemplar"))
        for (model, kinds) in snap["memory"]["by_model"].items():
            for kind, nbytes in kinds.items():
                reg.set_value("memory.bytes", {"model": model, "kind": kind},
                              nbytes)
        reg.set_value("memory.hbm_bytes", {},
                      snap["memory"]["total_bytes"])
        reg.set_value("memory.hbm_high_watermark_bytes", {},
                      snap["memory"]["high_watermark_bytes"])
        if self.router is not None and "router" in snap:
            reg.set_value("fleet.failovers", {},
                          snap["fleet"].get("failovers", 0.0), "counter")
            reg.set_value("fleet.all_shed", {},
                          snap["fleet"].get("all_shed", 0.0), "counter")

    # -- exports -----------------------------------------------------------
    def prometheus_text(self) -> str:
        if self._last is None:
            self.scrape()
        return self.registry.prometheus_text()

    def to_dict(self) -> Dict[str, Any]:
        if self._last is None:
            self.scrape()
        return self.registry.to_dict()

    # -- SLO bridge --------------------------------------------------------
    def slo_sample(self,
                   snapshot: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
        """Distill one scrape into the cumulative totals the SLO engine
        windows over: ``admitted`` (good+bad demand), ``bad`` (shed +
        expired + failed + router failovers — every request the fleet
        did not serve first-try), and the merged latency buckets."""
        snap = snapshot if snapshot is not None else self.scrape()
        fleet = snap.get("fleet", {})
        bad = (fleet.get("shed", 0.0) + fleet.get("expired", 0.0)
               + fleet.get("failed", 0.0) + fleet.get("failovers", 0.0))
        sample = {"t": float(snap["ts"]),
                  "admitted": float(fleet.get("admitted", 0.0)),
                  "bad": float(bad)}
        lat = snap.get("latency")
        if lat:
            sample["latency_buckets"] = dict(lat["buckets"])
        ttft = metrics.get_registry().to_dict().get("generate.ttft_ms")
        if ttft and ttft.get("type") == "histogram":
            sample["ttft_buckets"] = dict(ttft["buckets"])
        return sample

    # -- background loop ---------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> None:
        """Scrape on a daemon thread every ``interval_s`` (default
        ``observability.scrape_interval_s``) until :meth:`stop`."""
        if self._thread is not None:
            return
        interval = float(interval_s if interval_s is not None
                         else mmlconfig.get(
                             "observability.scrape_interval_s"))

        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.scrape()
                except Exception:  # pragma: no cover - defensive
                    logger.exception("fleet scrape failed")

        self._stop.clear()
        self._thread = threading.Thread(
            target=loop, name="mmlspark-tpu-scraper", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
