"""Host-sync accounting: make every device round trip a counted event.

ROADMAP item 4's target is "zero host syncs per step" in the low-MFU
lanes — but a target you cannot measure is a slogan. Every
``jax.device_get``/``block_until_ready`` is a host<->device round trip
(the dispatch pipeline drains, the host blocks); this module is the ONE
place they are allowed to happen (lint Rule 7 flags the raw calls
anywhere else without a ``# lint: allow-sync`` marker), and each one is
accounted:

- ``observability.sync_points`` counter (total) plus a per-site counter
  ``observability.sync_points.<site>`` — the scoreboard;
- a ``sync.point`` event carrying the site and the innermost open span's
  ``(name, span_id, pid)``, so a report/trace can attribute the sync to
  the phase that paid for it (gated on :func:`events.recording_enabled`,
  so syncs land in the flight recorder too);
- the trainer samples :func:`total` around its fit loop and publishes the
  per-step delta as the ``train.sync_points_per_step`` gauge — the number
  item 4 drives to zero.

``sync_point(site)`` is the primitive; :func:`device_get` and
:func:`block_until_ready` wrap the jax calls for drop-in replacement at
call sites. Counting is a plain int add under a lock — cheap enough that
it is unconditional, like the cold-path counters in :mod:`metrics`.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

from mmlspark_tpu.observability import events, metrics, spans

_lock = threading.Lock()
_total = 0


def total() -> int:
    """Lifetime sync-point count for this process (the trainer diffs this
    around a step to compute syncs-per-step)."""
    return _total


def sync_point(site: str, kind: str = "sync") -> None:
    """Record one host sync at ``site`` (e.g. ``"trainer.collect_losses"``).

    ``kind`` names the blocking primitive (``device_get`` /
    ``block_until_ready``) for the event log. Counts unconditionally;
    emits a ``sync.point`` event (with current-span attribution) when any
    event sink is live.
    """
    global _total
    with _lock:
        _total += 1
    metrics.counter("observability.sync_points").inc()
    metrics.counter(f"observability.sync_points.{site}").inc()
    if events.recording_enabled():
        cur = spans.current_span()
        events.emit("event", "sync.point", site=site, kind=kind,
                    span=cur[0] if cur else None,
                    span_id=cur[1] if cur else None)


def device_get(x: Any, site: str) -> Any:
    """Counted ``jax.device_get`` — the sanctioned spelling of a
    device->host transfer outside this module."""
    sync_point(site, "device_get")
    import jax
    return jax.device_get(x)  # lint: allow-sync (the accounting home)


def block_until_ready(x: Any, site: str) -> Any:
    """Counted ``jax.block_until_ready`` (works for arrays and pytrees;
    also the spelling for ``arr.block_until_ready()`` method-call sites).
    """
    sync_point(site, "block_until_ready")
    import jax
    return jax.block_until_ready(x)  # lint: allow-sync (the accounting home)


def reset(_only_for_tests: Optional[bool] = None) -> None:
    """Zero the process total (tests measuring per-phase deltas)."""
    global _total
    with _lock:
        _total = 0
