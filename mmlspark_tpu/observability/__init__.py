"""Unified telemetry: spans, event log, metrics registry, run reports.

The single layer the whole stack reports through (SURVEY.md §5 sets the
observability bar above the reference, which had nothing beyond test
wall-clock timing). Three pieces, one pipeline:

- :mod:`events` — a process-wide JSON-lines event log with an injectable
  clock (tests are deterministic) — off until ``observability.events_path``
  is set (env: ``MMLSPARK_TPU_OBSERVABILITY_EVENTS_PATH``);
- :mod:`spans` — ``span("fit", "Featurize")`` context manager with a
  context-propagated parent stack; each span emits one structured event on
  exit and can pass through a ``jax.profiler.TraceAnnotation``
  (``observability.annotate``);
- :mod:`metrics` — counters / gauges / fixed-bucket histograms with
  Prometheus text exposition and a JSON dump.

Everything is off by default and near-zero-cost when disabled: ``span()``
short-circuits to a shared no-op before any string work, ``emit()`` returns
before serializing, and hot loops gate per-step collection on
``observability.metrics``. ``mmlspark-tpu report <events.jsonl>``
(:mod:`report`) renders the wall-time breakdown from a captured log.
"""
from mmlspark_tpu.observability.events import (  # noqa: F401
    emit,
    events_enabled,
    perf,
    reset_clock,
    set_clock,
    wall,
)
from mmlspark_tpu.observability.metrics import (  # noqa: F401
    MetricsRegistry,
    counter,
    gauge,
    get_registry,
    histogram,
    metrics_enabled,
)
from mmlspark_tpu.observability.spans import span  # noqa: F401
