"""Unified telemetry: spans, event log, metrics, traces, run reports.

The single layer the whole stack reports through (SURVEY.md §5 sets the
observability bar above the reference, which had nothing beyond test
wall-clock timing). The pieces, one pipeline:

- :mod:`events` — a process-wide JSON-lines event log with an injectable
  clock (tests are deterministic) — off until ``observability.events_path``
  is set (env: ``MMLSPARK_TPU_OBSERVABILITY_EVENTS_PATH``);
- :mod:`spans` — ``span("fit", "Featurize")`` context manager with a
  context-propagated parent stack; each span emits one structured event on
  exit and can pass through a ``jax.profiler.TraceAnnotation``
  (``observability.annotate``);
- :mod:`metrics` — counters / gauges / fixed-bucket histograms (with
  trace-id exemplars) plus Prometheus text exposition and a JSON dump;
- :mod:`syncs` — the host-sync accounter: every
  ``device_get``/``block_until_ready`` goes through :func:`sync_point`
  so "syncs per step" is a measured number, not a slogan (lint Rule 7
  enforces the routing);
- :mod:`flightrec` — a bounded in-memory ring of the last N events, ON
  by default, dumped on watchdog stalls / chaos red verdicts / CLI
  crashes so incidents ship a timeline even with the event log off;
- :mod:`trace` — Chrome-trace/Perfetto export of a captured log
  (``mmlspark-tpu report ... --trace out.trace.json``);
- :mod:`benchgate` — the bench regression gate
  (``mmlspark-tpu bench --baseline BENCH_rNN.json``);
- :mod:`aggregate` — the fleet scraper: per-replica ``/metrics`` +
  ``/readyz`` merged into one ``replica=``-labeled registry, plus
  multi-process event-log merging for the report;
- :mod:`slo` — declarative ``slo.*`` objectives with fast/slow-window
  burn-rate alerting (``slo.burn``/``slo.breach`` events);
- :mod:`memory` — the unified HBM ledger (bytes by ``{model, kind}``,
  high-watermark, ``memory.pressure`` events, live-array audit);
- :mod:`dashboard` — ``mmlspark-tpu top``, the live fleet view.

Everything is near-zero-cost when disabled: ``span()`` short-circuits to
a shared no-op before any string work, ``emit()`` returns before
serializing when no sink is live, and hot loops gate per-step collection
on ``observability.metrics``. The flight recorder is the one default-on
sink — an in-memory deque append, no I/O (set
``observability.flight_recorder_size`` to 0 for the true-zero path).
``mmlspark-tpu report <events.jsonl>`` (:mod:`report`) renders the
wall-time breakdown from a captured log (``--json`` for the structured
form).
"""
from mmlspark_tpu.observability.events import (  # noqa: F401
    emit,
    events_enabled,
    perf,
    recording_enabled,
    reset_clock,
    set_clock,
    wall,
)
from mmlspark_tpu.observability.metrics import (  # noqa: F401
    MetricsRegistry,
    counter,
    escape_label_value,
    gauge,
    get_registry,
    histogram,
    metrics_enabled,
)
from mmlspark_tpu.observability.spans import span  # noqa: F401
from mmlspark_tpu.observability.syncs import sync_point  # noqa: F401
from mmlspark_tpu.observability.aggregate import (  # noqa: F401
    AggregatedRegistry,
    FleetScraper,
    merge_event_logs,
)
from mmlspark_tpu.observability.memory import (  # noqa: F401
    MemoryLedger,
    audit_device_bytes,
    get_ledger,
)
from mmlspark_tpu.observability.slo import Objective, SloEngine  # noqa: F401
from mmlspark_tpu.observability.dashboard import TopDashboard  # noqa: F401
