"""Process-wide JSON-lines event log with an injectable clock.

One append-only stream every subsystem reports through: spans
(:mod:`spans`), train-loop metrics (``utils/logging.MetricLogger``),
reliability activity (retries, fault hits, checkpoint quarantines), model
downloads, and bench results. Each line is one JSON object::

    {"ts": <wall seconds>, "type": "span"|"event"|"metric", "name": "...",
     ...event-specific fields...}

Off until ``observability.events_path`` is set (config or
``MMLSPARK_TPU_OBSERVABILITY_EVENTS_PATH``); :func:`emit` then appends and
flushes under a lock, so concurrent threads interleave whole lines, never
partial ones. The clock pair (:func:`wall` for timestamps, :func:`perf`
for durations) is injectable via :func:`set_clock` so tests produce
byte-deterministic logs. Multi-process runs should point each process at
its own path (e.g. suffix ``jax.process_index()``) — appends from separate
processes are not coordinated.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Optional

from mmlspark_tpu.observability import flightrec
from mmlspark_tpu.utils import config

_lock = threading.Lock()
# injectable clock: [wall, perf] — swapped atomically under _lock
_clock = [time.time, time.perf_counter]
# lazily-opened writer, re-resolved when the configured path changes
_writer_path: Optional[str] = None
_writer_fh = None


def wall() -> float:
    """Wall-clock seconds (event timestamps)."""
    return _clock[0]()


def perf() -> float:
    """Monotonic seconds (durations)."""
    return _clock[1]()


def set_clock(wall_fn: Optional[Callable[[], float]] = None,
              perf_fn: Optional[Callable[[], float]] = None) -> None:
    """Inject fake clocks (tests). ``None`` leaves that clock unchanged."""
    with _lock:
        if wall_fn is not None:
            _clock[0] = wall_fn
        if perf_fn is not None:
            _clock[1] = perf_fn


def reset_clock() -> None:
    with _lock:
        _clock[0] = time.time
        _clock[1] = time.perf_counter


def events_enabled() -> bool:
    """Is the event log on? The one check hot paths make before any
    event-related work (string building, dict assembly)."""
    return bool(config.get("observability.events_path"))


def recording_enabled() -> bool:
    """Is ANY event sink live — the JSONL log or the in-memory flight
    recorder (:mod:`flightrec`, on by default)? Cold/incident paths gate
    on this so post-mortem timelines exist even in runs that never set
    ``observability.events_path``; per-step hot paths keep gating on
    :func:`events_enabled`."""
    return bool(config.get("observability.events_path")) \
        or flightrec.active()


def events_path() -> str:
    return config.get("observability.events_path")


def emit(etype: str, name: str, **fields: Any) -> None:
    """Append one event line; also feeds the flight-recorder ring
    (:mod:`flightrec`) when it is on. A silent no-op when both sinks are
    off.

    ``fields`` must be JSON-representable; anything else falls back to
    ``str()`` rather than killing the instrumented caller.
    """
    path = config.get("observability.events_path")
    ring = flightrec.active()
    if not (path or ring):
        return
    event = {"ts": round(wall(), 6), "type": etype, "name": name}
    event.update(fields)
    if ring:
        # the ring stores the dict (serialization deferred to dump time);
        # emit never mutates `event` after this point
        flightrec.record(event)
    if not path:
        return
    line = json.dumps(event, sort_keys=True, default=str)
    global _writer_path, _writer_fh
    with _lock:
        if _writer_path != path:
            if _writer_fh is not None:
                _writer_fh.close()
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            _writer_fh = open(path, "a", encoding="utf-8")
            _writer_path = path
        _writer_fh.write(line + "\n")
        _writer_fh.flush()


def close() -> None:
    """Close the writer (tests / clean shutdown); the next :func:`emit`
    reopens in append mode, so nothing is lost."""
    global _writer_path, _writer_fh
    with _lock:
        if _writer_fh is not None:
            _writer_fh.close()
        _writer_fh = None
        _writer_path = None
