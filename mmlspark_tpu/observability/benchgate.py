"""Bench regression gate: fresh bench line vs a committed baseline.

``mmlspark-tpu bench --baseline BENCH_r05.json`` (or ``./bench.py
--baseline ...``) runs the bench as usual, then compares the fresh
one-line JSON result against the committed baseline per lane:

- ``value`` (the lane's headline throughput) must not drop more than the
  tolerance below the baseline;
- ``step_ms`` must not rise more than the tolerance above it;
- ``mfu`` must not drop more than the tolerance below it;
- ``ttft_p99_ms`` (serving lanes) must not rise more than the tolerance
  above it;
- ``shed_rate`` / ``spike_p99_ms`` (the autopilot lane) must not rise
  more than the tolerance above it — a controller change that sheds
  more or recovers slower under the seeded spike is a regression;
- ``goodput`` (open-loop lanes: fraction of OFFERED requests answered
  within the lane's deadline) must not drop more than the tolerance
  below it, and ``arrival_p99_ms`` (latency from the INTENDED arrival
  time, un-clipped) must not rise more than the tolerance above it.

Tail-latency percentiles carry two noise guards the ratio gate lacks:
an absolute resolution floor — a ``*_p99_ms`` check whose rise is
within ``_MS_RESOLUTION`` (5 ms) passes even past the ratio tolerance,
because on a single-digit-ms percentile the ratio gate would red on
sub-millisecond host scheduler jitter no bench host can resolve (the
check records ``floor_ms`` when the floor is what saved it) — and
deep-headroom absorption: when BOTH sides of the comparison sit within
10% of the lane's ``deadline_ms``, the percentile is measuring host
noise far from the saturation knee, not SLO behaviour (goodput is the
gated signal there), so the check passes and records ``headroom_ms``.
A rise that crosses OUT of the headroom band still reds. ``step_ms``
gets neither guard: it is a mean over many steps, where a 2 ms rise is
signal, not noise.

Clipped percentiles are never parity evidence. A latency percentile
that sits exactly at the lane's ``deadline_ms`` — or that the lane
marks ``<field>_clipped`` — is a FLOOR, not a value: the true
percentile is somewhere above it. So a clipped fresh value against an
un-clipped baseline is a regression outright (the fresh run saturated
where the baseline did not), while any comparison against a clipped
baseline is demoted to informational (``clipped-vs-clipped`` showing
90000 vs 90000 proves nothing — exactly the blind spot that hid the
r08 spike regression). A legacy baseline lane that predates the
open-loop rework (it has ``spike_p99_ms`` but neither ``deadline_ms``
nor ``arrival_p99_ms``) cannot even be tested for clipping, so its
``spike_p99_ms`` is informational too — the transition can never
false-fail.

A lane that was budget-skipped (or terminated) in EITHER run is marked
``skipped``, never red — congestion on the bench host must not fail CI.
A lane missing a field in the baseline simply skips that check. The
verdict is printed as a second JSON line on stdout and the process exits
0 iff every checked lane is green.

Baselines are accepted in both shapes the repo produces: the raw bench
line (``{"metric", "value", "configs": {...}}``) and the driver wrapper
committed as BENCH_r05.json (``{"n", "cmd", "rc", "parsed": <line>}``).

Pure data in, data out — no jax, no bench imports — so the comparison is
unit-testable without running a single bench step.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

# 10%: wide enough to ride out shared-host noise on a 5-rep bench, tight
# enough to catch the 20%+ cliffs a bad dispatch-path change causes.
DEFAULT_TOLERANCE = 0.10


def load_baseline(path: str) -> Dict[str, Any]:
    """Read a committed baseline; unwraps the ``{"parsed": ...}`` driver
    wrapper when present. Raises ValueError when no bench line with a
    ``configs`` map can be found."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if not isinstance(data, dict) or not isinstance(data.get("configs"),
                                                    dict):
        raise ValueError(
            f"{path}: not a bench baseline (expected a bench line with a "
            "'configs' map, or a wrapper with 'parsed')")
    return data


def _num(lane: Dict[str, Any], field: str) -> Optional[float]:
    v = lane.get(field)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


# latency percentiles that can saturate at a lane deadline; everything in
# the clipped-handling path below applies to these and only these
_LATENCY_FIELDS = ("ttft_p99_ms", "spike_p99_ms", "arrival_p99_ms")


def clipped(lane: Dict[str, Any], field: str) -> bool:
    """Is this lane's latency percentile a deadline-saturated FLOOR
    rather than a measured value? True when the lane says so outright
    (``<field>_clipped``) or when the value sits EXACTLY at the lane's
    ``deadline_ms`` — the saturated-top-bucket signature. An honest
    open-loop measurement ABOVE the deadline is not clipped: that is a
    real (bad) number, and gating it is the whole point."""
    if lane.get(f"{field}_clipped") is True:
        return True
    v = _num(lane, field)
    d = _num(lane, "deadline_ms")
    return v is not None and d is not None and d > 0 and v == d


def _legacy_closed_loop(lane: Dict[str, Any]) -> bool:
    """A pre-open-loop baseline lane: it reports ``spike_p99_ms`` but
    carries neither the deadline nor the arrival-time percentile, so its
    latency numbers cannot even be tested for clipping (r08 and earlier
    committed 90000.0-clipped values as if they were measurements)."""
    return ("spike_p99_ms" in lane and "deadline_ms" not in lane
            and "arrival_p99_ms" not in lane)


# absolute resolution floor for tail-latency percentiles: below this, a
# difference is host scheduler jitter, not a regression — a 10% ratio
# gate on an 8 ms p99 would be red over 0.8 ms of noise no measurement
# on a shared-core bench host can resolve. Applies ONLY to the
# percentile fields in _LATENCY_FIELDS: step_ms is a mean over many
# steps, where a 2 ms rise IS signal.
_MS_RESOLUTION = 5.0

# deep-headroom band for tail-latency percentiles: when both sides of a
# comparison sit within this fraction of the lane's deadline, the p99
# is nowhere near the queueing knee and its movement is host noise —
# goodput (gated) is the SLO signal in that regime. A shared-core
# bench host can turn an 8 ms p99 into 19 ms between identical runs; a
# real saturation drift blows past 10% of the deadline immediately.
_HEADROOM_FRAC = 0.10


def _check(name: str, fresh_v: Optional[float], base_v: Optional[float],
           tolerance: float, higher_is_better: bool) -> Optional[Dict[str, Any]]:
    """One metric comparison; None when either side can't be checked
    (missing field, or a zero/negative baseline that makes a ratio
    meaningless)."""
    if fresh_v is None or base_v is None or base_v <= 0:
        return None
    ratio = fresh_v / base_v
    if higher_is_better:
        ok = ratio >= 1.0 - tolerance
    else:
        ok = ratio <= 1.0 + tolerance
    out = {"metric": name, "fresh": fresh_v, "baseline": base_v,
           "ratio": round(ratio, 4), "tolerance": tolerance, "ok": ok}
    if (not ok and not higher_is_better and name in _LATENCY_FIELDS
            and fresh_v - base_v <= _MS_RESOLUTION):
        out["ok"] = True
        out["floor_ms"] = _MS_RESOLUTION
    return out


def compare(fresh: Dict[str, Any], baseline: Dict[str, Any],
            tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Per-lane comparison of a fresh bench line against a baseline line.

    Returns the verdict dict: ``{"gate": ..., "green": bool, "lanes":
    {name: {"status": green|red|skipped, "checks": [...], "reasons":
    [...]}}, "red": [...], "skipped": [...]}``.
    """
    fresh_cfg = fresh.get("configs") or {}
    base_cfg = baseline.get("configs") or {}
    lanes: Dict[str, Any] = {}
    red, skipped = [], []
    for name in sorted(base_cfg):
        base_lane = base_cfg.get(name) or {}
        fresh_lane = fresh_cfg.get(name)
        if base_lane.get("skipped"):
            lanes[name] = {"status": "skipped",
                           "reasons": ["skipped in baseline"]}
            skipped.append(name)
            continue
        if fresh_lane is None or fresh_lane.get("skipped"):
            reason = (fresh_lane or {}).get("reason", "lane did not run")
            lanes[name] = {"status": "skipped", "reasons": [str(reason)]}
            skipped.append(name)
            continue
        checks = []
        for field, higher in (("value", True), ("step_ms", False),
                              ("mfu", True), ("ttft_p99_ms", False),
                              ("shed_rate", False),
                              ("spike_p99_ms", False),
                              ("goodput", True),
                              ("arrival_p99_ms", False),
                              # fleet-wide prefix re-use under affinity
                              # routing: a change that stops the router
                              # steering repeats to warm replicas IS a
                              # regression, so this one gates
                              ("fleet_prefix_hit_rate", True)):
            c = _check(field, _num(fresh_lane, field),
                       _num(base_lane, field), tolerance, higher)
            if c is None:
                continue
            if field in _LATENCY_FIELDS:
                fresh_clip = clipped(fresh_lane, field)
                base_clip = clipped(base_lane, field)
                if fresh_clip:
                    c["clipped"] = True
                if base_clip:
                    c["baseline_clipped"] = True
                legacy = (field == "spike_p99_ms"
                          and _legacy_closed_loop(base_lane))
                if base_clip or legacy:
                    # the baseline number is a floor (or can't be told
                    # from one): a ratio against it proves nothing in
                    # either direction — report, never red, and never
                    # count clipped-vs-clipped as parity
                    c["ok"] = True
                    c["informational"] = True
                    c["note"] = (
                        "clipped-vs-clipped: not parity evidence"
                        if fresh_clip and base_clip else
                        "baseline is a clipped/legacy closed-loop "
                        "floor; not comparable")
                elif fresh_clip:
                    # the fresh run saturated where the baseline did
                    # not — a regression even at ratio 1.0
                    c["ok"] = False
                    c["note"] = ("fresh percentile clipped at the "
                                 "deadline; baseline was un-clipped")
                elif not c["ok"]:
                    # deep-headroom absorption: both sides far inside
                    # the deadline — see _HEADROOM_FRAC
                    d = (_num(fresh_lane, "deadline_ms")
                         or _num(base_lane, "deadline_ms"))
                    band = _HEADROOM_FRAC * d if d and d > 0 else None
                    if (band is not None and c["fresh"] <= band
                            and c["baseline"] <= band):
                        c["ok"] = True
                        c["headroom_ms"] = band
                        c["note"] = ("deep headroom: both sides within "
                                     "10% of the deadline")
            checks.append(c)
        # compile_ms / cold_start_ms are INFORMATIONAL: cold-start cost
        # swings with cache state and host load, so the comparison is
        # reported (so the compile-cache win is a visible number) but can
        # never flip a lane red. Prefix hit rate and speculative
        # acceptance are workload signatures, not regressions — reported
        # so a cache-defeating change is visible, never red. Per-shard
        # HBM (shard_bytes_max) tracks the mesh topology, not the code
        # under test — reported so the crossing-the-chip win is a
        # visible number, never red.
        # Decision counts and recovery time are controller workload
        # signatures, not regressions — reported so a policy change that
        # triples the action rate is visible, never red.
        # spawn_to_ready_ms (process cold-start + cache loads) swings
        # with host load, and steady_compiles is a warm-scale-up
        # contract count — both reported, never red.
        for info_field, higher in (("compile_ms", False),
                                   ("cold_start_ms", False),
                                   ("prefix_hit_rate", True),
                                   ("spec_accept_rate", True),
                                   ("shard_bytes_max", False),
                                   ("decisions", False),
                                   ("suppressed", False),
                                   ("time_to_recover_s", False),
                                   ("spawn_to_ready_ms", False),
                                   ("steady_compiles", False),
                                   # routing-mode split: a workload
                                   # signature (how often affinity found
                                   # a signal), not a regression axis
                                   ("affinity_route_share", True)):
            c = _check(info_field, _num(fresh_lane, info_field),
                       _num(base_lane, info_field), tolerance, higher)
            if c is not None:
                c["ok"] = True
                c["informational"] = True
                checks.append(c)
        reasons = [
            f"{c['metric']}: {c['fresh']:g} vs baseline "
            f"{c['baseline']:g} (ratio {c['ratio']:g}, "
            f"tolerance {c['tolerance']:g})"
            + (f" — {c['note']}" if c.get("note") else "")
            for c in checks if not c["ok"]]
        status = "red" if reasons else "green"
        if reasons:
            red.append(name)
        lanes[name] = {"status": status, "checks": checks,
                       "reasons": reasons}
    # lanes only in the fresh run have nothing to regress against
    for name in sorted(set(fresh_cfg) - set(base_cfg)):
        lanes[name] = {"status": "skipped",
                       "reasons": ["no baseline lane"]}
        skipped.append(name)
    return {"gate": "bench-regression", "tolerance": tolerance,
            "green": not red, "red": red, "skipped": skipped,
            "lanes": lanes}


def gate(fresh: Dict[str, Any], baseline_path: str,
         tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Load the baseline, compare, and return the verdict with the
    baseline path recorded (the bench CLI prints this as its second
    stdout line and exits nonzero unless ``verdict["green"]``)."""
    verdict = compare(fresh, load_baseline(baseline_path),
                      tolerance=tolerance)
    verdict["baseline"] = baseline_path
    return verdict
