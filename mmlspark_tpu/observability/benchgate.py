"""Bench regression gate: fresh bench line vs a committed baseline.

``mmlspark-tpu bench --baseline BENCH_r05.json`` (or ``./bench.py
--baseline ...``) runs the bench as usual, then compares the fresh
one-line JSON result against the committed baseline per lane:

- ``value`` (the lane's headline throughput) must not drop more than the
  tolerance below the baseline;
- ``step_ms`` must not rise more than the tolerance above it;
- ``mfu`` must not drop more than the tolerance below it;
- ``ttft_p99_ms`` (serving lanes) must not rise more than the tolerance
  above it;
- ``shed_rate`` / ``spike_p99_ms`` (the autopilot lane) must not rise
  more than the tolerance above it — a controller change that sheds
  more or recovers slower under the seeded spike is a regression.

A lane that was budget-skipped (or terminated) in EITHER run is marked
``skipped``, never red — congestion on the bench host must not fail CI.
A lane missing a field in the baseline simply skips that check. The
verdict is printed as a second JSON line on stdout and the process exits
0 iff every checked lane is green.

Baselines are accepted in both shapes the repo produces: the raw bench
line (``{"metric", "value", "configs": {...}}``) and the driver wrapper
committed as BENCH_r05.json (``{"n", "cmd", "rc", "parsed": <line>}``).

Pure data in, data out — no jax, no bench imports — so the comparison is
unit-testable without running a single bench step.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

# 10%: wide enough to ride out shared-host noise on a 5-rep bench, tight
# enough to catch the 20%+ cliffs a bad dispatch-path change causes.
DEFAULT_TOLERANCE = 0.10


def load_baseline(path: str) -> Dict[str, Any]:
    """Read a committed baseline; unwraps the ``{"parsed": ...}`` driver
    wrapper when present. Raises ValueError when no bench line with a
    ``configs`` map can be found."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if not isinstance(data, dict) or not isinstance(data.get("configs"),
                                                    dict):
        raise ValueError(
            f"{path}: not a bench baseline (expected a bench line with a "
            "'configs' map, or a wrapper with 'parsed')")
    return data


def _num(lane: Dict[str, Any], field: str) -> Optional[float]:
    v = lane.get(field)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _check(name: str, fresh_v: Optional[float], base_v: Optional[float],
           tolerance: float, higher_is_better: bool) -> Optional[Dict[str, Any]]:
    """One metric comparison; None when either side can't be checked
    (missing field, or a zero/negative baseline that makes a ratio
    meaningless)."""
    if fresh_v is None or base_v is None or base_v <= 0:
        return None
    ratio = fresh_v / base_v
    if higher_is_better:
        ok = ratio >= 1.0 - tolerance
    else:
        ok = ratio <= 1.0 + tolerance
    return {"metric": name, "fresh": fresh_v, "baseline": base_v,
            "ratio": round(ratio, 4), "tolerance": tolerance, "ok": ok}


def compare(fresh: Dict[str, Any], baseline: Dict[str, Any],
            tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Per-lane comparison of a fresh bench line against a baseline line.

    Returns the verdict dict: ``{"gate": ..., "green": bool, "lanes":
    {name: {"status": green|red|skipped, "checks": [...], "reasons":
    [...]}}, "red": [...], "skipped": [...]}``.
    """
    fresh_cfg = fresh.get("configs") or {}
    base_cfg = baseline.get("configs") or {}
    lanes: Dict[str, Any] = {}
    red, skipped = [], []
    for name in sorted(base_cfg):
        base_lane = base_cfg.get(name) or {}
        fresh_lane = fresh_cfg.get(name)
        if base_lane.get("skipped"):
            lanes[name] = {"status": "skipped",
                           "reasons": ["skipped in baseline"]}
            skipped.append(name)
            continue
        if fresh_lane is None or fresh_lane.get("skipped"):
            reason = (fresh_lane or {}).get("reason", "lane did not run")
            lanes[name] = {"status": "skipped", "reasons": [str(reason)]}
            skipped.append(name)
            continue
        checks = [c for c in (
            _check("value", _num(fresh_lane, "value"),
                   _num(base_lane, "value"), tolerance, True),
            _check("step_ms", _num(fresh_lane, "step_ms"),
                   _num(base_lane, "step_ms"), tolerance, False),
            _check("mfu", _num(fresh_lane, "mfu"),
                   _num(base_lane, "mfu"), tolerance, True),
            _check("ttft_p99_ms", _num(fresh_lane, "ttft_p99_ms"),
                   _num(base_lane, "ttft_p99_ms"), tolerance, False),
            _check("shed_rate", _num(fresh_lane, "shed_rate"),
                   _num(base_lane, "shed_rate"), tolerance, False),
            _check("spike_p99_ms", _num(fresh_lane, "spike_p99_ms"),
                   _num(base_lane, "spike_p99_ms"), tolerance, False),
        ) if c is not None]
        # compile_ms / cold_start_ms are INFORMATIONAL: cold-start cost
        # swings with cache state and host load, so the comparison is
        # reported (so the compile-cache win is a visible number) but can
        # never flip a lane red. Prefix hit rate and speculative
        # acceptance are workload signatures, not regressions — reported
        # so a cache-defeating change is visible, never red. Per-shard
        # HBM (shard_bytes_max) tracks the mesh topology, not the code
        # under test — reported so the crossing-the-chip win is a
        # visible number, never red.
        # Decision counts and recovery time are controller workload
        # signatures, not regressions — reported so a policy change that
        # triples the action rate is visible, never red.
        # spawn_to_ready_ms (process cold-start + cache loads) swings
        # with host load, and steady_compiles is a warm-scale-up
        # contract count — both reported, never red.
        for info_field, higher in (("compile_ms", False),
                                   ("cold_start_ms", False),
                                   ("prefix_hit_rate", True),
                                   ("spec_accept_rate", True),
                                   ("shard_bytes_max", False),
                                   ("decisions", False),
                                   ("suppressed", False),
                                   ("time_to_recover_s", False),
                                   ("spawn_to_ready_ms", False),
                                   ("steady_compiles", False)):
            c = _check(info_field, _num(fresh_lane, info_field),
                       _num(base_lane, info_field), tolerance, higher)
            if c is not None:
                c["ok"] = True
                c["informational"] = True
                checks.append(c)
        reasons = [
            f"{c['metric']}: {c['fresh']:g} vs baseline "
            f"{c['baseline']:g} (ratio {c['ratio']:g}, "
            f"tolerance {c['tolerance']:g})"
            for c in checks if not c["ok"]]
        status = "red" if reasons else "green"
        if reasons:
            red.append(name)
        lanes[name] = {"status": status, "checks": checks,
                       "reasons": reasons}
    # lanes only in the fresh run have nothing to regress against
    for name in sorted(set(fresh_cfg) - set(base_cfg)):
        lanes[name] = {"status": "skipped",
                       "reasons": ["no baseline lane"]}
        skipped.append(name)
    return {"gate": "bench-regression", "tolerance": tolerance,
            "green": not red, "red": red, "skipped": skipped,
            "lanes": lanes}


def gate(fresh: Dict[str, Any], baseline_path: str,
         tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Load the baseline, compare, and return the verdict with the
    baseline path recorded (the bench CLI prints this as its second
    stdout line and exits nonzero unless ``verdict["green"]``)."""
    verdict = compare(fresh, load_baseline(baseline_path),
                      tolerance=tolerance)
    verdict["baseline"] = baseline_path
    return verdict
