"""Chrome-trace (Perfetto) export of the span event log.

``mmlspark-tpu report <events.jsonl> --trace out.trace.json`` turns the
JSONL event log into ``trace_event``-format JSON — the format Perfetto
(https://ui.perfetto.dev) and chrome://tracing open directly — so "where
did the wall time go" becomes a zoomable timeline instead of a table.

Reconstruction rules:

- span events are keyed on ``(pid, span_id)`` — span ids are per-process
  counters, so a merged multi-host log collides on ``span_id`` alone
  (events from logs predating the ``pid`` field fall back to pid 0);
- nesting comes from the recorded ``parent_id``/``depth`` fields: each
  root span chain becomes one Perfetto track (``tid``), chosen greedily so
  non-overlapping roots share a track and concurrent roots get their own;
- every span emits a ``B``/``E`` duration pair (timestamps in
  microseconds, rebased to the log's earliest span start). Children are
  clamped inside their parent's interval and siblings are sequentialized
  when rounding makes them overlap — a few-µs distortion, in exchange for
  a track that always nests (every ``B`` closed by its ``E``, timestamps
  monotone per track);
- plain ``event``-type records (watchdog stalls, shed requests, sync
  points, fault hits) become instant (``i``) marks on a dedicated track,
  so incidents line up against the spans that surround them.

Pure data in, data out — no jax, no framework state (same discipline as
:mod:`report`).
"""
from __future__ import annotations

import json
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.observability.report import load_events

_EVENTS_TID = 0          # instant marks live on tid 0; span tracks start at 1


def _span_key(e: Dict[str, Any]) -> Tuple[int, int]:
    return int(e.get("pid") or 0), int(e["span_id"])


def build_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Event dicts -> ``{"traceEvents": [...], "displayTimeUnit": "ms"}``.

    Only spans with a ``span_id`` and plain events with a ``ts`` are
    consumed; anything else (metrics, malformed records) is skipped.
    """
    spans = [e for e in events
             if e.get("type") == "span" and e.get("span_id") is not None]
    instants = [e for e in events
                if e.get("type") in ("event", "serving")
                and e.get("ts") is not None]

    # intervals: (pid, span_id) -> [start, end]; tree: parent -> children
    by_key: Dict[Tuple[int, int], Dict[str, Any]] = {}
    for s in spans:
        by_key[_span_key(s)] = s
    children: Dict[Optional[Tuple[int, int]], List[Tuple[int, int]]] = \
        defaultdict(list)
    for key, s in by_key.items():
        parent = (key[0], int(s["parent_id"])) \
            if s.get("parent_id") else None
        if parent is not None and parent not in by_key:
            parent = None          # orphan (partial capture): treat as root
        children[parent].append(key)

    t0s = [float(s.get("start", s.get("ts", 0.0))) for s in spans]
    t0s += [float(e["ts"]) for e in instants]
    t0 = min(t0s) if t0s else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    out: List[Dict[str, Any]] = []
    tracks_per_pid: Dict[int, List[float]] = defaultdict(list)

    def emit_span(key: Tuple[int, int], lo: float, hi: float,
                  tid: int) -> None:
        """Emit one span's B/E (clamped into [lo, hi]) and recurse."""
        s = by_key[key]
        start = float(s.get("start", s.get("ts", 0.0)))
        end = start + float(s.get("dur_s", 0.0))
        start = min(max(start, lo), hi)
        end = max(min(end, hi), start)
        pid = key[0]
        args: Dict[str, Any] = {"span_id": key[1], "depth": s.get("depth")}
        if s.get("error"):
            args["error"] = s["error"]
        if isinstance(s.get("attrs"), dict):
            args.update(s["attrs"])
        name = str(s.get("name", "?"))
        out.append({"ph": "B", "name": name,
                    "cat": name.split(":", 1)[0],
                    "ts": us(start), "pid": pid, "tid": tid, "args": args})
        cursor = start
        kids = sorted(children.get(key, ()),
                      key=lambda k: float(by_key[k].get("start", 0.0)))
        for kid in kids:
            k_start = max(cursor,
                          float(by_key[kid].get("start", start)))
            emit_span(kid, k_start, end, tid)
            cursor = max(cursor, k_start
                         + float(by_key[kid].get("dur_s", 0.0)))
        out.append({"ph": "E", "ts": us(end), "pid": pid, "tid": tid})

    # per process: lay roots onto tracks (greedy first-fit on end time)
    roots_by_pid: Dict[int, List[Tuple[int, int]]] = defaultdict(list)
    for key in children[None]:
        roots_by_pid[key[0]].append(key)
    for pid, roots in sorted(roots_by_pid.items()):
        roots.sort(key=lambda k: float(by_key[k].get("start", 0.0)))
        tracks = tracks_per_pid[pid]
        for key in roots:
            s = by_key[key]
            start = float(s.get("start", s.get("ts", 0.0)))
            end = start + float(s.get("dur_s", 0.0))
            tid = None
            for i, busy_until in enumerate(tracks):
                if busy_until <= start:
                    tid = i + 1
                    break
            if tid is None:
                tracks.append(end)
                tid = len(tracks)
            else:
                tracks[tid - 1] = end
            emit_span(key, start, end, tid)

    # instant marks: incidents/events on their own track per pid
    pids = set(tracks_per_pid) | {int(e.get("pid") or 0) for e in instants}
    default_pid = min(tracks_per_pid) if tracks_per_pid else 0
    for e in instants:
        pid = int(e.get("pid") or default_pid)
        skip = {"ts", "type", "name", "pid"}
        args = {k: v for k, v in e.items() if k not in skip}
        name = str(e.get("name", "?"))
        if e.get("type") == "serving":
            name = f"serving.{name}"
        out.append({"ph": "i", "s": "t", "name": name,
                    "ts": us(float(e["ts"])), "pid": pid,
                    "tid": _EVENTS_TID,
                    "args": json.loads(json.dumps(args, default=str))})

    # metadata: readable process/track names in the Perfetto UI
    meta: List[Dict[str, Any]] = []
    for pid in sorted(pids):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": f"mmlspark-tpu pid {pid}"}})
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": _EVENTS_TID, "args": {"name": "events"}})
        for i in range(len(tracks_per_pid.get(pid, ()))):
            meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                         "tid": i + 1, "args": {"name": f"spans-{i + 1}"}})

    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"source": "mmlspark-tpu events.jsonl",
                          "t0_wall_s": t0,
                          "spans": len(spans), "events": len(instants)}}


def validate_trace(trace: Dict[str, Any]) -> List[str]:
    """Schema check for an exported trace; returns problems (empty =
    valid). Enforced: every ``B`` is closed by an ``E`` on the same
    ``(pid, tid)`` (LIFO), and timestamps are monotone non-decreasing per
    track in emission order."""
    problems: List[str] = []
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    open_stacks: Dict[Tuple[int, int], List[str]] = defaultdict(list)
    last_ts: Dict[Tuple[int, int], float] = {}
    for i, e in enumerate(evs):
        ph = e.get("ph")
        if ph not in ("B", "E", "i", "M", "X", "C"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        track = (e.get("pid"), e.get("tid"))
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if ph in ("B", "E") and ts < last_ts.get(track, float("-inf")):
            problems.append(
                f"event {i}: ts {ts} < previous {last_ts[track]} on "
                f"track {track}")
        if ph in ("B", "E"):
            last_ts[track] = float(ts)
        if ph == "B":
            if not e.get("name"):
                problems.append(f"event {i}: B without name")
            open_stacks[track].append(str(e.get("name", "")))
        elif ph == "E":
            if not open_stacks[track]:
                problems.append(f"event {i}: E without open B on "
                                f"track {track}")
            else:
                open_stacks[track].pop()
    for track, stack in open_stacks.items():
        if stack:
            problems.append(f"track {track}: {len(stack)} unclosed B "
                            f"({stack[-1]!r} innermost)")
    return problems


def export_trace(events_path: str, out_path: str) -> Dict[str, Any]:
    """Read an events.jsonl, write the Chrome-trace JSON to ``out_path``,
    and return summary stats (spans/events/tracks exported)."""
    trace = build_trace(load_events(events_path))
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    other = trace.get("otherData", {})
    tracks = len({(e.get("pid"), e.get("tid"))
                  for e in trace["traceEvents"] if e.get("ph") == "B"})
    return {"out": out_path, "spans": other.get("spans", 0),
            "events": other.get("events", 0), "tracks": tracks}
