"""Wall-time spans with a context-propagated parent stack.

``span(kind, detail)`` times a region and emits one ``"span"`` event to the
event log on exit, carrying its ``span_id``, its parent's id/name, and its
depth — enough to reconstruct the full nesting tree offline
(``mmlspark-tpu report``). The stack lives in a ``contextvars.ContextVar``,
so threads and async tasks each see their own ancestry instead of racing a
global.

Cost discipline: when neither ``observability.events_path`` nor
``observability.annotate`` is set, :func:`span` returns a shared no-op
context manager BEFORE any string is built — the name is assembled from
``(kind, detail)`` only on the enabled path, which is why call sites pass
the two pieces instead of a preformatted f-string. With
``observability.annotate`` on, the span also opens a
``jax.profiler.TraceAnnotation`` so the same names line up in
TensorBoard/Perfetto timelines (via the failure-safe
``utils.profiling.annotate``).
"""
from __future__ import annotations

import contextvars
import itertools
import os
import threading
from typing import Any, Optional, Tuple

from mmlspark_tpu.observability import events
from mmlspark_tpu.utils import config

# (name, span_id) ancestry for the current context; () at the root
_STACK: contextvars.ContextVar[Tuple[Tuple[str, int], ...]] = \
    contextvars.ContextVar("mmlspark_tpu_span_stack", default=())
_ids = itertools.count(1)
_ids_lock = threading.Lock()


def next_span_id() -> int:
    """Allocate a span id from the process counter. Span ids are unique
    only WITHIN a process — every span event therefore carries ``pid``,
    and consumers (report, trace export) key on ``(pid, span_id)`` so
    multi-host/merged logs never collide. Used by the tail-sampling path
    in ``serve/`` to mint ids for retroactively-emitted spans without
    colliding with live ones."""
    with _ids_lock:
        return next(_ids)


class _NoopSpan:
    """Shared disabled-path singleton: zero allocation per use."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "span_id", "_token", "_start_wall",
                 "_start_perf", "_parent", "_depth", "_annotation")

    def __init__(self, name: str, attrs: dict, annotate: bool):
        self.name = name
        self.attrs = attrs
        self.span_id = next_span_id()
        self._annotation = None
        if annotate:
            from mmlspark_tpu.utils.profiling import annotate as _annotate
            self._annotation = _annotate(name)

    def __enter__(self) -> "_Span":
        stack = _STACK.get()
        self._parent = stack[-1] if stack else None
        self._depth = len(stack)
        self._token = _STACK.set(stack + ((self.name, self.span_id),))
        self._start_wall = events.wall()
        self._start_perf = events.perf()
        if self._annotation is not None:
            self._annotation.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._annotation is not None:
            self._annotation.__exit__(exc_type, exc, tb)
        dur = events.perf() - self._start_perf
        _STACK.reset(self._token)
        fields = {
            "span_id": self.span_id,
            "pid": os.getpid(),
            "parent_id": self._parent[1] if self._parent else None,
            "parent": self._parent[0] if self._parent else "",
            "depth": self._depth,
            "start": round(self._start_wall, 6),
            "dur_s": round(dur, 9),
        }
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        if self.attrs:
            fields["attrs"] = self.attrs
        events.emit("span", self.name, **fields)
        return False


def span(kind: str, detail: str = "", **attrs: Any):
    """Context manager timing ``kind[:detail]`` (e.g. ``span("fit",
    "Featurize")`` -> span name ``fit:Featurize``).

    Returns the shared no-op when telemetry is off — callers may hold the
    result but must not rely on span identity. ``attrs`` ride along on the
    emitted event (keep them small and JSON-friendly).
    """
    annotate = bool(config.get("observability.annotate"))
    # recording_enabled, not events_enabled: the flight recorder (on by
    # default) captures spans too, so an incident dump has the timeline —
    # the true-noop fast path needs ALL three sinks off
    if not (annotate or events.recording_enabled()):
        return _NOOP
    return _Span(f"{kind}:{detail}" if detail else kind, attrs, annotate)


def current_span() -> Optional[Tuple[str, int]]:
    """(name, span_id) of the innermost open span, or None at the root."""
    stack = _STACK.get()
    return stack[-1] if stack else None
