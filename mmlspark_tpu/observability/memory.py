"""Unified HBM ledger: one accounting home for device bytes.

Residency accounting used to be scattered: the serving registry summed
param bytes, the KV arena computed its own footprint, the AOT program
cache knew serialized executable sizes, and nobody added them up. This
module is the single place device-byte arithmetic is allowed to live
(lint Rule 11 flags ``nbytes``/``itemsize`` arithmetic in ``serve/``
outside this home) and the single place totals are kept:

- :func:`nbytes_of` / :func:`param_bytes` — the shared size arithmetic
  the registry and KV arena delegate to;
- :class:`MemoryLedger` — bytes by ``{model, kind in
  params|table|kv|program}`` (``table`` = embedding-table rows, split
  out by :func:`split_param_shard_bytes`)
  with a process high-watermark, published as ``memory.*`` gauges and
  exported per-``{model,kind}`` as labeled series by the fleet scraper;
- ``memory.pressure`` events emitted when the registry LRU evicts a
  warm model (they land in the flight recorder, so an OOM post-mortem
  shows WHO was evicted to make room);
- :func:`audit_device_bytes` — an optional ``jax.live_arrays()`` sweep
  that compares actually-live device bytes against the ledger and flags
  the unaccounted remainder (leaked intermediates, untracked caches).

``program`` bytes are the serialized executable size reported by the
persistent compile cache — a proxy for the program's HBM footprint,
known only when ``runtime.compile_cache_dir`` is active (in-memory
bypass compiles are not charged).
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.utils import config as mmlconfig

KINDS = ("params", "table", "kv", "program")

# embedding-table leaves follow the SAME naming convention the sharding
# rules key on (parallel/sharding.py's ``.*embedding$``): a param path
# ending in "embedding" is table rows, everything else is dense weights
_TABLE_LEAF = re.compile(r".*embedding$")


def nbytes_of(shape: Sequence[int], dtype: Any) -> int:
    """Bytes of one dense array of ``shape``/``dtype`` — THE size
    arithmetic everything in serve/ delegates to."""
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def param_bytes(params: Any) -> int:
    """Summed bytes of every array leaf in a param tree (0 for None)."""
    if params is None:
        return 0
    import jax
    return sum(nbytes_of(l.shape, l.dtype)
               for l in jax.tree_util.tree_leaves(params)
               if hasattr(l, "shape") and hasattr(l, "dtype"))


def shard_bytes_of(leaf: Any) -> int:
    """Bytes of one array AS RESIDENT ON ONE DEVICE: the per-shard size
    for mesh-sharded arrays, the full size otherwise. This is what the
    HBM ledger charges for sharded models — a 2x-tensor-sharded kernel
    costs each chip half its logical bytes."""
    if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
        return 0
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        try:
            return nbytes_of(sharding.shard_shape(tuple(leaf.shape)),
                             leaf.dtype)
        except (TypeError, ValueError):
            pass  # abstract/odd leaves fall back to full logical bytes
    return nbytes_of(leaf.shape, leaf.dtype)


def param_shard_bytes(params: Any) -> int:
    """Per-device resident bytes of a param tree: sum of each leaf's
    :func:`shard_bytes_of`. Equal to :func:`param_bytes` for unsharded
    trees, strictly smaller once the model axis splits kernels."""
    if params is None:
        return 0
    import jax
    return sum(shard_bytes_of(l)
               for l in jax.tree_util.tree_leaves(params))


def projected_shard_bytes(params: Any, mesh: Any = None,
                          rules: Any = None) -> int:
    """Per-device bytes a HOST param tree WOULD pin once placed on
    ``mesh`` under :func:`~mmlspark_tpu.parallel.sharding.param_shardings`
    — computed from shapes alone, with NOTHING materialized on device.
    ``mesh=None`` means the single-device path (full logical bytes). The
    registry's ``replace`` pre-check uses this to refuse a placement that
    cannot fit the ``runtime.device_cache_mb`` budget BEFORE it drops the
    entry it would displace."""
    if params is None:
        return 0
    if mesh is None:
        return param_bytes(params)
    import jax
    from mmlspark_tpu.parallel.sharding import param_shardings
    shardings = param_shardings(params, mesh, rules)
    total = 0
    for leaf, sh in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(shardings)):
        leaf = np.asarray(leaf) if not hasattr(leaf, "shape") else leaf
        try:
            total += nbytes_of(sh.shard_shape(tuple(leaf.shape)),
                               leaf.dtype)
        except (TypeError, ValueError):
            total += nbytes_of(leaf.shape, leaf.dtype)
    return total


def split_param_shard_bytes(params: Any) -> Tuple[int, int]:
    """Per-device resident bytes of a param tree SPLIT into
    ``(dense_bytes, table_bytes)``: leaves whose '/'-joined path matches
    the ``.*embedding$`` convention are embedding-table rows (charged to
    the ledger as ``kind="table"`` — the component that scales with the
    business, not the architecture), everything else is dense weights
    (``kind="params"``). The two always sum to
    :func:`param_shard_bytes`."""
    if params is None:
        return 0, 0
    import jax
    from mmlspark_tpu.parallel.sharding import _path_str
    dense = table = 0
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        b = shard_bytes_of(leaf)
        if _TABLE_LEAF.match(_path_str(path)):
            table += b
        else:
            dense += b
    return dense, table


class MemoryLedger:
    """Process-wide bytes-by-``{model, kind}`` map with a high-watermark.

    ``params`` and ``kv`` are *set* (the registry re-syncs them after
    every warm/evict, so the ledger mirrors the current warm set);
    ``program`` entries are keyed by the compiled artifact's cache path
    so re-loading the same executable never double-charges.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._bytes: Dict[Tuple[str, str], int] = {}
        self._programs: Dict[str, Dict[str, int]] = {}
        self._hwm = 0

    # -- writes ------------------------------------------------------------
    def set_bytes(self, model: str, kind: str, nbytes: int) -> None:
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        with self._lock:
            if nbytes <= 0:
                self._bytes.pop((str(model), kind), None)
            else:
                self._bytes[(str(model), kind)] = int(nbytes)
        self._publish()

    def note_program(self, model: str, key: str, nbytes: int) -> None:
        """Charge one compiled program (idempotent per ``key``)."""
        with self._lock:
            progs = self._programs.setdefault(str(model), {})
            progs[str(key)] = int(nbytes)
            self._bytes[(str(model), "program")] = sum(progs.values())
        self._publish()

    def clear(self, model: Optional[str] = None,
              kind: Optional[str] = None) -> None:
        with self._lock:
            if model is None and kind is None:
                self._bytes.clear()
                self._programs.clear()
            else:
                for k in list(self._bytes):
                    if ((model is None or k[0] == str(model))
                            and (kind is None or k[1] == kind)):
                        del self._bytes[k]
                if kind in (None, "program"):
                    if model is None:
                        self._programs.clear()
                    else:
                        self._programs.pop(str(model), None)
        self._publish()

    def reset(self) -> None:
        with self._lock:
            self._bytes.clear()
            self._programs.clear()
            self._hwm = 0
        self._publish()

    # -- reads -------------------------------------------------------------
    def total(self, model: Optional[str] = None,
              kind: Optional[str] = None) -> int:
        with self._lock:
            return sum(v for k, v in self._bytes.items()
                       if (model is None or k[0] == str(model))
                       and (kind is None or k[1] == kind))

    @property
    def high_watermark(self) -> int:
        return self._hwm

    def snapshot(self) -> Dict[str, Any]:
        """``{total_bytes, high_watermark_bytes, by_kind, by_model}`` —
        the shape the scraper turns into labeled series and ``top``
        renders as the HBM panel."""
        with self._lock:
            by_model: Dict[str, Dict[str, int]] = {}
            by_kind = {k: 0 for k in KINDS}
            for (model, kind), v in sorted(self._bytes.items()):
                by_model.setdefault(model, {})[kind] = v
                by_kind[kind] += v
            total = sum(self._bytes.values())
            return {"total_bytes": total,
                    "high_watermark_bytes": self._hwm,
                    "by_kind": by_kind,
                    "by_model": by_model}

    # -- eviction pressure -------------------------------------------------
    def on_eviction(self, model: str, freed_bytes: int, *,
                    resident_bytes: int, budget_bytes: float,
                    reason: str = "lru") -> None:
        """Called by the registry LRU when it evicts a warm model: clear
        the victim's ledger lines and emit a ``memory.pressure`` event
        (flight-recorder visible) plus a counter."""
        self.clear(model)
        metrics.counter("memory.pressure").inc()
        if events.recording_enabled():
            events.emit("memory", "pressure", model=str(model),
                        reason=reason, freed_bytes=int(freed_bytes),
                        resident_bytes=int(resident_bytes),
                        budget_bytes=float(budget_bytes))

    # -- internal ----------------------------------------------------------
    def _publish(self) -> None:
        with self._lock:
            by_kind = {k: 0 for k in KINDS}
            for (_, kind), v in self._bytes.items():
                by_kind[kind] += v
            total = sum(self._bytes.values())
            if total > self._hwm:
                self._hwm = total
            hwm = self._hwm
        metrics.gauge("memory.hbm_bytes").set(total)
        metrics.gauge("memory.hbm_high_watermark_bytes").set(hwm)
        for kind, v in by_kind.items():
            metrics.gauge(f"memory.bytes.{kind}").set(v)


_LEDGER = MemoryLedger()


def get_ledger() -> MemoryLedger:
    """The process-wide ledger every accounting site reports into."""
    return _LEDGER


def audit_device_bytes(ledger: Optional[MemoryLedger] = None
                       ) -> Dict[str, Any]:
    """Compare actually-live device bytes (``jax.live_arrays()``) against
    the ledger. ``unaccounted_bytes`` > 0 means device memory the ledger
    does not know about (leaked intermediates, untracked caches); the
    result is advisory — committed-vs-live can legitimately diverge
    (donated buffers, as-yet-uncollected garbage).

    Live arrays are counted at PER-SHARD bytes (``shard_bytes_of``, via
    the sharding's ``shard_shape``), matching how the ledger charges
    sharded residents — a row-sharded embedding table counts one chip's
    rows, not the logical total, so sharded models don't show up as
    phantom "unaccounted" bytes."""
    ledger = ledger or get_ledger()
    accounted = ledger.total()
    try:
        import jax
        arrs = jax.live_arrays()
        live = sum(shard_bytes_of(a) for a in arrs)
        arrays = len(arrs)
    except Exception as e:  # platforms without live_arrays support
        return {"supported": False, "error": f"{type(e).__name__}: {e}",
                "accounted_bytes": accounted}
    unaccounted = max(0, live - accounted)
    out = {"supported": True, "live_bytes": live, "live_arrays": arrays,
           "accounted_bytes": accounted, "unaccounted_bytes": unaccounted}
    metrics.gauge("memory.unaccounted_bytes").set(unaccounted)
    if events.recording_enabled():
        events.emit("memory", "audit", **out)
    return out


_POLLER: Dict[str, Any] = {"thread": None, "stop": None}


def start_audit_poller(interval_s: Optional[float] = None) -> bool:
    """Run :func:`audit_device_bytes` on a daemon thread every
    ``observability.memory_poll_s`` seconds (<= 0 = disabled, no thread).
    Idempotent; returns True when a poller is running."""
    interval = float(interval_s if interval_s is not None
                     else mmlconfig.get("observability.memory_poll_s"))
    if _POLLER["thread"] is not None:
        return True
    if interval <= 0:
        return False
    stop = threading.Event()

    def loop() -> None:
        while not stop.wait(interval):
            audit_device_bytes()

    t = threading.Thread(target=loop, name="mmlspark-tpu-memaudit",
                         daemon=True)
    _POLLER["thread"], _POLLER["stop"] = t, stop
    t.start()
    return True


def stop_audit_poller() -> None:
    t, stop = _POLLER["thread"], _POLLER["stop"]
    if t is None:
        return
    stop.set()
    t.join(timeout=5.0)
    _POLLER["thread"] = _POLLER["stop"] = None
