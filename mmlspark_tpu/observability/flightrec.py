"""Flight recorder: a bounded in-memory ring of the last N events.

The post-mortem half of the event log. The JSONL log
(``observability.events_path``) is opt-in and append-everything; the
flight recorder is ON by default (``observability.flight_recorder_size``,
0 disables) and keeps only the most recent events in memory — no I/O, no
growth — so when something goes wrong in a run that never configured an
events path, there is still a timeline to dump:

- the watchdog dumps it when a heartbeat stalls (``watchdog.stall``);
- the chaos harness dumps it next to a red verdict;
- the CLI dumps it on an unhandled crash.

:func:`record` is called by :func:`events.emit` for every event it sees
(the ring stores the event dict as-is; JSON serialization happens only at
:func:`dump` time), so anything the event log would have captured is in
the ring — including the incident event itself, which is why a dump is
never empty when recording is on.

Dumps are JSONL (same schema as the event log — ``mmlspark-tpu report``
and ``--trace`` read them directly) prefixed with one ``flightrec.dump``
header line carrying the reason and ring stats. Default dump location:
next to the configured events path when set, else the working directory.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from mmlspark_tpu.utils import config

_lock = threading.Lock()
_ring: "deque[Dict[str, Any]]" = deque(maxlen=256)
_ring_size = 256          # maxlen the deque was built with
_dropped = 0              # events evicted from the ring (lifetime)
_seq = itertools.count(1)  # dump-file uniquifier within one process


def size() -> int:
    """Configured ring capacity (0 = recorder off)."""
    try:
        return int(config.get("observability.flight_recorder_size"))
    except (TypeError, ValueError):
        return 0


def active() -> bool:
    """Is the recorder capturing? One cheap check for ``events.emit``."""
    return size() > 0


def record(event: Dict[str, Any]) -> None:
    """Append one event dict to the ring (no copy, no serialization —
    callers hand over a fresh dict they will not mutate)."""
    global _ring, _ring_size, _dropped
    n = size()
    if n <= 0:
        return
    with _lock:
        if n != _ring_size:
            # capacity changed under config: keep the newest events
            _ring = deque(_ring, maxlen=n)
            _ring_size = n
        if len(_ring) == _ring.maxlen:
            _dropped += 1
        _ring.append(event)


def snapshot() -> List[Dict[str, Any]]:
    """The ring's current contents, oldest first."""
    with _lock:
        return list(_ring)


def clear() -> None:
    """Empty the ring (tests / between scenarios)."""
    global _dropped
    with _lock:
        _ring.clear()
        _dropped = 0


def default_dump_path(reason: str = "incident") -> str:
    """``flightrec-<pid>-<n>.jsonl`` next to the events log when one is
    configured, else in the working directory."""
    events_path = str(config.get("observability.events_path") or "")
    parent = os.path.dirname(os.path.abspath(events_path)) if events_path \
        else os.getcwd()
    return os.path.join(parent,
                        f"flightrec-{os.getpid()}-{next(_seq)}.jsonl")


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Write the ring to ``path`` (JSONL, header line first) and return
    the path, or None when the recorder is off or has captured nothing.
    Never raises — a failed dump must not mask the incident being dumped.
    """
    events = snapshot()
    if not events:
        return None
    if path is None:
        path = default_dump_path(reason)
    # lazy import: events.py imports this module at load time
    from mmlspark_tpu.observability import events as _events
    header = {"ts": round(_events.wall(), 6),
              "type": "event", "name": "flightrec.dump", "reason": reason,
              "events": len(events), "dropped": _dropped,
              "capacity": _ring_size, "pid": os.getpid()}
    try:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(header, sort_keys=True, default=str) + "\n")
            for e in events:
                f.write(json.dumps(e, sort_keys=True, default=str) + "\n")
    except OSError:
        return None
    return path
