"""Arrival-time-truth serving measurement: goodput under deadline.

The companion to :mod:`mmlspark_tpu.testing.loadgen` — the generator
decides WHEN every request should arrive; this module decides what the
system's answer was worth. The rules that make the numbers honest:

- **Latency is measured from the INTENDED arrival time**, never from a
  throttled send or a retry's re-enqueue. A client that couldn't send
  because the system was wedged is exactly the sample a closed-loop
  driver omits (coordinated omission); here it shows up as queueing
  delay, because the request's clock started when it was supposed to.
- **Goodput** is the fraction of OFFERED requests answered within the
  deadline. Shed, expired, and deadline-busting completions all count
  against it — a system that sheds its way to a pretty p99 has low
  goodput, not low latency.
- **Percentiles are over completions only** and explicitly UN-clipped:
  a completion may exceed the deadline by any amount and is recorded at
  its real value. The shed/expired mass is reported beside them, never
  folded into the percentile (that would either clip at the deadline —
  the blind spot this replaces — or invent latencies for requests that
  never finished).
- **Time-bucketed series**: per-bucket offered/delivered counts and
  arrival-to-response p99 with the worst request's trace_id as an
  exemplar, so "the p99 was bad" comes with WHEN and WHICH.

Results export through the existing events/metrics registry
(:meth:`GoodputMeter.export`) so ``mmlspark-tpu report`` and ``top``
render the workload section without new plumbing.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.observability import events
from mmlspark_tpu.observability import metrics as _metrics
from mmlspark_tpu.observability.metrics import nearest_rank


class GoodputMeter:
    """Offered / delivered / shed / expired accounting over one run.

    Feed it intended arrival times (:meth:`offer`) and outcomes
    (:meth:`complete` / :meth:`shed` / :meth:`expire`), all on ONE clock
    (wall or virtual — the meter never reads a clock itself);
    :meth:`result` folds them into the workload verdict."""

    def __init__(self, *, deadline_s: float, bucket_s: float = 30.0):
        if deadline_s <= 0 or bucket_s <= 0:
            raise ValueError("deadline_s and bucket_s must be positive")
        self.deadline_s = float(deadline_s)
        self.bucket_s = float(bucket_s)
        self._arrivals: Dict[str, float] = {}
        self._done: List[Tuple[str, float, float]] = []   # id, t_arr, t_done
        self._shed: List[Tuple[str, float]] = []          # id, t_arr
        self._expired: List[Tuple[str, float]] = []

    # -- recording ---------------------------------------------------------
    def offer(self, trace_id: str, t: float) -> None:
        """Request ``trace_id`` was INTENDED to arrive at ``t``."""
        self._arrivals[trace_id] = float(t)

    def _arrival_of(self, trace_id: str) -> float:
        try:
            return self._arrivals[trace_id]
        except KeyError:
            raise KeyError(f"complete/shed/expire before offer: "
                           f"{trace_id!r}") from None

    def complete(self, trace_id: str, t: float) -> float:
        """Request answered at ``t``; returns its arrival-to-response
        latency in seconds (from the intended arrival, not any send)."""
        t_arr = self._arrival_of(trace_id)
        self._done.append((trace_id, t_arr, float(t)))
        return float(t) - t_arr

    def shed(self, trace_id: str) -> None:
        self._shed.append((trace_id, self._arrival_of(trace_id)))

    def expire(self, trace_id: str) -> None:
        self._expired.append((trace_id, self._arrival_of(trace_id)))

    # -- the verdict -------------------------------------------------------
    def result(self) -> Dict[str, Any]:
        offered = len(self._arrivals)
        delivered = len(self._done)
        shed = len(self._shed)
        expired = len(self._expired)
        lats_ms = sorted((td - ta) * 1e3 for _, ta, td in self._done)
        within = sum(1 for v in lats_ms if v <= self.deadline_s * 1e3)
        times = list(self._arrivals.values())
        t0 = min(times) if times else 0.0
        t_end = max([t0] + [td for _, _, td in self._done]
                    + [ta for ta in times])
        span = max(t_end - t0, 1e-9)
        res: Dict[str, Any] = {
            "offered": offered, "delivered": delivered,
            "shed": shed, "expired": expired,
            "unresolved": offered - delivered - shed - expired,
            "deadline_ms": self.deadline_s * 1e3,
            "goodput": round(within / offered, 4) if offered else 0.0,
            "offered_qps": round(offered / span, 4),
            "delivered_qps": round(delivered / span, 4),
            "arrival_p50_ms": round(nearest_rank(lats_ms, 50), 3),
            "arrival_p99_ms": round(nearest_rank(lats_ms, 99), 3),
            "arrival_max_ms": round(lats_ms[-1], 3) if lats_ms else 0.0,
        }
        res["buckets"] = self._buckets(t0)
        worst = max(res["buckets"], key=lambda b: b["p99_ms"], default=None)
        if worst is not None:
            res["worst_bucket"] = worst
        return res

    def _buckets(self, t0: float) -> List[Dict[str, Any]]:
        by_bucket: Dict[int, Dict[str, Any]] = {}

        def slot(t_arr: float) -> Dict[str, Any]:
            i = int((t_arr - t0) / self.bucket_s)
            return by_bucket.setdefault(i, {
                "t0": t0 + i * self.bucket_s, "offered": 0,
                "delivered": 0, "shed": 0, "lats": [], "worst": None})

        for t_arr in self._arrivals.values():
            slot(t_arr)["offered"] += 1
        for trace_id, t_arr, t_done in self._done:
            b = slot(t_arr)
            b["delivered"] += 1
            lat = (t_done - t_arr) * 1e3
            b["lats"].append(lat)
            if b["worst"] is None or lat > b["worst"][1]:
                b["worst"] = (trace_id, lat)
        for _, t_arr in self._shed + self._expired:
            slot(t_arr)["shed"] += 1
        out = []
        for i in sorted(by_bucket):
            b = by_bucket[i]
            lats = sorted(b.pop("lats"))
            worst = b.pop("worst")
            b["p99_ms"] = round(nearest_rank(lats, 99), 3)
            if worst is not None:
                b["trace_id"] = worst[0]
            out.append(b)
        return out

    # -- export ------------------------------------------------------------
    def export(self, *, lane: str = "") -> Dict[str, Any]:
        """Push the verdict into the event log (``workload.summary``) and
        the metrics registry (``workload.*`` gauges) so ``report`` and
        ``top`` render it; returns the verdict dict."""
        res = self.result()
        if events.recording_enabled():
            fields = {k: v for k, v in res.items() if k != "buckets"}
            events.emit("workload", "summary", lane=lane, **fields)
        if _metrics.metrics_enabled():
            for key in ("offered", "delivered", "shed", "expired",
                        "goodput", "offered_qps", "delivered_qps",
                        "arrival_p99_ms", "deadline_ms"):
                _metrics.gauge(f"workload.{key}").set(float(res[key]))
            worst = res.get("worst_bucket")
            if worst:
                _metrics.gauge("workload.worst_bucket_p99_ms").set(
                    float(worst["p99_ms"]))
        return res
