"""Declarative SLOs with multi-window burn-rate alerting.

Objectives come from the ``slo.*`` config namespace and are evaluated
against the aggregated fleet view (:meth:`FleetScraper.slo_sample`):

- **availability** — ``1 - bad/admitted`` against
  ``slo.availability_target`` (``bad`` = shed + expired + failed +
  router failovers: every request the fleet did not serve first-try);
- **latency** — "99% of requests complete within ``slo.latency_p99_ms``"
  (0 = objective off), measured from the merged total-latency buckets;
- **ttft** — same shape for the generate lane's time-to-first-token
  against ``slo.ttft_p99_ms``.

Alerting is the standard SRE-workbook multi-window recipe: the burn
rate (bad fraction over the window, divided by the error budget) is
computed over a FAST window (``slo.fast_window_s``, default 5m — pages
fast on a cliff) and a SLOW window (``slo.slow_window_s``, default 1h —
filters blips). ``burning`` = fast burn over ``slo.fast_burn``;
``breaching`` = BOTH windows over their thresholds. Transitions are
edge-triggered events — ``slo.burn`` / ``slo.breach`` / ``slo.recover``
— which land in the event log AND the flight recorder, so a post-mortem
dump shows exactly when the budget started burning.

The engine is pure arithmetic over (clock, cumulative-counter) samples:
inject ``clock`` and feed :meth:`SloEngine.observe` synthetic samples to
test window behavior deterministically.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.utils import config as mmlconfig

_THRESHOLD_PCT = 99.0  # latency/ttft objectives are "99% under budget"


def fraction_le(cumulative: Dict[str, float], x: float) -> float:
    """Interpolated fraction of observations <= ``x`` from a cumulative
    ``{le: count}`` mapping (1.0 for an empty histogram — no traffic
    means no budget burned)."""
    finite: List[tuple] = []
    total = 0.0
    for le, c in cumulative.items():
        if isinstance(le, str) and le.strip().lstrip("+") in ("Inf", "inf"):
            total = float(c)
        else:
            finite.append((float(le), float(c)))
    finite.sort()
    if total <= 0:
        total = finite[-1][1] if finite else 0.0
    if total <= 0:
        return 1.0
    prev_b, prev_c = 0.0, 0.0
    for b, c in finite:
        if x <= b:
            span = b - prev_b
            frac = (x - prev_b) / span if span > 0 else 1.0
            return (prev_c + (c - prev_c) * max(0.0, min(1.0, frac))) / total
        prev_b, prev_c = b, c
    return (finite[-1][1] if finite else total) / total


class Objective:
    """One declarative objective: a name, a target fraction of good
    events, and how to extract (good, bad) totals from a sample."""

    def __init__(self, name: str, kind: str, target: float,
                 budget_ms: float = 0.0):
        if not 0.0 < target < 1.0:
            raise ValueError(f"slo {name}: target must be in (0, 1), "
                             f"got {target}")
        self.name = name
        self.kind = kind          # availability | latency | ttft
        self.target = float(target)
        self.budget_ms = float(budget_ms)

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target

    def totals(self, sample: Dict[str, Any]) -> Optional[tuple]:
        """Cumulative ``(events, bad)`` as of this sample, or None when
        the sample does not carry this objective's inputs."""
        if self.kind == "availability":
            admitted = float(sample.get("admitted", 0.0))
            return admitted, float(sample.get("bad", 0.0))
        key = "latency_buckets" if self.kind == "latency" else "ttft_buckets"
        buckets = sample.get(key)
        if buckets is None:
            return None
        total = 0.0
        for le, c in buckets.items():
            total = max(total, float(c))
        good = fraction_le(buckets, self.budget_ms) * total
        return total, total - good


def objectives_from_config() -> List[Objective]:
    """The active objective set per ``slo.*`` (latency/ttft join only
    when their budget keys are > 0)."""
    out = [Objective("availability", "availability",
                     float(mmlconfig.get("slo.availability_target")))]
    lat = float(mmlconfig.get("slo.latency_p99_ms"))
    if lat > 0:
        out.append(Objective("latency_p99", "latency",
                             _THRESHOLD_PCT / 100.0, budget_ms=lat))
    ttft = float(mmlconfig.get("slo.ttft_p99_ms"))
    if ttft > 0:
        out.append(Objective("ttft_p99", "ttft",
                             _THRESHOLD_PCT / 100.0, budget_ms=ttft))
    return out


class SloEngine:
    """Rolling-window burn-rate evaluation over scrape samples.

    Feed :meth:`observe` one :meth:`FleetScraper.slo_sample` per scrape;
    each call re-evaluates every objective over the fast and slow
    windows and returns the per-objective status list (also kept on
    :meth:`status`). Counter resets (a replica restart shrinking the
    cumulative totals) drop the affected history rather than computing
    negative deltas.
    """

    def __init__(self, objectives: Optional[List[Objective]] = None, *,
                 clock: Optional[Callable[[], float]] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 fast_burn: Optional[float] = None,
                 slow_burn: Optional[float] = None):
        self.objectives = objectives if objectives is not None \
            else objectives_from_config()
        self.clock = clock or events.wall
        self.fast_window_s = float(
            fast_window_s if fast_window_s is not None
            else mmlconfig.get("slo.fast_window_s"))
        self.slow_window_s = float(
            slow_window_s if slow_window_s is not None
            else mmlconfig.get("slo.slow_window_s"))
        self.fast_burn = float(fast_burn if fast_burn is not None
                               else mmlconfig.get("slo.fast_burn"))
        self.slow_burn = float(slow_burn if slow_burn is not None
                               else mmlconfig.get("slo.slow_burn"))
        # per-objective history: [(t, total_events, bad_events), ...]
        self._history: Dict[str, List[tuple]] = {
            o.name: [] for o in self.objectives}
        self._burning: Dict[str, bool] = {}
        self._breaching: Dict[str, bool] = {}
        self._status: List[Dict[str, Any]] = []

    # -- windows -----------------------------------------------------------
    def _window_burn(self, obj: Objective, hist: List[tuple],
                     now: float, window_s: float) -> float:
        """Burn rate over ``[now - window_s, now]``: bad fraction of the
        events in the window, divided by the error budget. No events in
        the window = no burn."""
        if not hist:
            return 0.0
        cur = hist[-1]
        cutoff = now - window_s
        # reference = last sample at-or-before the window start (so the
        # delta covers the whole window), else the oldest we have
        ref = hist[0]
        for s in hist:
            if s[0] <= cutoff:
                ref = s
            else:
                break
        d_events = cur[1] - ref[1]
        d_bad = cur[2] - ref[2]
        if d_events <= 0:
            return 0.0
        bad_fraction = max(0.0, min(1.0, d_bad / d_events))
        return bad_fraction / max(obj.error_budget, 1e-9)

    # -- the step ----------------------------------------------------------
    def observe(self, sample: Dict[str, Any]) -> List[Dict[str, Any]]:
        now = float(sample.get("t", self.clock()))
        status: List[Dict[str, Any]] = []
        keep_after = now - self.slow_window_s * 1.5
        for obj in self.objectives:
            totals = obj.totals(sample)
            hist = self._history[obj.name]
            if totals is not None:
                if hist and (totals[0] < hist[-1][1]
                             or totals[1] < hist[-1][2]):
                    hist.clear()  # counter reset (replica restart)
                hist.append((now, float(totals[0]), float(totals[1])))
                while len(hist) > 2 and hist[1][0] <= keep_after:
                    hist.pop(0)
            fast = self._window_burn(obj, hist, now, self.fast_window_s)
            slow = self._window_burn(obj, hist, now, self.slow_window_s)
            burning = fast >= self.fast_burn
            breaching = burning and slow >= self.slow_burn
            st = {"objective": obj.name, "kind": obj.kind,
                  "target": obj.target, "budget_ms": obj.budget_ms,
                  "burn_fast": round(fast, 4), "burn_slow": round(slow, 4),
                  "burning": burning, "breaching": breaching}
            self._emit_transitions(obj, st)
            metrics.gauge(f"slo.burn_fast.{obj.name}").set(fast)
            metrics.gauge(f"slo.burn_slow.{obj.name}").set(slow)
            status.append(st)
        self._status = status
        return status

    def _emit_transitions(self, obj: Objective,
                          st: Dict[str, Any]) -> None:
        """Edge-triggered slo.burn / slo.breach / slo.recover events —
        they go through events.emit, so an active flight recorder keeps
        them for the post-mortem dump."""
        was_burning = self._burning.get(obj.name, False)
        was_breaching = self._breaching.get(obj.name, False)
        self._burning[obj.name] = st["burning"]
        self._breaching[obj.name] = st["breaching"]
        log = events.recording_enabled()
        fields = {"objective": obj.name, "burn_fast": st["burn_fast"],
                  "burn_slow": st["burn_slow"], "target": obj.target}
        if st["burning"] and not was_burning:
            metrics.counter("slo.burns").inc()
            if log:
                events.emit("slo", "burn", **fields)
        if st["breaching"] and not was_breaching:
            metrics.counter("slo.breaches").inc()
            if log:
                events.emit("slo", "breach", **fields)
        if was_breaching and not st["breaching"] and log:
            events.emit("slo", "recover", **fields)

    def status(self) -> List[Dict[str, Any]]:
        """Most recent per-objective evaluation (empty before the first
        observe)."""
        return list(self._status)
