"""``mmlspark-tpu top``: the operator's one-glance fleet view.

A live terminal dashboard over :class:`FleetScraper` + :class:`SloEngine`
— per-replica ready/draining, queue depth, QPS, p50/p99, shed rate, SLO
burn, HBM occupancy, the open-loop workload line when one is live
(offered vs delivered QPS, goodput under deadline, un-clipped
arrival-time p99 — a GoodputMeter passed as ``workload=`` or scraped
``workload.*`` gauges), and (when a generate lane is live) the decode line:
prefix-cache hit rate, CoW copies, speculation acceptance, int8 arena
savings — for watching a ``Fleet.rollout`` or a chaos run in real time. Deliberately curses-free: each frame is a plain string and
the live loop just re-homes the cursor with ANSI ``ESC[H ESC[J`` before
printing, so it works over ssh, inside tmux, and in CI logs alike.
``--once`` (the :meth:`TopDashboard.run` ``once`` flag) prints a single
frame and exits — the form tests and scripts use. Clock and output
stream are injectable.

Rates (QPS, shed rate) are derived from the delta between consecutive
scrapes, so the first frame shows totals only.
"""
from __future__ import annotations

import sys
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from mmlspark_tpu.observability import events
from mmlspark_tpu.observability.aggregate import FleetScraper
from mmlspark_tpu.observability.slo import SloEngine

_CLEAR = "\x1b[H\x1b[J"


def format_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1000.0 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1000.0
    return f"{n:.1f}GB"  # pragma: no cover - loop always returns


def _rate(cur: float, prev: Optional[float], dt: float) -> Optional[float]:
    if prev is None or dt <= 0 or cur < prev:
        return None
    return (cur - prev) / dt


class TopDashboard:
    """Render loop over one scraper (and optionally one SLO engine).

    ``tick()`` = one scrape -> one SLO evaluation -> one frame string;
    ``run(once=True)`` prints a single frame, ``run()`` redraws every
    ``interval_s`` until ``stop()`` / KeyboardInterrupt.
    """

    def __init__(self, scraper: FleetScraper,
                 engine: Optional[SloEngine] = None, *,
                 autopilot=None, supervisor=None, workload=None,
                 clock: Optional[Callable[[], float]] = None,
                 out=None, interval_s: float = 2.0):
        self.scraper = scraper
        self.engine = engine
        # anything with an Autopilot-shaped stats() dict; the panel shows
        # the live decision stream next to the signals that drive it
        self.autopilot = autopilot
        # anything with a Supervisor-shaped stats() dict; the panel shows
        # desired vs live plus the elasticity in flight
        self.supervisor = supervisor
        # anything with a GoodputMeter-shaped result() dict (an open-loop
        # driver in this process); without one, the line falls back to
        # scraped ``workload.*`` gauges when a replica exports them
        self.workload = workload
        self.clock = clock or events.wall
        self.out = out if out is not None else sys.stdout
        self.interval_s = float(interval_s)
        self._prev: Optional[Dict[str, Any]] = None
        self._prev_t: Optional[float] = None
        self._stop = threading.Event()

    # -- one frame ---------------------------------------------------------
    def tick(self) -> str:
        snap = self.scraper.scrape()
        status = None
        if self.engine is not None:
            status = self.engine.observe(self.scraper.slo_sample(snap))
        frame = self.render(snap, status)
        self._prev = snap
        self._prev_t = float(snap["ts"])
        return frame

    def render(self, snap: Dict[str, Any],
               slo_status: Optional[List[Dict[str, Any]]] = None) -> str:
        now = float(snap["ts"])
        dt = (now - self._prev_t) if self._prev_t is not None else 0.0
        prev_fleet = (self._prev or {}).get("fleet", {})
        fleet = snap.get("fleet", {})
        reps = snap.get("replicas", {})
        ready = sum(1 for r in reps.values() if r.get("ready"))
        lines = [
            f"mmlspark-tpu top  t={now:.1f}  replicas {ready}/{len(reps)} "
            f"ready  scrape {snap.get('scrape_ms', 0.0):.1f}ms"]

        qps = _rate(fleet.get("admitted", 0.0),
                    prev_fleet.get("admitted"), dt)
        shed_rate = _rate(fleet.get("shed", 0.0), prev_fleet.get("shed"), dt)
        parts = [f"admitted {fleet.get('admitted', 0.0):.0f}",
                 f"shed {fleet.get('shed', 0.0):.0f}",
                 f"expired {fleet.get('expired', 0.0):.0f}",
                 f"failovers {fleet.get('failovers', 0.0):.0f}",
                 f"p50 {fleet.get('p50_ms', 0.0):.1f}ms",
                 f"p99 {fleet.get('p99_ms', 0.0):.1f}ms"]
        if qps is not None:
            parts.insert(0, f"qps {qps:.1f}")
        if shed_rate is not None:
            parts.append(f"shed/s {shed_rate:.1f}")
        lines.append("fleet    " + "  ".join(parts))

        # open-loop workload truth: offered vs delivered and the
        # un-clipped arrival-time p99 against the deadline — from a live
        # GoodputMeter when the driver runs in-process, else from the
        # ``workload.*`` gauges a replica exported
        wl: Optional[Dict[str, Any]] = None
        if self.workload is not None:
            wl = self.workload.result()
        elif any(k.startswith("workload.") for k in fleet):
            wl = {k.split(".", 1)[1]: v for k, v in fleet.items()
                  if k.startswith("workload.")}
        if wl:
            parts = [
                f"offered {float(wl.get('offered', 0)):.0f}",
                f"delivered {float(wl.get('delivered', 0)):.0f}",
                f"goodput {100.0 * float(wl.get('goodput', 0.0)):.1f}%",
                f"arrival p99 {float(wl.get('arrival_p99_ms', 0.0)):.1f}ms"
                f" (deadline {float(wl.get('deadline_ms', 0.0)):.0f}ms)"]
            shed_n = float(wl.get("shed", 0))
            exp_n = float(wl.get("expired", 0))
            if shed_n or exp_n:
                parts.append(f"shed {shed_n:.0f}  expired {exp_n:.0f}")
            lines.append("workload " + "  ".join(parts))

        # generative decode lane: fleet totals hold summed
        # ``generate.<model>.<key>`` stats; match on exact key depth so
        # the lane's prefix_hits is not conflated with kv.prefix_hits
        def _gsum(*tail: str) -> float:
            want = list(tail)
            return sum(float(v) for k, v in fleet.items()
                       if isinstance(v, (int, float))
                       and k.split(".")[:1] == ["generate"]
                       and k.split(".")[2:] == want)

        if any(k.startswith("generate.") for k in fleet):
            hits, misses = _gsum("prefix_hits"), _gsum("prefix_misses")
            prop, acc = _gsum("spec_proposed"), _gsum("spec_accepted")
            saved = (_gsum("kv", "unquantized_arena_bytes")
                     - _gsum("kv", "arena_bytes"))
            parts = [
                f"prefix {100.0 * hits / max(1.0, hits + misses):.1f}%",
                f"cow {_gsum('cow_copies'):.0f}",
                f"spec {100.0 * acc / prop:.1f}%" if prop else "spec -"]
            if _gsum("kv", "quantized"):
                parts.append(f"int8 saved {format_bytes(max(0.0, saved))}")
            lines.append("decode   " + "  ".join(parts))

        # prefix-affinity routing: how the router split traffic (prefix /
        # session / plain WRR) and what each replica is advertising — the
        # live view of "N replicas, one cache"
        aff = snap.get("affinity")
        if aff and aff.get("routes"):
            parts = [
                f"routes {aff['routes']:.0f}",
                f"prefix {aff.get('routes_prefix', 0):.0f}",
                f"session {aff.get('routes_session', 0):.0f}",
                f"wrr {aff.get('routes_wrr', 0):.0f}",
                f"share {100.0 * aff.get('affinity_route_share', 0.0):.1f}%"]
            adv = aff.get("advertised") or []
            if adv:
                parts.append("adv " + ", ".join(
                    f"{d['replica']}:{d['max_depth']}" for d in adv[:6]))
            lines.append("affinity " + "  ".join(parts))

        for st in slo_status or []:
            flag = "BREACH" if st["breaching"] else (
                "burn" if st["burning"] else "ok")
            lines.append(
                f"slo      {st['objective']:<14} fast {st['burn_fast']:>7.2f}"
                f"  slow {st['burn_slow']:>7.2f}  [{flag}]")

        if self.autopilot is not None:
            ap = self.autopilot.stats()
            parts = [f"ticks {ap.get('ticks', 0)}",
                     f"actions {ap.get('actions', 0)}",
                     f"suppressed {ap.get('suppressed', 0)}"]
            if ap.get("errors"):
                parts.append(f"errors {ap['errors']}")
            recent = [d for d in ap.get("recent", ())
                      if not d.get("suppressed")][-3:]
            if recent:
                parts.append("last " + ", ".join(
                    d["action"] + (f"({d['target']})" if d.get("target")
                                   else "")
                    for d in recent))
            lines.append("autopilot " + "  ".join(parts))

        if self.supervisor is not None:
            sp = self.supervisor.stats()
            desired = sp.get("desired_replicas", 0)
            live = sp.get("live_replicas", 0)
            parts = [f"desired {desired}",
                     f"live {live}" + ("" if live == desired else " (!)")]
            if sp.get("spawns_in_flight"):
                parts.append(f"spawning {sp['spawns_in_flight']}")
            if sp.get("retiring"):
                parts.append(f"retiring {sp['retiring']}")
            h = sp.get("spawn_to_ready_ms", {})
            if h.get("count"):
                parts.append(f"spawn->ready p50 {h['p50']:.0f}ms "
                             f"p99 {h['p99']:.0f}ms")
            lines.append("workers  " + "  ".join(parts))

        mem = snap.get("memory", {})
        kinds = mem.get("by_kind", {})
        lines.append(
            "hbm      total " + format_bytes(mem.get("total_bytes", 0))
            + "  hwm " + format_bytes(mem.get("high_watermark_bytes", 0))
            + "".join(f"  {k} {format_bytes(v)}"
                      for k, v in sorted(kinds.items())))
        for model, mk in sorted(mem.get("by_model", {}).items()):
            lines.append(
                f"         {model}: "
                + "  ".join(f"{k} {format_bytes(v)}"
                            for k, v in sorted(mk.items())))

        name_w = max([10] + [len(n) + 2 for n in reps])
        header = (f"{'replica':<{name_w}}{'state':<10}{'ready':<7}{'queue':<7}"
                  f"{'inflight':<10}{'admitted':<10}{'shed':<7}"
                  f"{'p50ms':<9}{'p99ms':<9}{'breaker':<10}")
        lines.append(header)
        lines.append("-" * len(header))
        prev_reps = (self._prev or {}).get("replicas", {})
        for name, r in sorted(reps.items()):
            s = r.get("stats", {})
            prev_s = prev_reps.get(name, {}).get("stats", {})
            rqps = _rate(s.get("admitted", 0.0),
                         prev_s.get("admitted"), dt)
            admitted = (f"{rqps:.1f}/s" if rqps is not None
                        else f"{s.get('admitted', 0.0):.0f}")
            err = r.get("error")
            state = r.get("state", "?") if not err else err[:18]
            lines.append(
                f"{name:<{name_w}}{state:<10}"
                f"{'yes' if r.get('ready') else 'NO':<7}"
                f"{s.get('queue_depth', 0.0):<7.0f}"
                f"{s.get('inflight', 0.0):<10.0f}"
                f"{admitted:<10}"
                f"{s.get('shed', 0.0):<7.0f}"
                f"{s.get('p50_ms', 0.0):<9.2f}"
                f"{s.get('p99_ms', 0.0):<9.2f}"
                f"{r.get('breaker', '?'):<10}")
        return "\n".join(lines) + "\n"

    # -- loop --------------------------------------------------------------
    def run(self, once: bool = False,
            sleep: Optional[Callable[[float], None]] = None) -> None:
        """Print frames until stopped. ``once=True`` prints exactly one
        frame with no ANSI clear (CI/test friendly)."""
        if once:
            self.out.write(self.tick())
            self.out.flush()
            return
        sleep = sleep or _time.sleep
        try:
            while not self._stop.is_set():
                frame = self.tick()
                self.out.write(_CLEAR + frame)
                self.out.flush()
                sleep(self.interval_s)
        except KeyboardInterrupt:  # pragma: no cover - interactive only
            pass

    def stop(self) -> None:
        self._stop.set()
