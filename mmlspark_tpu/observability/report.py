"""Run reports: render a captured events.jsonl into a human summary.

The offline half of the telemetry loop (``mmlspark-tpu report
<events.jsonl>``): given the JSON-lines log a run produced under
``observability.events_path``, print where the time went —

- per-stage wall-time breakdown: spans aggregated by name (count, total,
  mean, share of the root spans' wall time);
- slowest individual spans (the long-tail view the aggregate hides);
- reliability activity: retry attempts, fault-site hits, checkpoint
  quarantines, by site;
- liveness: watchdog stalls (per heartbeat, longest silence),
  circuit-breaker transitions, preemption signals/drains, quarantined
  data-state sidecars;
- throughput: the ``train.fit`` / ``train.step`` summaries the trainer and
  MetricLogger emit (steps, rows, examples/sec), plus any bench results;
- serving: per-request SLO breakdown from the serve subsystem's
  ``serving.request`` events (p50/p99 total latency, mean queue/pad/compute
  split, batch occupancy) plus shed/expired counts and the shed rate;
- input pipeline: per-epoch item counts and wall time from the streaming
  ``data.epoch`` events (data/pipeline.py's ``Repeat`` stage).

Pure text in, text out — no jax, no framework state — so it runs anywhere
the log file can be copied to.
"""
from __future__ import annotations

import json
from collections import defaultdict
from typing import Any, Dict, List

from mmlspark_tpu.utils.logging import get_logger


def load_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSON-lines event log; malformed lines are counted and
    skipped (a crash mid-write may truncate the final line), not fatal."""
    events: List[Dict[str, Any]] = []
    bad = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
    if bad:
        get_logger("observability.report").warning(
            "%s: skipped %d malformed line(s)", path, bad)
    return events


def _pct(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile of an ascending list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def _mean(events: List[Dict[str, Any]], field: str) -> float:
    vals = [float(e.get(field, 0.0)) for e in events]
    return sum(vals) / len(vals) if vals else 0.0


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines.extend(fmt.format(*map(str, r)) for r in rows)
    return lines


def render_report(path: str, top: int = 10) -> str:
    """The full text report for one event log."""
    events = load_events(path)
    spans = [e for e in events if e.get("type") == "span"]
    plain = [e for e in events if e.get("type") == "event"]
    metrics = [e for e in events if e.get("type") == "metric"]

    out: List[str] = [f"run report: {path}",
                      f"{len(events)} events "
                      f"({len(spans)} spans, {len(metrics)} metrics)", ""]

    # -- per-stage wall time -------------------------------------------------
    if spans:
        agg: Dict[str, List[float]] = defaultdict(list)
        for s in spans:
            agg[s.get("name", "?")].append(float(s.get("dur_s", 0.0)))
        # run wall = sum of root spans; fall back to the span total when the
        # log has no roots (e.g. a filtered or partial capture)
        root_total = sum(float(s.get("dur_s", 0.0)) for s in spans
                         if not s.get("parent_id"))
        denom = root_total or sum(sum(v) for v in agg.values()) or 1.0
        rows = []
        for name, durs in sorted(agg.items(),
                                 key=lambda kv: -sum(kv[1]))[:top]:
            total = sum(durs)
            rows.append([name, len(durs), f"{total:.4f}",
                         f"{total / len(durs) * 1e3:.2f}",
                         f"{100.0 * total / denom:.1f}%"])
        out.append("per-stage wall time:")
        out.extend(_table(rows, ["span", "count", "total_s", "mean_ms",
                                 "share"]))
        out.append("")

        slow = sorted(spans, key=lambda s: -float(s.get("dur_s", 0.0)))[:top]
        rows = [[s.get("name", "?"), f"{float(s.get('dur_s', 0.0)):.4f}",
                 s.get("depth", 0), s.get("parent", "") or "-"]
                for s in slow]
        out.append("slowest spans:")
        out.extend(_table(rows, ["span", "dur_s", "depth", "parent"]))
        out.append("")

    # -- reliability ---------------------------------------------------------
    retries = [e for e in plain if e.get("name") == "retry.attempt"]
    faults = [e for e in plain if e.get("name") == "fault.hit"]
    quarantines = [e for e in plain
                   if e.get("name") == "checkpoint.quarantine"]
    if retries or faults or quarantines:
        out.append("reliability:")
        if retries:
            by_site: Dict[str, int] = defaultdict(int)
            for e in retries:
                by_site[e.get("policy", "?")] += 1
            detail = ", ".join(f"{k}={v}" for k, v in sorted(by_site.items()))
            out.append(f"  retry attempts: {len(retries)} ({detail})")
        if faults:
            by_site = defaultdict(int)
            for e in faults:
                by_site[e.get("site", "?")] += 1
            detail = ", ".join(f"{k}={v}" for k, v in sorted(by_site.items()))
            out.append(f"  fault hits: {len(faults)} ({detail})")
        if quarantines:
            steps = [e.get("step") for e in quarantines]
            out.append(f"  checkpoint quarantines: {len(quarantines)} "
                       f"(steps {steps})")
        out.append("")

    # -- liveness ------------------------------------------------------------
    stalls = [e for e in plain if e.get("name") == "watchdog.stall"]
    trips = [e for e in plain
             if str(e.get("name", "")).startswith("breaker.")]
    preempts = [e for e in plain if e.get("name") == "preemption.signal"]
    drains = [e for e in plain if e.get("name") == "preemption.drain"]
    ds_quar = [e for e in plain
               if e.get("name") == "checkpoint.data_state_quarantine"]
    if stalls or trips or preempts or drains or ds_quar:
        out.append("liveness:")
        if stalls:
            by_hb: Dict[str, int] = defaultdict(int)
            for e in stalls:
                by_hb[e.get("heartbeat", "?")] += 1
            detail = ", ".join(f"{k}={v}" for k, v in sorted(by_hb.items()))
            worst = max(float(e.get("stalled_s", 0.0)) for e in stalls)
            out.append(f"  watchdog stalls: {len(stalls)} ({detail}); "
                       f"longest {worst:.1f}s (stacks in the event log)")
        if trips:
            by_key: Dict[str, List[str]] = defaultdict(list)
            for e in trips:
                by_key[e.get("key", "?")].append(
                    str(e.get("name", "")).split(".", 1)[-1])
            detail = ", ".join(f"{k}: {'->'.join(v)}"
                               for k, v in sorted(by_key.items()))
            opened = sum(1 for e in trips if e.get("name") == "breaker.open")
            out.append(f"  breaker transitions: {len(trips)} "
                       f"({opened} trips to open) [{detail}]")
        if preempts or drains:
            reasons = sorted({str(e.get("reason", "?"))
                              for e in preempts + drains})
            kinds = ", ".join(
                f"{e.get('kind', '?')}@step {e.get('step')}"
                if "step" in e else str(e.get("kind", "?"))
                for e in drains)
            out.append(f"  preemptions: {len(preempts)} signalled, "
                       f"{len(drains)} clean drains"
                       + (f" ({kinds})" if kinds else "")
                       + (f"; reasons: {', '.join(reasons)}"
                          if reasons else ""))
        if ds_quar:
            out.append(f"  data-state sidecars quarantined: {len(ds_quar)}")
        out.append("")

    # -- serving -------------------------------------------------------------
    serving = [e for e in events if e.get("type") == "serving"]
    reqs = [e for e in serving if e.get("name") == "request"]
    shed = [e for e in serving if e.get("name") == "shed"]
    expired = [e for e in serving if e.get("name") == "expired"]
    if serving:
        out.append("serving:")
        if reqs:
            totals = sorted(float(e.get("total_ms", 0.0)) for e in reqs)
            by_model: Dict[str, int] = defaultdict(int)
            for e in reqs:
                by_model[e.get("model", "?")] += 1
            detail = ", ".join(f"{k}={v}" for k, v in sorted(by_model.items()))
            out.append(
                f"  requests: {len(reqs)} completed ({detail}); "
                f"latency p50={_pct(totals, 50):.3f}ms "
                f"p99={_pct(totals, 99):.3f}ms")
            out.append(
                f"  mean split: queue={_mean(reqs, 'queue_ms'):.3f}ms "
                f"pad={_mean(reqs, 'pad_ms'):.3f}ms "
                f"compute={_mean(reqs, 'compute_ms'):.3f}ms; "
                f"batch occupancy mean="
                f"{_mean(reqs, 'occupancy'):.2f}")
        offered = len(reqs) + len(shed)
        rate = (100.0 * len(shed) / offered) if offered else 0.0
        out.append(f"  shed: {len(shed)} ({rate:.1f}% of offered), "
                   f"expired: {len(expired)}")
        out.append("")

    # -- throughput ----------------------------------------------------------
    fits = [e for e in plain if e.get("name") == "train.fit"]
    step_metrics = [e for e in metrics if e.get("name") == "train.step"]
    if fits or step_metrics:
        out.append("throughput:")
        for e in fits:
            out.append(
                f"  train.fit: {e.get('steps', '?')} steps, "
                f"{e.get('rows', '?')} rows in {e.get('wall_s', 0):.3f}s "
                f"({e.get('examples_per_sec', 0):.1f} examples/sec)")
        if step_metrics:
            last = step_metrics[-1]
            rates = [m.get("examples_per_sec", 0.0) for m in step_metrics]
            out.append(
                f"  train.step: {len(step_metrics)} logged steps, last "
                f"step {last.get('step', '?')}, examples/sec last="
                f"{rates[-1]:.1f} max={max(rates):.1f}")
        out.append("")

    # -- input pipeline ------------------------------------------------------
    epochs = [e for e in plain if e.get("name") == "data.epoch"]
    if epochs:
        out.append("input pipeline:")
        for e in epochs:
            wall = float(e.get("wall_s", 0.0))
            items = int(e.get("items", 0))
            rate = items / wall if wall > 0 else 0.0
            out.append(f"  epoch {e.get('epoch', '?')}: {items} items in "
                       f"{wall:.3f}s ({rate:.1f} items/sec)")
        out.append("")

    # -- bench results -------------------------------------------------------
    bench = [e for e in plain if e.get("name") == "bench.config"]
    if bench:
        rows = []
        for e in bench:
            r = e.get("result") or {}
            rows.append([e.get("config", "?"),
                         r.get("value", "-"), r.get("unit", "-"),
                         r.get("vs_baseline", "-")])
        out.append("bench configs:")
        out.extend(_table(rows, ["config", "value", "unit", "vs_baseline"]))
        out.append("")

    if len(out) == 3:  # only the header: nothing recognizable in the log
        out.append("no spans, reliability events, or throughput records "
                   "found")
    return "\n".join(out).rstrip() + "\n"
