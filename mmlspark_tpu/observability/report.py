"""Run reports: render a captured events.jsonl into a human summary.

The offline half of the telemetry loop (``mmlspark-tpu report
<events.jsonl>``): given the JSON-lines log a run produced under
``observability.events_path`` (or a flight-recorder dump — same schema),
print where the time went —

- per-stage wall-time breakdown: spans aggregated by name (count, total,
  mean, share of the root spans' wall time);
- slowest individual spans (the long-tail view the aggregate hides);
- reliability activity: retry attempts, fault-site hits, checkpoint
  quarantines, by site;
- liveness: watchdog stalls (per heartbeat, longest silence),
  circuit-breaker transitions, preemption signals/drains, quarantined
  data-state sidecars, flight-recorder dumps;
- host syncs: ``sync.point`` events by site (the ROADMAP item-4
  "zero host syncs per step" scoreboard — see observability/syncs.py);
- compile cache: hit/miss/stale/store/bypass/quarantine activity from the
  ``compile_cache.*`` events the persistent AOT program cache emits
  (mmlspark_tpu/compile_cache.py), with the hit rate the rollout warm
  path is supposed to drive up;
- throughput: the ``train.fit`` / ``train.step`` summaries the trainer and
  MetricLogger emit (steps, rows, examples/sec), plus any bench results;
- serving: per-request SLO breakdown from the serve subsystem's
  ``serving.request`` events (p50/p99 total latency, mean queue/pad/compute
  split, batch occupancy) plus shed/expired counts, the shed rate, and
  tail-sampled slow-request trace ids;
- workload: the open-loop driver's honesty section — per-lane
  ``workload.summary`` events (observability/goodput.py): offered vs
  delivered QPS, goodput under the deadline, shed/expired split, and
  the UN-clipped arrival-time p50/p99 with the worst time-bucket's p99
  and its trace_id exemplar;
- generative serving: TTFT/ITL percentiles, token counts, KV-arena
  occupancy and decode-step facts from the generate lane's
  ``generate.request`` / ``decode.step`` events, plus shed/expired
  counts, fleet failover-restarts (``fleet.failover`` with
  ``kind=generate``), the slowest-TTFT exemplar trace ids, and the
  decode-speed signatures: prefix-cache hit rate / CoW copies
  (``decode.prefix`` / ``decode.cow``), speculation acceptance
  (``generate.request`` spec fields), and int8 KV arena savings
  (``decode.arena``);
- fleet: router activity from ``fleet.*`` events (failovers by replica,
  fleet-wide sheds, tenant throttles, replica kills) and rollout progress
  from ``rollout.*`` events (shifted/warmed replicas per model version);
- input pipeline: per-epoch item counts and wall time from the streaming
  ``data.epoch`` events (data/pipeline.py's ``Repeat`` stage).

:func:`build_report` produces all of the above as ONE structured dict
(``mmlspark-tpu report --json``; CI and the bench regression gate consume
it without scraping text); :func:`render_report` formats that dict as the
human text. Span aggregation keys on ``(pid, span_id)`` so merged
multi-process logs never alias two processes' spans.

Pure text in, text out — no jax, no framework state — so it runs anywhere
the log file can be copied to.
"""
from __future__ import annotations

import json
from collections import defaultdict
from typing import Any, Dict, List, Optional

from mmlspark_tpu.observability import metrics
from mmlspark_tpu.utils.logging import get_logger


def load_events(path: str) -> List[Dict[str, Any]]:
    """Parse a JSON-lines event log; malformed lines are counted and
    skipped (a SIGKILLed process tears its final line mid-write), not
    fatal. Every skipped line increments the ``events.torn_lines``
    counter so a merged fleet view quantifies its own data loss."""
    events: List[Dict[str, Any]] = []
    bad = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
    if bad:
        metrics.counter("events.torn_lines").inc(bad)
        get_logger("observability.report").warning(
            "%s: skipped %d torn/malformed line(s)", path, bad)
    return events


def _pct(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile of an ascending list (0 when empty) —
    delegates to the shared estimator in :mod:`..metrics` so report,
    bench and the serve summary agree on the arithmetic."""
    return metrics.nearest_rank(sorted_vals, p)


def _mean(events: List[Dict[str, Any]], field: str) -> float:
    vals = [float(e.get(field, 0.0)) for e in events]
    return sum(vals) / len(vals) if vals else 0.0


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines.extend(fmt.format(*map(str, r)) for r in rows)
    return lines


def build_report(path, top: int = 10,
                 events: Optional[List[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """One structured dict with every section of the run report (the
    ``--json`` output). Sections with nothing to say are absent.

    ``path`` may be one event-log path or a list of them (per-pid
    sidecars from a multi-process run): multiple logs are merged into
    one ts-ordered stream; the span sections' ``(pid, span_id)`` dedupe
    already absorbs any overlap."""
    paths = [path] if isinstance(path, str) else list(path)
    if events is None:
        if len(paths) == 1:
            events = load_events(paths[0])
        else:
            from mmlspark_tpu.observability.aggregate import merge_event_logs
            events = merge_event_logs(paths)
    path = paths[0] if len(paths) == 1 else ", ".join(paths)
    spans = [e for e in events if e.get("type") == "span"]
    plain = [e for e in events if e.get("type") == "event"]
    metrics = [e for e in events if e.get("type") == "metric"]

    report: Dict[str, Any] = {
        "path": path,
        "paths": paths,
        "events": len(events),
        "spans": len(spans),
        "metrics": len(metrics),
    }

    # -- per-stage wall time (spans keyed per (pid, span_id)) --------------
    if spans:
        agg: Dict[str, List[float]] = defaultdict(list)
        seen = set()
        for s in spans:
            key = (s.get("pid") or 0, s.get("span_id"))
            if key[1] is not None and key in seen:
                continue               # merged-log duplicate
            seen.add(key)
            agg[s.get("name", "?")].append(float(s.get("dur_s", 0.0)))
        # run wall = sum of root spans; fall back to the span total when the
        # log has no roots (e.g. a filtered or partial capture)
        root_total = sum(float(s.get("dur_s", 0.0)) for s in spans
                         if not s.get("parent_id"))
        denom = root_total or sum(sum(v) for v in agg.values()) or 1.0
        report["stages"] = [
            {"span": name, "count": len(durs), "total_s": round(sum(durs), 6),
             "mean_ms": round(sum(durs) / len(durs) * 1e3, 4),
             "share": round(100.0 * sum(durs) / denom, 2)}
            for name, durs in sorted(agg.items(),
                                     key=lambda kv: -sum(kv[1]))[:top]]
        report["slowest"] = [
            {"span": s.get("name", "?"),
             "dur_s": round(float(s.get("dur_s", 0.0)), 6),
             "depth": s.get("depth", 0), "pid": s.get("pid") or 0,
             "parent": s.get("parent", "") or None}
            for s in sorted(spans,
                            key=lambda s: -float(s.get("dur_s", 0.0)))[:top]]

    # -- reliability -------------------------------------------------------
    retries = [e for e in plain if e.get("name") == "retry.attempt"]
    faults = [e for e in plain if e.get("name") == "fault.hit"]
    quarantines = [e for e in plain
                   if e.get("name") == "checkpoint.quarantine"]
    if retries or faults or quarantines:
        rel: Dict[str, Any] = {}
        if retries:
            by_site: Dict[str, int] = defaultdict(int)
            for e in retries:
                by_site[e.get("policy", "?")] += 1
            rel["retries"] = {"total": len(retries), "by_policy": dict(
                sorted(by_site.items()))}
        if faults:
            by_site = defaultdict(int)
            for e in faults:
                by_site[e.get("site", "?")] += 1
            rel["faults"] = {"total": len(faults),
                             "by_site": dict(sorted(by_site.items()))}
        if quarantines:
            rel["quarantines"] = {"total": len(quarantines),
                                  "steps": [e.get("step")
                                            for e in quarantines]}
        report["reliability"] = rel

    # -- liveness ----------------------------------------------------------
    stalls = [e for e in plain if e.get("name") == "watchdog.stall"]
    trips = [e for e in plain
             if str(e.get("name", "")).startswith("breaker.")]
    preempts = [e for e in plain if e.get("name") == "preemption.signal"]
    drains = [e for e in plain if e.get("name") == "preemption.drain"]
    ds_quar = [e for e in plain
               if e.get("name") == "checkpoint.data_state_quarantine"]
    fdumps = [e for e in plain if e.get("name") == "flightrec.dump"]
    if stalls or trips or preempts or drains or ds_quar or fdumps:
        live: Dict[str, Any] = {}
        if stalls:
            by_hb: Dict[str, int] = defaultdict(int)
            for e in stalls:
                by_hb[e.get("heartbeat", "?")] += 1
            live["stalls"] = {
                "total": len(stalls),
                "by_heartbeat": dict(sorted(by_hb.items())),
                "longest_s": max(float(e.get("stalled_s", 0.0))
                                 for e in stalls)}
        if trips:
            by_key: Dict[str, List[str]] = defaultdict(list)
            for e in trips:
                by_key[e.get("key", "?")].append(
                    str(e.get("name", "")).split(".", 1)[-1])
            live["breakers"] = {
                "transitions": len(trips),
                "opened": sum(1 for e in trips
                              if e.get("name") == "breaker.open"),
                "by_key": dict(sorted(by_key.items()))}
        if preempts or drains:
            live["preemptions"] = {
                "signalled": len(preempts),
                "drains": len(drains),
                "drain_kinds": [
                    {"kind": e.get("kind", "?"), "step": e.get("step")}
                    for e in drains],
                "reasons": sorted({str(e.get("reason", "?"))
                                   for e in preempts + drains})}
        if ds_quar:
            live["data_state_quarantines"] = len(ds_quar)
        if fdumps:
            live["flight_dumps"] = [
                {"reason": e.get("reason", "?"),
                 "events": e.get("events"), "dropped": e.get("dropped")}
                for e in fdumps]
        report["liveness"] = live

    # -- host syncs (observability/syncs.py sync_point events) -------------
    syncs = [e for e in plain if e.get("name") == "sync.point"]
    if syncs:
        by_site: Dict[str, int] = defaultdict(int)
        by_span: Dict[str, int] = defaultdict(int)
        for e in syncs:
            by_site[e.get("site", "?")] += 1
            if e.get("span"):
                by_span[str(e["span"])] += 1
        step_metrics = [m for m in metrics if m.get("name") == "train.step"]
        sec: Dict[str, Any] = {"total": len(syncs),
                               "by_site": dict(sorted(by_site.items()))}
        if by_span:
            sec["by_span"] = dict(sorted(by_span.items()))
        if step_metrics:
            steps = max(int(m.get("step", 0)) for m in step_metrics) or 1
            sec["per_step"] = round(len(syncs) / steps, 4)
        report["syncs"] = sec

    # -- serving -----------------------------------------------------------
    serving = [e for e in events if e.get("type") == "serving"]
    reqs = [e for e in serving if e.get("name") == "request"]
    shed = [e for e in serving if e.get("name") == "shed"]
    expired = [e for e in serving if e.get("name") == "expired"]
    if serving:
        sv: Dict[str, Any] = {}
        if reqs:
            totals = sorted(float(e.get("total_ms", 0.0)) for e in reqs)
            by_model: Dict[str, int] = defaultdict(int)
            for e in reqs:
                by_model[e.get("model", "?")] += 1
            sv["requests"] = {
                "completed": len(reqs),
                "by_model": dict(sorted(by_model.items())),
                "p50_ms": round(_pct(totals, 50), 3),
                "p99_ms": round(_pct(totals, 99), 3),
                "mean_queue_ms": round(_mean(reqs, "queue_ms"), 3),
                "mean_pad_ms": round(_mean(reqs, "pad_ms"), 3),
                "mean_compute_ms": round(_mean(reqs, "compute_ms"), 3),
                "mean_occupancy": round(_mean(reqs, "occupancy"), 4)}
            slow = [e for e in reqs if e.get("slow")]
            if slow:
                sv["slow_traces"] = [
                    {"trace_id": e.get("trace_id"),
                     "total_ms": e.get("total_ms")}
                    for e in sorted(
                        slow, key=lambda e: -float(e.get("total_ms", 0.0))
                    )[:top]]
        offered = len(reqs) + len(shed)
        sv["shed"] = len(shed)
        sv["shed_rate"] = round(
            (100.0 * len(shed) / offered) if offered else 0.0, 2)
        sv["expired"] = len(expired)
        report["serving"] = sv

    # -- workload (open-loop goodput summaries from GoodputMeter.export) ---
    wl_ev = [e for e in events
             if e.get("type") == "workload" and e.get("name") == "summary"]
    if wl_ev:
        lanes = []
        for e in wl_ev:
            lane: Dict[str, Any] = {
                "lane": str(e.get("lane", "") or "-"),
                "offered": int(e.get("offered", 0)),
                "delivered": int(e.get("delivered", 0)),
                "shed": int(e.get("shed", 0)),
                "expired": int(e.get("expired", 0)),
                "goodput": float(e.get("goodput", 0.0)),
                "deadline_ms": float(e.get("deadline_ms", 0.0)),
                "offered_qps": float(e.get("offered_qps", 0.0)),
                "delivered_qps": float(e.get("delivered_qps", 0.0)),
                "arrival_p50_ms": float(e.get("arrival_p50_ms", 0.0)),
                "arrival_p99_ms": float(e.get("arrival_p99_ms", 0.0)),
            }
            worst = e.get("worst_bucket")
            if isinstance(worst, dict):
                lane["worst_bucket"] = {
                    "t0": worst.get("t0"),
                    "p99_ms": worst.get("p99_ms"),
                    "trace_id": worst.get("trace_id")}
            lanes.append(lane)
        report["workload"] = lanes

    # -- generative serving (generate.* + decode.* events) ----------------
    gen_ev = [e for e in events if e.get("type") == "generate"]
    dec_ev = [e for e in events if e.get("type") == "decode"]
    if gen_ev or dec_ev:
        gv: Dict[str, Any] = {}
        greqs = [e for e in gen_ev if e.get("name") == "request"]
        if greqs:
            ttfts = sorted(float(e.get("ttft_ms", 0.0)) for e in greqs)
            itls = sorted(float(e.get("itl_mean_ms", 0.0)) for e in greqs)
            by_model: Dict[str, int] = defaultdict(int)
            for e in greqs:
                by_model[e.get("model", "?")] += 1
            by_finish: Dict[str, int] = defaultdict(int)
            for e in greqs:
                by_finish[str(e.get("finish", "?"))] += 1
            gv["requests"] = {
                "completed": len(greqs),
                "by_model": dict(sorted(by_model.items())),
                "by_finish": dict(sorted(by_finish.items())),
                "tokens": sum(int(e.get("tokens", 0)) for e in greqs),
                "ttft_p50_ms": round(_pct(ttfts, 50), 3),
                "ttft_p99_ms": round(_pct(ttfts, 99), 3),
                "itl_p50_ms": round(_pct(itls, 50), 3),
                "itl_p99_ms": round(_pct(itls, 99), 3),
                "mean_kv_occupancy": round(
                    _mean(greqs, "kv_occupancy"), 4)}
            gv["slow_traces"] = [
                {"trace_id": e.get("trace_id"),
                 "ttft_ms": e.get("ttft_ms")}
                for e in sorted(
                    greqs, key=lambda e: -float(e.get("ttft_ms", 0.0))
                )[:min(top, 3)]]
        gshed = [e for e in gen_ev if e.get("name") == "shed"]
        gv["shed"] = len(gshed)
        gv["expired"] = len([e for e in gen_ev
                             if e.get("name") == "expired"])
        gv["failed_over"] = len([
            e for e in events
            if e.get("type") == "fleet" and e.get("name") == "failover"
            and e.get("kind") == "generate"])
        steps = [e for e in dec_ev if e.get("name") == "step"]
        if steps:
            gv["decode_steps"] = {
                "count": len(steps),
                "mean_active": round(_mean(steps, "active"), 2),
                "mean_step_ms": round(_mean(steps, "step_ms"), 3)}
        pref = [e for e in dec_ev if e.get("name") == "prefix"]
        cows = [e for e in dec_ev if e.get("name") == "cow"]
        if pref or cows:
            hits = sum(int(e.get("hits", 0)) for e in pref)
            misses = sum(int(e.get("misses", 0)) for e in pref)
            gv["prefix_cache"] = {
                "hits": hits, "misses": misses,
                "hit_rate": round(hits / max(1, hits + misses), 4),
                "cached_tokens": sum(int(e.get("cached_tokens", 0))
                                     for e in pref),
                "cow_copies": len(cows)}
        proposed = sum(int(e.get("spec_proposed", 0)) for e in greqs)
        if proposed:
            accepted = sum(int(e.get("spec_accepted", 0)) for e in greqs)
            gv["speculation"] = {
                "proposed": proposed, "accepted": accepted,
                "accept_rate": round(accepted / proposed, 4)}
        quant = [e for e in dec_ev if e.get("name") == "arena"
                 and str(e.get("kv_dtype", "")) == "int8"]
        if quant:
            arena = sum(int(e.get("arena_bytes", 0)) for e in quant)
            gv["int8_kv"] = {
                "arenas": len(quant), "arena_bytes": arena,
                "saved_bytes": sum(int(e.get("unquantized_bytes", 0))
                                   for e in quant) - arena}
        report["generate"] = gv

    # -- fleet (router + rollout) ------------------------------------------
    fleet_ev = [e for e in events if e.get("type") == "fleet"]
    rollout_ev = [e for e in events if e.get("type") == "rollout"]
    if fleet_ev or rollout_ev:
        fl: Dict[str, Any] = {}
        failovers = [e for e in fleet_ev if e.get("name") == "failover"]
        if failovers:
            by_rep: Dict[str, int] = defaultdict(int)
            for e in failovers:
                by_rep[e.get("replica", "?")] += 1
            fl["failovers"] = {"count": len(failovers),
                               "by_replica": dict(sorted(by_rep.items()))}
        all_shed = [e for e in fleet_ev if e.get("name") == "all_shed"]
        if all_shed:
            fl["all_shed"] = len(all_shed)
        throttled = [e for e in fleet_ev
                     if e.get("name") == "tenant_throttled"]
        if throttled:
            by_ten: Dict[str, int] = defaultdict(int)
            for e in throttled:
                by_ten[e.get("tenant", "?")] += 1
            fl["tenant_throttled"] = dict(sorted(by_ten.items()))
        killed = [e.get("replica", "?") for e in fleet_ev
                  if e.get("name") in ("kill", "replica_killed")]
        if killed:
            fl["replicas_killed"] = killed
        if rollout_ev:
            by_target: Dict[Any, Dict[str, Any]] = {}
            for e in rollout_ev:
                key = (e.get("model", "?"), e.get("version", "?"))
                ro = by_target.setdefault(
                    key, {"model": key[0], "version": key[1],
                          "shifted": 0, "warmed": 0, "status": "deploying"})
                if e.get("name") == "shift":
                    ro["shifted"] += 1
                elif e.get("name") == "warm":
                    ro["warmed"] += 1
                elif e.get("name") == "done":
                    ro["status"] = "done"
                elif e.get("name") == "abort":
                    ro["status"] = f"aborted@{e.get('replica', '?')}"
            fl["rollouts"] = list(by_target.values())
        report["fleet"] = fl

    # -- affinity (prefix-digest routing) ----------------------------------
    aff_ev = [e for e in events if e.get("type") == "affinity"]
    if aff_ev:
        routes = [e for e in aff_ev if e.get("name") == "route"]
        af: Dict[str, Any] = {}
        if routes:
            by_mode: Dict[str, int] = defaultdict(int)
            by_rep: Dict[str, int] = defaultdict(int)
            hist: Dict[int, int] = defaultdict(int)
            for e in routes:
                by_mode[e.get("mode", "?")] += 1
                by_rep[e.get("replica", "?")] += 1
                if e.get("mode") == "prefix":
                    hist[int(e.get("depth", 0))] += 1
            n = len(routes)
            af["routes"] = n
            af["by_mode"] = dict(sorted(by_mode.items()))
            af["by_replica"] = dict(sorted(by_rep.items()))
            af["affinity_route_share"] = round(
                (n - by_mode.get("wrr", 0)) / n, 4)
            if hist:
                af["hit_depth_hist"] = {str(k): v for k, v
                                        in sorted(hist.items())}
        adverts = [e for e in aff_ev if e.get("name") == "advertise"]
        if adverts:
            latest: Dict[Any, Dict[str, Any]] = {}
            for e in adverts:    # last write wins: the current digest
                latest[(e.get("replica", "?"), e.get("model", "?"))] = {
                    "replica": e.get("replica", "?"),
                    "model": e.get("model", "?"),
                    "chains": int(e.get("chains", 0)),
                    "max_depth": int(e.get("max_depth", 0))}
            af["advertised"] = sorted(
                latest.values(),
                key=lambda d: (d["replica"], d["model"]))
        report["affinity"] = af

    # -- supervisor (process-fleet restart decisions) ----------------------
    sup_ev = [e for e in events if e.get("type") == "supervisor"]
    if sup_ev:
        sup: Dict[str, Any] = {}
        spawns = [e for e in sup_ev if e.get("name") == "spawn"]
        restarts = [e for e in sup_ev if e.get("name") == "restart"]
        backoffs = [e for e in sup_ev if e.get("name") == "backoff"]
        giveups = [e for e in sup_ev if e.get("name") == "giveup"]
        exits = [e for e in sup_ev if e.get("name") == "exit"]
        by_rep: Dict[str, Dict[str, int]] = defaultdict(
            lambda: {"spawns": 0, "restarts": 0, "backoffs": 0,
                     "giveups": 0})
        for name, evs in (("spawns", spawns), ("restarts", restarts),
                          ("backoffs", backoffs), ("giveups", giveups)):
            for e in evs:
                by_rep[str(e.get("replica", "?"))][name] += 1
        sup["spawns"] = len(spawns)
        sup["restarts"] = len(restarts)
        sup["backoffs"] = len(backoffs)
        sup["giveups"] = len(giveups)
        sup["by_replica"] = {k: dict(v)
                             for k, v in sorted(by_rep.items())}
        sup["worker_pids"] = sorted(
            {int(e["pid"]) for e in spawns
             if e.get("pid") is not None})
        if exits:
            sup["exits"] = [
                {"replica": e.get("replica", "?"), "pid": e.get("pid"),
                 "returncode": e.get("returncode"),
                 "uptime_s": e.get("uptime_s")}
                for e in exits]
        if restarts:
            sup["restart_ready_s_max"] = max(
                float(e.get("ready_s", 0.0)) for e in restarts)
        # elasticity: scale decisions (add_slot/retire) and the
        # spawn->ready latency distribution from the ready events
        adds = [e for e in sup_ev if e.get("name") == "add_slot"]
        retires = [e for e in sup_ev if e.get("name") == "retire"]
        noops = [e for e in sup_ev if e.get("name") == "retire_noop"]
        if adds or retires or noops:
            desired = [int(e["desired"]) for e in adds + retires
                       if e.get("desired") is not None]
            sup["elastic"] = {
                "slots_added": len(adds),
                "slots_retired": len(retires),
                "retire_noops": len(noops),
                "drained": sum(1 for e in retires if e.get("drained")),
                "desired_final": desired[-1] if desired else None,
            }
        ready_ms = sorted(
            float(e["spawn_to_ready_ms"]) for e in sup_ev
            if e.get("name") == "ready"
            and e.get("spawn_to_ready_ms") is not None)
        if ready_ms:
            sup["spawn_to_ready_ms"] = {
                "count": len(ready_ms),
                "p50": round(_pct(ready_ms, 50), 3),
                "p99": round(_pct(ready_ms, 99), 3),
                "max": round(ready_ms[-1], 3),
            }
        shut = [e for e in sup_ev if e.get("name") == "shutdown"]
        if shut:
            sup["shutdowns"] = [
                {"reason": e.get("reason", "?"),
                 "workers": e.get("workers")} for e in shut]
        report["supervisor"] = sup

    # -- SLO burn/breach (slo.* events from the burn-rate engine) ----------
    slo_ev = [e for e in events if e.get("type") == "slo"]
    if slo_ev:
        by_obj: Dict[str, Dict[str, Any]] = {}
        for e in slo_ev:
            o = by_obj.setdefault(
                str(e.get("objective", "?")),
                {"burns": 0, "breaches": 0, "recovers": 0,
                 "max_burn_fast": 0.0})
            name = e.get("name")
            if name == "burn":
                o["burns"] += 1
            elif name == "breach":
                o["breaches"] += 1
            elif name == "recover":
                o["recovers"] += 1
            o["max_burn_fast"] = round(max(
                o["max_burn_fast"], float(e.get("burn_fast", 0.0))), 4)
        report["slo"] = {"events": len(slo_ev),
                         "objectives": dict(sorted(by_obj.items()))}

    # -- autopilot (control-loop decisions: actuated + suppressed) ---------
    ap_ev = [e for e in events if e.get("type") == "autopilot"]
    if ap_ev:
        acted = [e for e in ap_ev if not e.get("suppressed")]
        held = [e for e in ap_ev if e.get("suppressed")]
        by_action: Dict[str, int] = defaultdict(int)
        for e in acted:
            by_action[str(e.get("name", "?"))] += 1
        reasons: Dict[str, int] = defaultdict(int)
        for e in held:
            token = str(e.get("reason", "?")).split()[0] if \
                str(e.get("reason", "")).strip() else "?"
            for prefix in ("cooldown", "window", "hold"):
                if token.startswith(prefix):
                    token = prefix
                    break
            reasons[token] += 1
        report["autopilot"] = {
            "decisions": len(ap_ev),
            "actions": len(acted),
            "suppressed": len(held),
            "by_action": dict(sorted(by_action.items())),
            "suppressed_reasons": dict(sorted(reasons.items())),
            "last": [{"t": e.get("t"), "action": e.get("name", "?"),
                      "target": str(e.get("target", "")),
                      "reason": str(e.get("reason", ""))}
                     for e in acted[-5:]],
        }

    # -- HBM memory (memory.pressure / memory.audit events) ----------------
    mem_ev = [e for e in events if e.get("type") == "memory"]
    if mem_ev:
        pressures = [e for e in mem_ev if e.get("name") == "pressure"]
        audits = [e for e in mem_ev if e.get("name") == "audit"]
        mem: Dict[str, Any] = {}
        if pressures:
            by_model: Dict[str, int] = defaultdict(int)
            freed = 0
            for e in pressures:
                by_model[str(e.get("model", "?"))] += 1
                freed += int(e.get("freed_bytes", 0))
            mem["pressure"] = {"count": len(pressures),
                               "freed_bytes": freed,
                               "by_model": dict(sorted(by_model.items()))}
        if audits:
            last = audits[-1]
            mem["audit"] = {
                "live_bytes": last.get("live_bytes"),
                "accounted_bytes": last.get("accounted_bytes"),
                "unaccounted_bytes": last.get("unaccounted_bytes")}
        if mem:
            report["memory"] = mem

    # -- compile cache (compile_cache.* events) ----------------------------
    cc = [e for e in events if e.get("type") == "compile_cache"]
    if cc:
        by_name: Dict[str, int] = defaultdict(int)
        for e in cc:
            by_name[str(e.get("name", "?"))] += 1
        sec = {"events": len(cc),
               "hits": by_name.get("hit", 0),
               "misses": by_name.get("miss", 0),
               "stores": by_name.get("store", 0),
               "stale": by_name.get("stale", 0),
               "bypasses": by_name.get("bypass", 0),
               "quarantined": by_name.get("quarantine", 0)}
        looked = sec["hits"] + sec["misses"] + sec["stale"]
        sec["hit_rate"] = round(
            (100.0 * sec["hits"] / looked) if looked else 0.0, 2)
        quar = [e for e in cc if e.get("name") == "quarantine"]
        if quar:
            sec["quarantine_reasons"] = sorted(
                {str(e.get("reason", "?")) for e in quar})
        report["compile_cache"] = sec

    # -- throughput --------------------------------------------------------
    fits = [e for e in plain if e.get("name") == "train.fit"]
    step_metrics = [e for e in metrics if e.get("name") == "train.step"]
    if fits or step_metrics:
        th: Dict[str, Any] = {}
        if fits:
            th["fits"] = [
                {"steps": e.get("steps"), "rows": e.get("rows"),
                 "wall_s": e.get("wall_s", 0),
                 "examples_per_sec": e.get("examples_per_sec", 0)}
                for e in fits]
        if step_metrics:
            last = step_metrics[-1]
            rates = [m.get("examples_per_sec", 0.0) for m in step_metrics]
            th["steps"] = {"logged": len(step_metrics),
                           "last_step": last.get("step"),
                           "examples_per_sec_last": rates[-1],
                           "examples_per_sec_max": max(rates)}
        report["throughput"] = th

    # -- input pipeline ----------------------------------------------------
    epochs = [e for e in plain if e.get("name") == "data.epoch"]
    if epochs:
        report["input_pipeline"] = [
            {"epoch": e.get("epoch"), "items": int(e.get("items", 0)),
             "wall_s": float(e.get("wall_s", 0.0))}
            for e in epochs]

    # -- bench results -----------------------------------------------------
    bench = [e for e in plain if e.get("name") == "bench.config"]
    if bench:
        report["bench"] = [
            {"config": e.get("config", "?"), **(e.get("result") or {})}
            for e in bench]

    return report


def render_report(path, top: int = 10) -> str:
    """The full text report for one event log (or a list of per-pid
    sidecar logs, merged)."""
    r = build_report(path, top=top)
    out: List[str] = [f"run report: {r['path']}",
                      f"{r['events']} events "
                      f"({r['spans']} spans, {r['metrics']} metrics)", ""]
    if len(r.get("paths", ())) > 1:
        out.insert(1, f"merged from {len(r['paths'])} event log(s)")

    if "stages" in r:
        rows = [[s["span"], s["count"], f"{s['total_s']:.4f}",
                 f"{s['mean_ms']:.2f}", f"{s['share']:.1f}%"]
                for s in r["stages"]]
        out.append("per-stage wall time:")
        out.extend(_table(rows, ["span", "count", "total_s", "mean_ms",
                                 "share"]))
        out.append("")
        rows = [[s["span"], f"{s['dur_s']:.4f}", s["depth"],
                 s["parent"] or "-"] for s in r["slowest"]]
        out.append("slowest spans:")
        out.extend(_table(rows, ["span", "dur_s", "depth", "parent"]))
        out.append("")

    if "reliability" in r:
        rel = r["reliability"]
        out.append("reliability:")
        if "retries" in rel:
            detail = ", ".join(f"{k}={v}" for k, v in
                               rel["retries"]["by_policy"].items())
            out.append(f"  retry attempts: {rel['retries']['total']} "
                       f"({detail})")
        if "faults" in rel:
            detail = ", ".join(f"{k}={v}" for k, v in
                               rel["faults"]["by_site"].items())
            out.append(f"  fault hits: {rel['faults']['total']} ({detail})")
        if "quarantines" in rel:
            out.append(f"  checkpoint quarantines: "
                       f"{rel['quarantines']['total']} "
                       f"(steps {rel['quarantines']['steps']})")
        out.append("")

    if "liveness" in r:
        live = r["liveness"]
        out.append("liveness:")
        if "stalls" in live:
            detail = ", ".join(f"{k}={v}" for k, v in
                               live["stalls"]["by_heartbeat"].items())
            out.append(f"  watchdog stalls: {live['stalls']['total']} "
                       f"({detail}); longest "
                       f"{live['stalls']['longest_s']:.1f}s "
                       "(stacks in the event log)")
        if "breakers" in live:
            detail = ", ".join(f"{k}: {'->'.join(v)}" for k, v in
                               live["breakers"]["by_key"].items())
            out.append(f"  breaker transitions: "
                       f"{live['breakers']['transitions']} "
                       f"({live['breakers']['opened']} trips to open) "
                       f"[{detail}]")
        if "preemptions" in live:
            pre = live["preemptions"]
            kinds = ", ".join(
                f"{d['kind']}@step {d['step']}" if d["step"] is not None
                else str(d["kind"]) for d in pre["drain_kinds"])
            out.append(f"  preemptions: {pre['signalled']} signalled, "
                       f"{pre['drains']} clean drains"
                       + (f" ({kinds})" if kinds else "")
                       + (f"; reasons: {', '.join(pre['reasons'])}"
                          if pre["reasons"] else ""))
        if "data_state_quarantines" in live:
            out.append(f"  data-state sidecars quarantined: "
                       f"{live['data_state_quarantines']}")
        if "flight_dumps" in live:
            detail = ", ".join(f"{d['reason']} ({d['events']} events)"
                               for d in live["flight_dumps"])
            out.append(f"  flight-recorder dumps: "
                       f"{len(live['flight_dumps'])} [{detail}]")
        out.append("")

    if "syncs" in r:
        sy = r["syncs"]
        out.append("host syncs:")
        detail = ", ".join(f"{k}={v}" for k, v in sy["by_site"].items())
        line = f"  sync points: {sy['total']} ({detail})"
        if "per_step" in sy:
            line += f"; per train step: {sy['per_step']:.2f}"
        out.append(line)
        if "by_span" in sy:
            detail = ", ".join(f"{k}={v}" for k, v in sy["by_span"].items())
            out.append(f"  by span: {detail}")
        out.append("")

    if "serving" in r:
        sv = r["serving"]
        out.append("serving:")
        if "requests" in sv:
            rq = sv["requests"]
            detail = ", ".join(f"{k}={v}"
                               for k, v in rq["by_model"].items())
            out.append(
                f"  requests: {rq['completed']} completed ({detail}); "
                f"latency p50={rq['p50_ms']:.3f}ms "
                f"p99={rq['p99_ms']:.3f}ms")
            out.append(
                f"  mean split: queue={rq['mean_queue_ms']:.3f}ms "
                f"pad={rq['mean_pad_ms']:.3f}ms "
                f"compute={rq['mean_compute_ms']:.3f}ms; "
                f"batch occupancy mean={rq['mean_occupancy']:.2f}")
        if sv.get("slow_traces"):
            detail = ", ".join(f"{t['trace_id']} ({t['total_ms']}ms)"
                               for t in sv["slow_traces"][:3])
            out.append(f"  slow traces (tail-sampled): "
                       f"{len(sv['slow_traces'])} [{detail}]")
        out.append(f"  shed: {sv['shed']} ({sv['shed_rate']:.1f}% of "
                   f"offered), expired: {sv['expired']}")
        out.append("")

    if "workload" in r:
        out.append("workload (open-loop, latency from intended arrival):")
        for wl in r["workload"]:
            out.append(
                f"  [{wl['lane']}] offered {wl['offered']} "
                f"({wl['offered_qps']:.2f} qps), delivered "
                f"{wl['delivered']} ({wl['delivered_qps']:.2f} qps); "
                f"goodput {wl['goodput'] * 100:.1f}% under "
                f"{wl['deadline_ms']:.0f}ms deadline")
            out.append(
                f"    shed {wl['shed']}, expired {wl['expired']}; "
                f"arrival p50={wl['arrival_p50_ms']:.1f}ms "
                f"p99={wl['arrival_p99_ms']:.1f}ms (un-clipped)")
            worst = wl.get("worst_bucket")
            if worst and worst.get("p99_ms") is not None:
                line = (f"    worst bucket @t={worst['t0']:.0f}s: "
                        f"p99={worst['p99_ms']:.1f}ms")
                if worst.get("trace_id"):
                    line += f" (trace {worst['trace_id']})"
                out.append(line)
        out.append("")

    if "generate" in r:
        gv = r["generate"]
        out.append("generative serving:")
        if "requests" in gv:
            rq = gv["requests"]
            detail = ", ".join(f"{k}={v}"
                               for k, v in rq["by_model"].items())
            finish = ", ".join(f"{k}={v}"
                               for k, v in rq["by_finish"].items())
            out.append(
                f"  requests: {rq['completed']} completed ({detail}); "
                f"{rq['tokens']} tokens [{finish}]")
            out.append(
                f"  TTFT p50={rq['ttft_p50_ms']:.3f}ms "
                f"p99={rq['ttft_p99_ms']:.3f}ms; "
                f"ITL p50={rq['itl_p50_ms']:.3f}ms "
                f"p99={rq['itl_p99_ms']:.3f}ms; "
                f"KV occupancy mean={rq['mean_kv_occupancy']:.2f}")
        if gv.get("slow_traces"):
            detail = ", ".join(f"{t['trace_id']} ({t['ttft_ms']}ms)"
                               for t in gv["slow_traces"])
            out.append(f"  slowest TTFT traces: [{detail}]")
        out.append(f"  shed: {gv['shed']}, expired: {gv['expired']}, "
                   f"failed over (restarted): {gv['failed_over']}")
        if "decode_steps" in gv:
            ds = gv["decode_steps"]
            out.append(
                f"  decode steps: {ds['count']} "
                f"(mean active={ds['mean_active']:.2f}, "
                f"mean step={ds['mean_step_ms']:.3f}ms)")
        if "prefix_cache" in gv:
            pc = gv["prefix_cache"]
            out.append(
                f"  prefix cache: {pc['hit_rate'] * 100:.1f}% hit "
                f"({pc['hits']}/{pc['hits'] + pc['misses']} blocks, "
                f"{pc['cached_tokens']} prompt tokens reused, "
                f"{pc['cow_copies']} CoW copies)")
        if "speculation" in gv:
            sp = gv["speculation"]
            out.append(
                f"  speculation: {sp['accept_rate'] * 100:.1f}% accepted "
                f"({sp['accepted']}/{sp['proposed']} draft tokens)")
        if "int8_kv" in gv:
            q = gv["int8_kv"]
            out.append(
                f"  int8 KV: {q['arenas']} arena(s), "
                f"{q['arena_bytes'] / 1e6:.1f}MB stored, "
                f"{q['saved_bytes'] / 1e6:.1f}MB saved vs fp")
        out.append("")

    if "fleet" in r:
        fl = r["fleet"]
        out.append("fleet:")
        if "failovers" in fl:
            detail = ", ".join(f"{k}={v}"
                               for k, v in fl["failovers"]["by_replica"]
                               .items())
            out.append(f"  failovers: {fl['failovers']['count']} "
                       f"({detail})")
        if fl.get("replicas_killed"):
            out.append("  replicas killed: "
                       + ", ".join(fl["replicas_killed"]))
        if "all_shed" in fl:
            out.append(f"  fleet-wide sheds (all replicas full): "
                       f"{fl['all_shed']}")
        if "tenant_throttled" in fl:
            detail = ", ".join(f"{k}={v}"
                               for k, v in fl["tenant_throttled"].items())
            out.append(f"  tenant throttled: {detail}")
        for ro in fl.get("rollouts", ()):
            out.append(
                f"  rollout {ro['model']} -> {ro['version']}: "
                f"{ro['shifted']} replica(s) shifted, "
                f"{ro['warmed']} warmed, {ro['status']}")
        out.append("")

    if "affinity" in r:
        af = r["affinity"]
        out.append("affinity (prefix-digest routing):")
        if "routes" in af:
            detail = ", ".join(f"{k}={v}"
                               for k, v in af["by_mode"].items())
            out.append(f"  routes: {af['routes']} ({detail}; "
                       f"affinity share "
                       f"{af['affinity_route_share'] * 100:.1f}%)")
            detail = ", ".join(f"{k}={v}"
                               for k, v in af["by_replica"].items())
            out.append(f"  by replica: {detail}")
        if "hit_depth_hist" in af:
            detail = ", ".join(f"depth {k}: {v}" for k, v in
                               af["hit_depth_hist"].items())
            out.append(f"  expected hit depth: {detail}")
        for ad in af.get("advertised", ()):
            out.append(
                f"  advertised {ad['replica']}/{ad['model']}: "
                f"{ad['chains']} chain(s), max depth {ad['max_depth']}")
        out.append("")

    if "supervisor" in r:
        sup = r["supervisor"]
        out.append("supervisor:")
        detail = ", ".join(
            f"{k}: {v['spawns']} spawn(s), {v['restarts']} restart(s), "
            f"{v['backoffs']} backoff(s), {v['giveups']} giveup(s)"
            for k, v in sup["by_replica"].items())
        out.append(f"  replicas: {detail}")
        out.append(
            f"  worker pids: "
            f"{', '.join(str(p) for p in sup['worker_pids']) or '-'}")
        for e in sup.get("exits", ()):
            out.append(
                f"  exit: {e['replica']} pid={e['pid']} "
                f"rc={e['returncode']} after {e['uptime_s']}s")
        if "restart_ready_s_max" in sup:
            out.append(f"  slowest restart to ready: "
                       f"{sup['restart_ready_s_max']:.2f}s")
        if "elastic" in sup:
            el = sup["elastic"]
            line = (f"  elastic: {el['slots_added']} slot(s) added, "
                    f"{el['slots_retired']} retired "
                    f"({el['drained']} drained cleanly)")
            if el["retire_noops"]:
                line += f", {el['retire_noops']} retire no-op(s)"
            if el["desired_final"] is not None:
                line += f"; desired now {el['desired_final']}"
            out.append(line)
        if "spawn_to_ready_ms" in sup:
            h = sup["spawn_to_ready_ms"]
            out.append(
                f"  spawn->ready: p50 {h['p50']:.0f}ms, "
                f"p99 {h['p99']:.0f}ms, max {h['max']:.0f}ms "
                f"over {h['count']} spawn(s)")
        for s in sup.get("shutdowns", ()):
            out.append(f"  shutdown ({s['reason']}): "
                       f"{s['workers']} worker(s) drained")
        out.append("")

    if "slo" in r:
        out.append("slo:")
        for name, o in r["slo"]["objectives"].items():
            out.append(
                f"  {name}: {o['burns']} burn(s), "
                f"{o['breaches']} breach(es), {o['recovers']} recover(s); "
                f"max fast burn {o['max_burn_fast']:.2f}x budget")
        out.append("")

    if "autopilot" in r:
        ap = r["autopilot"]
        out.append("autopilot:")
        detail = ", ".join(f"{k}={v}" for k, v in ap["by_action"].items())
        out.append(f"  decisions: {ap['decisions']} "
                   f"({ap['actions']} actuated, "
                   f"{ap['suppressed']} suppressed)"
                   + (f"; actions: {detail}" if detail else ""))
        if ap["suppressed_reasons"]:
            detail = ", ".join(f"{k}={v}" for k, v in
                               ap["suppressed_reasons"].items())
            out.append(f"  suppressed: {detail}")
        for d in ap.get("last", ()):
            tgt = f" {d['target']}" if d["target"] else ""
            out.append(f"  {d['action']}{tgt}: {d['reason']}")
        out.append("")

    if "memory" in r:
        mem = r["memory"]
        out.append("hbm memory:")
        if "pressure" in mem:
            detail = ", ".join(f"{k}={v}" for k, v in
                               mem["pressure"]["by_model"].items())
            out.append(
                f"  pressure evictions: {mem['pressure']['count']} "
                f"({detail}); {mem['pressure']['freed_bytes']} bytes freed")
        if "audit" in mem:
            a = mem["audit"]
            out.append(
                f"  last audit: {a.get('live_bytes')} live, "
                f"{a.get('accounted_bytes')} accounted, "
                f"{a.get('unaccounted_bytes')} unaccounted")
        out.append("")

    if "compile_cache" in r:
        cc = r["compile_cache"]
        out.append("compile cache:")
        out.append(
            f"  lookups: {cc['hits']} hit(s), {cc['misses']} miss(es), "
            f"{cc['stale']} stale ({cc['hit_rate']:.1f}% hit rate); "
            f"{cc['stores']} store(s), {cc['bypasses']} bypass(es)")
        if cc.get("quarantined"):
            reasons = "; ".join(cc.get("quarantine_reasons", ()))
            out.append(f"  quarantined entries: {cc['quarantined']}"
                       + (f" [{reasons}]" if reasons else ""))
        out.append("")

    if "throughput" in r:
        th = r["throughput"]
        out.append("throughput:")
        for e in th.get("fits", ()):
            out.append(
                f"  train.fit: {e['steps'] if e['steps'] is not None else '?'}"
                f" steps, {e['rows'] if e['rows'] is not None else '?'} rows"
                f" in {e['wall_s']:.3f}s "
                f"({e['examples_per_sec']:.1f} examples/sec)")
        if "steps" in th:
            st = th["steps"]
            out.append(
                f"  train.step: {st['logged']} logged steps, last "
                f"step {st['last_step'] if st['last_step'] is not None else '?'}, "
                f"examples/sec last={st['examples_per_sec_last']:.1f} "
                f"max={st['examples_per_sec_max']:.1f}")
        out.append("")

    if "input_pipeline" in r:
        out.append("input pipeline:")
        for e in r["input_pipeline"]:
            rate = e["items"] / e["wall_s"] if e["wall_s"] > 0 else 0.0
            out.append(f"  epoch {e['epoch'] if e['epoch'] is not None else '?'}: "
                       f"{e['items']} items in "
                       f"{e['wall_s']:.3f}s ({rate:.1f} items/sec)")
        out.append("")

    if "bench" in r:
        rows = [[b.get("config", "?"), b.get("value", "-"),
                 b.get("unit", "-"), b.get("vs_baseline", "-")]
                for b in r["bench"]]
        out.append("bench configs:")
        out.extend(_table(rows, ["config", "value", "unit", "vs_baseline"]))
        out.append("")

    if len(out) == 3:  # only the header: nothing recognizable in the log
        out.append("no spans, reliability events, or throughput records "
                   "found")
    return "\n".join(out).rstrip() + "\n"
