"""Streaming input pipeline: sharded, shuffled, resumable datasets.

``FileSource -> ShuffleBuffer -> ParallelDecode -> Batcher -> device``,
every stage checkpointable (``state_dict``/``load_state_dict``) so
training resumes mid-epoch bit-identically. See docs/DATA.md.
"""
from mmlspark_tpu.data.pipeline import (  # noqa: F401
    Batcher,
    Dataset,
    FileSource,
    MapRecords,
    ParallelDecode,
    PipelineIterator,
    Repeat,
    ShuffleBuffer,
    default_decode,
)
from mmlspark_tpu.data.prefetch import DevicePrefetcher  # noqa: F401
