"""DevicePrefetcher: double-buffered host->HBM transfer, as a public API.

Extracted from ``parallel/trainer.py`` so the streaming input pipeline's
terminal ``Dataset.to_device_iterator()`` and ``DistributedTrainer.fit``
share ONE prefetch implementation (``parallel.trainer`` keeps a
back-compat re-export). This module deliberately imports no jax and no
trainer code: the device commit is the injected ``put`` callable, so the
prefetcher composes with any dispatch layer (``trainer.put_batch``, a
plain ``jax.device_put``, or an identity function in host-only tests).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

from mmlspark_tpu.observability import metrics as obsmetrics
from mmlspark_tpu.reliability import watchdog as _watchdog
from mmlspark_tpu.utils import config as mmlconfig


class DevicePrefetcher:
    """Double-buffered host->HBM prefetch (SURVEY.md §7 "streaming host→HBM
    without stalls").

    A background thread pulls host batches — the expensive host work: epoch
    shuffling, tail padding, feature assembly — and queues them ``depth``
    deep. The consuming ``next()`` commits each batch's ``device_put`` on the
    caller's thread and returns immediately: JAX dispatch is asynchronous, so
    the transfer overlaps the still-running previous step and the Python loop
    stays ahead of the device. All JAX runtime calls therefore happen on ONE
    thread — issuing ``device_put`` from the producer thread concurrently
    with a jitted execution aborts flakily inside the multi-device CPU
    runtime (XLA client race), and single-threaded dispatch loses nothing
    because the runtime pipelines the async transfers anyway.
    Exceptions in the producer re-raise at the consuming ``next()``.
    """

    _SENTINEL = object()

    def __init__(self, host_batches: Iterable[Dict[str, Any]],
                 put: Callable[[Dict[str, Any]], Any],
                 depth: Optional[int] = None):
        self.depth = depth if depth is not None else int(
            mmlconfig.get("runtime.prefetch_depth"))
        self._put = put
        self._q: queue.Queue = queue.Queue(maxsize=max(self.depth, 1))
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._done = False
        self._closed = False
        self._telemetry = obsmetrics.metrics_enabled()

        def run():
            # liveness: beats on every produced batch AND on every bounded
            # wait tick — a producer parked on a full queue is healthy
            # (back-pressure), one wedged inside next(host_batches) is the
            # stall the watchdog should catch
            beat = _watchdog.register("data.prefetch")
            try:
                for hb in host_batches:
                    beat.beat()
                    if self._stop.is_set():
                        return
                    # bounded put that notices close(): never blocks forever
                    while not self._stop.is_set():
                        try:
                            self._q.put(hb, timeout=0.1)
                            break
                        except queue.Full:
                            beat.beat()
                            continue
            except BaseException as e:  # surfaced on the consumer side
                self._err = e
            finally:
                beat.close()
                # bounded sentinel put: a full queue must not lose the
                # end-of-stream marker, but close() must still unblock us
                while not self._stop.is_set():
                    try:
                        self._q.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="mmlspark-tpu-prefetch")
        self._thread.start()

    def close(self) -> None:
        """Stop the producer and drop queued host batches. Call from a
        ``finally`` when abandoning the stream early. Idempotent: a second
        call (or a call after the producer already exited) is a no-op —
        the ``TrainCheckpointer.close()`` contract."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # join FIRST (the producer's bounded put notices _stop within 0.1s),
        # then drain — draining before the join can free a slot that the
        # producer immediately refills, keeping a batch buffered
        self._thread.join(timeout=5)
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._done = True

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._done:
            raise StopIteration
        item = self._q.get()
        if self._telemetry:
            obsmetrics.gauge("data.prefetch_queue_depth").set(
                self._q.qsize())
        if item is self._SENTINEL:
            self._done = True
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return self._put(item)
