"""Composable streaming input pipeline: sharded, shuffled, resumable.

The tf.data/Grain-style counterpart of the reference's Spark-partition
ingestion (``Readers.scala``/``BinaryFileReader``): instead of
materializing a whole corpus into a host ``Frame`` before training
(``io.readers.read_images``), a ``Dataset`` describes a stream —

- :class:`FileSource` — deterministic per-host file sharding over the
  ``io.readers`` walk/sample/zip listing (every host lists the same
  files, reads only its contiguous slice);
- :class:`ShuffleBuffer` — seeded windowed shuffle (block permutation;
  the seed folds in the epoch and the block index, so order is a pure
  function of ``(seed, epoch, position)``);
- :class:`ParallelDecode` — a bounded worker pool running ``io.codecs``
  image decode (or any record function) OFF the consumer thread, yielding
  results in submission order; undecodable records drop, counted in the
  ``data.decode_dropped`` metric;
- :class:`Batcher` — fixed-size host batches with ``drop``/``pad``/
  ``keep`` remainder policies (``pad`` zero-fills and masks via a
  ``weight`` column — ``DistributedTrainer``'s pad-and-mask contract),
  plus a ``multi_hot`` pad policy for RAGGED id-list columns (recommender
  sparse features): each record's variable-length id list pads/truncates
  to a fixed slot width with pad id 0 and a per-slot weight mask;
- :meth:`Dataset.to_device_iterator` — the terminal stage: the same
  :class:`~mmlspark_tpu.data.prefetch.DevicePrefetcher` the trainer uses.

Resumability is the design center: every stage's iterator carries explicit
state (``state_dict()`` / ``load_state_dict()`` — epoch, file cursor,
shuffle block index, batch boundary), the dicts are JSON-serializable, and
the contract is *consumed-prefix equivalence*: restoring a snapshot yields
exactly the records an uninterrupted iterator would have yielded after the
snapshot point, bit-for-bit. ``TrainCheckpointer.put_data_state`` persists
these snapshots next to the model checkpoints and
``ResilientTrainLoop.run_dataset`` resumes mid-epoch from them.

Fault sites: ``data.list`` (before the listing), ``data.shuffle`` (before
each block permutes), ``data.decode`` (before each record is handed to the
pool) — plus ``readers.read`` on every blob payload, shared with the eager
readers.
"""
from __future__ import annotations

import random
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.data.prefetch import DevicePrefetcher
from mmlspark_tpu.observability import events as obsevents
from mmlspark_tpu.observability import metrics as obsmetrics
from mmlspark_tpu.reliability import watchdog as _watchdog
from mmlspark_tpu.reliability.faults import fault_site
from mmlspark_tpu.utils import config as mmlconfig

Record = Dict[str, Any]


class PipelineIterator:
    """One stage's stateful iterator.

    ``state_dict()`` captures everything CONSUMED so far — never in-flight
    work (a parallel decode in progress, a half-assembled batch). Restoring
    it re-pulls the uncommitted tail from upstream and replays it through
    the same deterministic transforms, so the resumed stream is
    bit-identical to the uninterrupted one from the snapshot point on.
    """

    def __iter__(self) -> "PipelineIterator":
        return self

    def __next__(self) -> Any:
        raise NotImplementedError

    def state_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        """Release stage resources (decode pools, zip handles). Idempotent."""

    def __enter__(self) -> "PipelineIterator":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False


class Dataset:
    """Declarative description of a streaming input pipeline.

    A ``Dataset`` is cheap and reusable: ``iter(epoch)`` builds a fresh
    :class:`PipelineIterator` chain each call (epoch folds into shuffle
    seeds). The fluent builders mirror the stage classes::

        ds = (FileSource("/data/flowers", recursive=True, process_shard=True)
              .shuffle(window=1024, seed=7)
              .decode()
              .batch(256, remainder="drop"))
        for host_batch in ds:                 # host-side iteration
            ...
        trainer.fit(state, ds)                # or hand it to the trainer

    ``DistributedTrainer.fit`` accepts a Dataset anywhere an iterable of
    host batches is accepted.
    """

    def iter(self, epoch: int = 0) -> PipelineIterator:
        raise NotImplementedError

    def __iter__(self) -> PipelineIterator:
        return self.iter(0)

    def shuffle(self, window: Optional[int] = None,
                seed: int = 0) -> "ShuffleBuffer":
        return ShuffleBuffer(self, window=window, seed=seed)

    def decode(self, fn: Optional[Callable[[Record], Optional[Record]]] = None,
               workers: Optional[int] = None,
               chunk: int = 16) -> "ParallelDecode":
        return ParallelDecode(self, fn=fn, workers=workers, chunk=chunk)

    def map(self, fn: Callable[[Any], Any]) -> "MapRecords":
        return MapRecords(self, fn)

    def batch(self, size: int, remainder: str = "drop",
              multi_hot: Optional[Dict[str, int]] = None) -> "Batcher":
        return Batcher(self, size, remainder=remainder, multi_hot=multi_hot)

    def repeat(self, epochs: Optional[int] = None) -> "Repeat":
        return Repeat(self, epochs=epochs)

    def to_device_iterator(self, put: Callable[[Record], Any],
                           depth: Optional[int] = None,
                           epoch: int = 0) -> DevicePrefetcher:
        """Terminal stage: a DevicePrefetcher committing each host batch via
        ``put`` (usually ``trainer.put_batch``). Depth resolves
        ``data.prefetch_depth`` (0 = fall back to
        ``runtime.prefetch_depth``). NOTE the prefetcher runs AHEAD of the
        consumer, so for checkpointable mid-epoch state drive the raw
        ``iter()`` synchronously instead (``ResilientTrainLoop.run_dataset``
        does)."""
        if depth is None:
            configured = int(mmlconfig.get("data.prefetch_depth"))
            depth = configured if configured > 0 else None
        return DevicePrefetcher(self.iter(epoch), put, depth=depth)


# -- source ------------------------------------------------------------------

class FileSource(Dataset):
    """Deterministic file/zip-entry source over the ``io.readers`` walk.

    The listing (recursive walk, seeded fractional sampling, zip-entry
    expansion, per-process contiguous slice) is exactly
    ``io.readers.list_binary_entries`` — the same files in the same order
    as ``read_binary_files``/``read_images``, so a streamed epoch is
    bit-comparable to the materialized-Frame path. Records are
    ``{"path": str, "bytes": bytes}``; payloads read lazily, one entry at
    a time.
    """

    def __init__(self, path: str, recursive: bool = False,
                 sample_ratio: float = 1.0, inspect_zip: bool = True,
                 seed: int = 0, process_shard: bool = False):
        if not 0.0 < sample_ratio <= 1.0:
            raise ValueError(
                f"sample_ratio must be in (0, 1], got {sample_ratio}")
        self.path = path
        self.recursive = recursive
        self.sample_ratio = sample_ratio
        self.inspect_zip = inspect_zip
        self.seed = seed
        self.process_shard = process_shard

    def iter(self, epoch: int = 0) -> PipelineIterator:
        return _FileSourceIter(self)


class _FileSourceIter(PipelineIterator):
    def __init__(self, src: FileSource):
        fault_site("data.list")
        from mmlspark_tpu.io.readers import list_binary_entries
        self._entries = list_binary_entries(
            src.path, src.recursive, src.sample_ratio, src.inspect_zip,
            src.seed, src.process_shard)
        self._cursor = 0
        self._zip_path: Optional[str] = None
        self._zip = None

    def __next__(self) -> Record:
        if self._cursor >= len(self._entries):
            raise StopIteration
        f, inner = self._entries[self._cursor]
        if inner is None:
            with open(f, "rb") as fh:
                path, data = f, fh.read()
        else:
            if self._zip_path != f:
                self.close()
                import zipfile
                self._zip_path, self._zip = f, zipfile.ZipFile(f)
            path, data = f"{f}/{inner}", self._zip.read(inner)
        self._cursor += 1
        return {"path": path, "bytes": fault_site("readers.read",
                                                  payload=data)}

    def state_dict(self) -> Dict[str, Any]:
        return {"cursor": self._cursor, "n": len(self._entries)}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        if int(state["n"]) != len(self._entries):
            raise ValueError(
                f"FileSource listing changed: snapshot saw {state['n']} "
                f"entries, this run lists {len(self._entries)} — resume "
                "requires the same files on disk")
        self._cursor = int(state["cursor"])

    def close(self) -> None:
        if self._zip is not None:
            self._zip.close()
            self._zip_path, self._zip = None, None


# -- shuffle -----------------------------------------------------------------

class ShuffleBuffer(Dataset):
    """Seeded windowed shuffle: read ``window`` records, permute the block
    with ``random.Random((seed, epoch, block_index))``, yield it, repeat.

    Block (not reservoir) shuffling makes resume exact AND cheap: the
    snapshot is (upstream state at block start, block index, position), so
    a restore re-pulls one window from the restored upstream, re-applies
    the same permutation, and skips to the position — no buffered records
    ever serialize.
    """

    def __init__(self, upstream: Dataset, window: Optional[int] = None,
                 seed: int = 0):
        window = int(window if window is not None
                     else mmlconfig.get("data.shuffle_window"))
        if window < 1:
            raise ValueError(f"shuffle window must be >= 1, got {window}")
        self.upstream = upstream
        self.window = window
        self.seed = seed

    def iter(self, epoch: int = 0) -> PipelineIterator:
        return _ShuffleIter(self.upstream.iter(epoch), self.window,
                            self.seed, epoch)


class _ShuffleIter(PipelineIterator):
    def __init__(self, up: PipelineIterator, window: int, seed: int,
                 epoch: int):
        self._up = up
        self._window = window
        self._seed = seed
        self._epoch = epoch
        self._block: List[Any] = []
        self._pos = 0
        self._blocks_done = 0                 # buffer refill count
        self._up_at_block = up.state_dict()   # upstream state at block start

    def __next__(self) -> Any:
        while self._pos >= len(self._block):
            self._refill()  # raises StopIteration when upstream is dry
        item = self._block[self._pos]
        self._pos += 1
        return item

    def _refill(self) -> None:
        snap = self._up.state_dict()
        block: List[Any] = []
        while len(block) < self._window:
            try:
                block.append(next(self._up))
            except StopIteration:
                break
        if not block:
            raise StopIteration
        fault_site("data.shuffle")
        # str seeding hashes with sha512 -> stable across interpreters
        # (tuple seeding is hash-based: deprecated and PYTHONHASHSEED-
        # dependent, which would break cross-run resume determinism)
        rng = random.Random(f"{self._seed}:{self._epoch}:{self._blocks_done}")
        rng.shuffle(block)
        self._up_at_block = snap
        self._block = block
        self._pos = 0
        self._blocks_done += 1
        if obsmetrics.metrics_enabled():
            obsmetrics.gauge("data.shuffle_fill").set(len(block))

    def state_dict(self) -> Dict[str, Any]:
        return {"blocks": self._blocks_done, "pos": self._pos,
                "upstream": self._up_at_block}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        blocks, pos = int(state["blocks"]), int(state["pos"])
        self._up.load_state_dict(state["upstream"])
        self._block, self._pos = [], 0
        self._blocks_done = max(blocks - 1, 0)
        self._up_at_block = self._up.state_dict()
        if blocks > 0:
            self._refill()  # replays block `blocks-1` with its original perm
        self._pos = pos

    def close(self) -> None:
        self._up.close()


# -- parallel decode ---------------------------------------------------------

def default_decode(record: Record) -> Optional[Record]:
    """``{"path","bytes"}`` -> ``{"path","image"}`` via ``io.codecs``;
    ``None`` (drop) when undecodable — ``ImageReader.scala:55-59``
    semantics."""
    from mmlspark_tpu.io.codecs import decode_image
    arr = decode_image(record["bytes"])
    if arr is None:
        return None
    return {"path": record["path"], "image": arr}


class ParallelDecode(Dataset):
    """Bounded worker pool applying ``fn`` (default: image decode) off the
    consumer thread, in submission order.

    Records submit in chunks of ``chunk`` (one future per chunk — a
    per-record future's executor round-trip costs more than a small image
    decode, so chunking is what lets fast decodes still win); up to
    ``2 * workers`` chunks stay in flight, and results pop strictly in
    submission order, so output is deterministic regardless of worker
    scheduling. ``fn`` returning ``None`` drops the record (counted in the
    ``data.decode_dropped`` metric). The snapshot commits only CONSUMED
    records — per record, not per chunk — so a crash mid-flight just
    re-decodes the in-flight tail on resume.
    """

    def __init__(self, upstream: Dataset,
                 fn: Optional[Callable[[Record], Optional[Record]]] = None,
                 workers: Optional[int] = None, chunk: int = 16):
        workers = int(workers if workers is not None
                      else mmlconfig.get("data.decode_workers"))
        if workers < 1:
            raise ValueError(f"decode workers must be >= 1, got {workers}")
        if chunk < 1:
            raise ValueError(f"decode chunk must be >= 1, got {chunk}")
        self.upstream = upstream
        self.fn = fn if fn is not None else default_decode
        self.workers = workers
        self.chunk = chunk

    def iter(self, epoch: int = 0) -> PipelineIterator:
        return _DecodeIter(self.upstream.iter(epoch), self.fn, self.workers,
                           self.chunk)


class _DecodeIter(PipelineIterator):
    def __init__(self, up: PipelineIterator,
                 fn: Callable[[Record], Optional[Record]], workers: int,
                 chunk: int):
        self._up = up
        self._fn = fn
        self._chunk = chunk
        self._depth = workers * 2          # in-flight CHUNKS
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="mmlspark-tpu-decode")
        self._inflight: deque = deque()    # (future -> [out], [snap]) chunks
        self._ready: deque = deque()       # (out, snap) per record, in order
        self._exhausted = False
        self._consumed = up.state_dict()
        self._telemetry = obsmetrics.metrics_enabled()
        # liveness: workers beat per decoded record (atomic write, shared
        # handle) — a hung codec shows as this heartbeat going silent
        self._hb = _watchdog.register("data.decode")

    def _run(self, recs: List[Record]) -> List[Optional[Record]]:
        if not self._telemetry:
            out = []
            for r in recs:
                out.append(self._fn(r))
                self._hb.beat()
            return out
        out = []
        hist = obsmetrics.histogram("data.decode_seconds")
        for r in recs:
            t0 = obsevents.perf()
            out.append(self._fn(r))
            self._hb.beat()
            hist.observe(obsevents.perf() - t0)
        return out

    def _top_up(self) -> None:
        while not self._exhausted and len(self._inflight) < self._depth:
            recs: List[Record] = []
            snaps: List[Dict[str, Any]] = []
            while len(recs) < self._chunk:
                try:
                    rec = next(self._up)
                except StopIteration:
                    self._exhausted = True
                    break
                # the fault site fires on the CONSUMER thread as each record
                # joins a chunk, so Nth-hit plans stay deterministic (worker
                # scheduling is not)
                fault_site("data.decode")
                recs.append(rec)
                snaps.append(self._up.state_dict())
            if not recs:
                return
            self._inflight.append((self._pool.submit(self._run, recs), snaps))

    def __next__(self) -> Record:
        while True:
            while not self._ready:
                self._top_up()
                if not self._inflight:
                    raise StopIteration
                fut, snaps = self._inflight.popleft()
                if self._telemetry:
                    t0 = obsevents.perf()
                    outs = fut.result()
                    obsmetrics.histogram(
                        "data.decode_wait_seconds").observe(
                        obsevents.perf() - t0)
                    obsmetrics.gauge("data.decode_queue_depth").set(
                        len(self._inflight))
                else:
                    outs = fut.result()
                self._ready.extend(zip(outs, snaps))
            out, snap = self._ready.popleft()
            self._consumed = snap
            if out is None:
                obsmetrics.counter("data.decode_dropped").inc()
                continue
            return out

    def state_dict(self) -> Dict[str, Any]:
        return {"upstream": self._consumed}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._abandon_inflight()
        self._up.load_state_dict(state["upstream"])
        self._consumed = self._up.state_dict()
        self._exhausted = False

    def _abandon_inflight(self) -> None:
        for fut, _snaps in self._inflight:
            fut.cancel()
        self._inflight.clear()
        self._ready.clear()

    def close(self) -> None:
        self._hb.close()
        self._abandon_inflight()
        self._pool.shutdown(wait=True, cancel_futures=True)
        self._up.close()


# -- map ---------------------------------------------------------------------

class MapRecords(Dataset):
    """1:1 transform on the consumer thread (parsing, label derivation).
    ``fn`` must be deterministic — it is replayed on resume."""

    def __init__(self, upstream: Dataset, fn: Callable[[Any], Any]):
        self.upstream = upstream
        self.fn = fn

    def iter(self, epoch: int = 0) -> PipelineIterator:
        return _MapIter(self.upstream.iter(epoch), self.fn)


class _MapIter(PipelineIterator):
    def __init__(self, up: PipelineIterator, fn: Callable[[Any], Any]):
        self._up = up
        self._fn = fn

    def __next__(self) -> Any:
        return self._fn(next(self._up))

    def state_dict(self) -> Dict[str, Any]:
        return {"upstream": self._up.state_dict()}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._up.load_state_dict(state["upstream"])

    def close(self) -> None:
        self._up.close()


# -- batch -------------------------------------------------------------------

class Batcher(Dataset):
    """Stack ``size`` records into one host-batch dict of numpy columns.

    Remainder policies match the trainer's global-batch contract (every
    step must see the same batch shape for jit shape stability):

    - ``"drop"`` — discard a short final batch;
    - ``"pad"``  — zero-fill to ``size`` and mask via a float32 ``weight``
      column (1.0 real / 0.0 pad) — the ``learners._pad_xyw`` convention;
    - ``"keep"`` — yield the short batch as-is (host-side consumers only).

    Numeric record fields stack (shapes must agree — resize images first
    via ``map``); strings/bytes/objects become object columns.

    ``multi_hot`` maps RAGGED id-list columns to a fixed slot width (the
    recommender's sparse-feature wire contract): each record's
    variable-length id sequence pads to ``slots`` with
    ``MULTI_HOT_PAD_ID`` (truncating overflow deterministically from the
    front-kept side) and gains a float32 ``<col>_weight`` mask column
    (1.0 real slot / 0.0 pad), so downstream embedding bag lookups see
    static shapes and zero-weighted pads — the same pad-and-mask
    convention ``embed.tables`` reserves row 0 for. The transform is
    stateless, so snapshot/resume bit-identity is untouched.
    """

    REMAINDERS = ("drop", "pad", "keep")

    def __init__(self, upstream: Dataset, size: int, remainder: str = "drop",
                 multi_hot: Optional[Dict[str, int]] = None):
        if size < 1:
            raise ValueError(f"batch size must be >= 1, got {size}")
        if remainder not in self.REMAINDERS:
            raise ValueError(f"remainder must be one of {self.REMAINDERS}, "
                             f"got {remainder!r}")
        if multi_hot:
            for col, slots in multi_hot.items():
                if int(slots) < 1:
                    raise ValueError(
                        f"multi_hot slots must be >= 1, got {col}={slots}")
        self.upstream = upstream
        self.size = size
        self.remainder = remainder
        self.multi_hot = dict(multi_hot or {})

    def iter(self, epoch: int = 0) -> PipelineIterator:
        return _BatchIter(self.upstream.iter(epoch), self.size,
                          self.remainder, self.multi_hot)


# pad slot id for multi-hot columns; matches embed.tables.PAD_ID (row 0
# of every embedding table is the reserved all-zero pad row)
MULTI_HOT_PAD_ID = 0


def _pad_multi_hot(rows: List[Record],
                   multi_hot: Dict[str, int]) -> List[Record]:
    """Normalize ragged id-list columns to fixed ``(slots,)`` int32 rows
    plus per-slot weight masks. Pure per-record transform — no state, so
    the batch boundary snapshot stays the only resume cursor."""
    out: List[Record] = []
    for r in rows:
        r = dict(r)
        for col, slots in multi_hot.items():
            ids = np.asarray(r.get(col, ()), np.int64).reshape(-1)[:slots]
            padded = np.full(slots, MULTI_HOT_PAD_ID, np.int32)
            padded[:ids.size] = ids
            mask = np.zeros(slots, np.float32)
            mask[:ids.size] = 1.0
            r[col] = padded
            r[f"{col}_weight"] = mask
        out.append(r)
    return out


def _stack_records(rows: List[Record], pad_to: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
    n = len(rows)
    out: Dict[str, np.ndarray] = {}
    for key in rows[0]:
        vals = [r[key] for r in rows]
        first = vals[0]
        if isinstance(first, np.ndarray) and first.dtype != np.object_:
            col = np.stack(vals)
        elif isinstance(first, (bool, int, float, np.bool_, np.integer,
                                np.floating)):
            col = np.asarray(vals)
        else:
            col = np.empty(n, dtype=np.object_)
            for i, v in enumerate(vals):
                col[i] = v
        out[key] = col
    if pad_to is not None and pad_to > n:
        for key, col in out.items():
            if col.dtype == np.object_:
                padded = np.empty(pad_to, dtype=np.object_)
                padded[:n] = col
                out[key] = padded
            else:
                pad = np.zeros((pad_to - n,) + col.shape[1:], col.dtype)
                out[key] = np.concatenate([col, pad])
        weight = out.get("weight")
        if weight is None:
            weight = np.ones(pad_to, np.float32)
        weight = np.asarray(weight, np.float32).copy()
        weight[n:] = 0.0
        out["weight"] = weight
    return out


class _BatchIter(PipelineIterator):
    def __init__(self, up: PipelineIterator, size: int, remainder: str,
                 multi_hot: Optional[Dict[str, int]] = None):
        self._up = up
        self._size = size
        self._remainder = remainder
        self._multi_hot = dict(multi_hot or {})
        self._boundary = up.state_dict()  # upstream state at last batch edge

    def __next__(self) -> Dict[str, np.ndarray]:
        rows: List[Record] = []
        while len(rows) < self._size:
            try:
                rows.append(next(self._up))
            except StopIteration:
                break
        if not rows:
            raise StopIteration
        if self._multi_hot:
            rows = _pad_multi_hot(rows, self._multi_hot)
        if len(rows) < self._size:
            if self._remainder == "drop":
                raise StopIteration
            pad_to = self._size if self._remainder == "pad" else None
            batch = _stack_records(rows, pad_to=pad_to)
        else:
            batch = _stack_records(rows)
        self._boundary = self._up.state_dict()
        return batch

    def state_dict(self) -> Dict[str, Any]:
        return {"upstream": self._boundary}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._up.load_state_dict(state["upstream"])
        self._boundary = self._up.state_dict()

    def close(self) -> None:
        self._up.close()


# -- repeat ------------------------------------------------------------------

class Repeat(Dataset):
    """Re-run the inner pipeline for ``epochs`` passes (``None`` = forever),
    folding the epoch number into every shuffle seed downstream of the
    source. Emits a ``data.epoch`` telemetry event at each epoch boundary
    (epoch, items, wall_s) — the run report's "input pipeline" section."""

    def __init__(self, upstream: Dataset, epochs: Optional[int] = None):
        if epochs is not None and epochs < 1:
            raise ValueError(f"epochs must be >= 1 or None, got {epochs}")
        self.upstream = upstream
        self.epochs = epochs

    def iter(self, epoch: int = 0) -> PipelineIterator:
        return _RepeatIter(self.upstream, self.epochs, start_epoch=epoch)


class _RepeatIter(PipelineIterator):
    def __init__(self, ds: Dataset, epochs: Optional[int], start_epoch: int):
        self._ds = ds
        self._epochs = epochs
        self._epoch = start_epoch
        self._inner: Optional[PipelineIterator] = ds.iter(start_epoch)
        self._items = 0
        self._t0 = obsevents.perf()

    def __next__(self) -> Any:
        while True:
            if self._inner is None:
                raise StopIteration
            try:
                item = next(self._inner)
            except StopIteration:
                self._roll_epoch()
                continue
            self._items += 1
            return item

    def _roll_epoch(self) -> None:
        if obsevents.events_enabled():
            obsevents.emit("event", "data.epoch", epoch=self._epoch,
                           items=self._items,
                           wall_s=round(obsevents.perf() - self._t0, 6))
        empty = self._items == 0
        self._inner.close()
        self._epoch += 1
        self._items = 0
        self._t0 = obsevents.perf()
        if empty or (self._epochs is not None
                     and self._epoch >= self._epochs):
            # an empty pass on an infinite repeat would spin forever
            self._inner = None
            raise StopIteration
        self._inner = self._ds.iter(self._epoch)

    def state_dict(self) -> Dict[str, Any]:
        return {"epoch": self._epoch, "items": self._items,
                "inner": None if self._inner is None
                else self._inner.state_dict()}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        if self._inner is not None:
            self._inner.close()
        self._epoch = int(state["epoch"])
        self._items = int(state["items"])
        self._t0 = obsevents.perf()
        if state["inner"] is None:
            self._inner = None
        else:
            self._inner = self._ds.iter(self._epoch)
            self._inner.load_state_dict(state["inner"])

    def close(self) -> None:
        if self._inner is not None:
            self._inner.close()
