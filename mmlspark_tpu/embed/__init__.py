"""Sharded-embedding recommender subsystem (docs/RECOMMENDER.md).

Model-parallel embedding tables deliberately too large for one chip:
rows shard over the ``tensor`` mesh axis, lookups run as one fused
``shard_map`` program (bucketize ids per shard -> all-to-all the
requests -> local gather -> all-to-all the rows back -> segment-sum the
multi-hot bags), and the sparse-gradient path scatter-adds straight
into each chip's row shard. ``model.py`` wraps the tables in a
DLRM-lite two-tower module registered in the model zoo, so the same
tables back ``DistributedTrainer.fit`` training AND online fleet
scoring through ``serve/``.
"""
from mmlspark_tpu.embed.tables import (EmbeddingCollection, EmbeddingTable,
                                       RowResidency, bag_lookup_reference,
                                       make_bag_lookup, sparse_table_grads)

__all__ = ["EmbeddingCollection", "EmbeddingTable", "RowResidency",
           "bag_lookup_reference", "make_bag_lookup", "sparse_table_grads"]
