"""Row-sharded embedding tables: fused sharded lookup + sparse update.

The scale problem this module exists for: recommender tables (users,
items, ads) are the one model component that grows with the BUSINESS,
not the architecture — 10^8 rows x 64 dims does not fit one chip, and
never will. So tables shard along the ``tensor`` mesh axis by ROW
(``parallel/sharding.py::embedding_table_sharding``: chip t holds rows
``[t*R/T, (t+1)*R/T)``), and the lookup/update paths are written so no
device ever materializes a full table, a full gather, or a dense
gradient for rows it does not own.

The fused lookup is ONE ``shard_map`` program per (batch, slots) shape:

1. **bucketize** — every device holds the full id block for its batch
   shard (ids are replicated over ``tensor``); it sorts the flat ids by
   owning shard (``owner = id // rows_per_shard``) and packs each
   shard's requests into a fixed-capacity bucket row;
2. **all-to-all** the request buckets over ``tensor`` — device t now
   holds every shard's requests for the rows *t* owns;
3. **local gather** — one ``table_shard[requests]`` per device, rows
   it physically holds, no cross-device indexing;
4. **all-to-all** the gathered rows back, un-permute into the original
   id order;
5. **segment-sum** the weighted multi-hot bags on device — the output
   is (batch, dim), sharded over the data axes like any activation.

Every step is static-shaped (bucket capacity = the id block size), so
one XLA program serves every batch of that shape — no retrace, no
host-side indirection, and the arithmetic per id is EXACTLY the
unsharded reference's (row fetch then the same segment-sum), which is
what makes the sharded path bit-identical to
:func:`bag_lookup_reference` on the same inputs.

The backward pass never builds a dense dLoss/dTable on one device
either, and it never MOVES one: :func:`sparse_table_grads` all-gathers
the (ids, weighted cotangents) over the data axes — O(batch) bytes —
and scatter-adds each bag cotangent into the owning shard's rows
(``.at[rows].add`` lowers to ``lax.scatter-add``). The gradient is
born with the table's own sharding and replicated over data without a
dense O(table) psum, so the optimizer update stays model-parallel end
to end.
:func:`make_bag_lookup` packages both directions as a ``custom_vjp``
so ``DistributedTrainer``'s plain ``jax.grad`` — donation, metrics
ring and all — trains through the fused path unchanged.

Lint Rule 17 makes this file the ONLY home for embedding
gather/scatter and id-bucketing arithmetic (`# lint: allow-embed`
escapes elsewhere must justify themselves in review).
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.parallel.sharding import (embedding_lookup_specs,
                                            embedding_table_sharding,
                                            shard_map_compat,
                                            tensor_axis_size)
from mmlspark_tpu.utils import config as mmlconfig

# id 0 is the pad slot in every table: lookups still FETCH row 0 (static
# shapes — masking happens via the weight, not the gather), so row 0 is
# reserved and real ids start at 1.
PAD_ID = 0


class EmbeddingTable(NamedTuple):
    """One logical table: ``rows`` ids (including the pad row 0) of
    ``dim`` features. ``rows`` is padded up to the tensor-axis multiple
    at placement time; the pad rows are dead weight that keeps every
    shard the same static shape."""
    name: str
    rows: int
    dim: int

    def padded_rows(self, mesh) -> int:
        t = tensor_axis_size(mesh)
        return -(-self.rows // t) * t

    def logical_bytes(self, dtype=np.float32) -> int:
        return int(self.rows) * int(self.dim) * np.dtype(dtype).itemsize


def _flat_ids(ids: jnp.ndarray) -> jnp.ndarray:
    return ids.reshape(-1).astype(jnp.int32)


def bag_lookup_reference(table: jnp.ndarray, ids: jnp.ndarray,
                         weights: jnp.ndarray) -> jnp.ndarray:
    """Unsharded reference bag lookup: gather + weighted segment-sum.

    The numerics ground truth the fused sharded path must match
    bit-for-bit — same rows fetched, same segment-sum order.
    """
    b, slots = ids.shape
    emb = jnp.take(table, _flat_ids(ids), axis=0)        # (b*slots, dim)
    vals = emb * weights.reshape(-1)[:, None]
    seg = jnp.repeat(jnp.arange(b, dtype=jnp.int32), slots)
    return jax.ops.segment_sum(vals, seg, num_segments=b)


def _bucketize(flat: jnp.ndarray, rows_per_shard: int, t: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort a flat id block by owning shard and pack per-shard request
    buckets. Returns ``(buckets, order, sorted_owner, pos)`` where
    ``buckets[t, c]`` is the c-th local row requested from shard t
    (capacity = the whole block — worst case every id on one shard)."""
    n = flat.shape[0]
    owner = flat // rows_per_shard
    local = flat - owner * rows_per_shard
    order = jnp.argsort(owner, stable=True)
    sorted_owner = owner[order]
    sorted_local = local[order]
    start = jnp.searchsorted(sorted_owner, jnp.arange(t, dtype=flat.dtype))
    pos = jnp.arange(n, dtype=jnp.int32) - start[sorted_owner].astype(jnp.int32)
    buckets = jnp.zeros((t, n), flat.dtype).at[sorted_owner, pos].set(
        sorted_local)
    return buckets, order, sorted_owner, pos


def make_fused_lookup(mesh):
    """The fused sharded bag lookup ``(table, ids, weights) -> bags``
    for this mesh — one shard_map program per input shape. Falls back
    to the reference path when the mesh has no model axis, or when
    ``embed.fused_lookup`` is off (GSPMD partitions the reference
    gather against the sharded table — the numerics-triage escape)."""
    t = tensor_axis_size(mesh)
    if mesh is None or t <= 1 or not mmlconfig.get("embed.fused_lookup"):
        return bag_lookup_reference
    table_spec, ids_spec, out_spec = embedding_lookup_specs(mesh)

    def body(tab, idl, wl):
        rows_per_shard = tab.shape[0]
        b, slots = idl.shape
        flat = _flat_ids(idl)
        buckets, order, sorted_owner, pos = _bucketize(flat, rows_per_shard, t)
        # requests OUT: row j of the result is what device j asked of us
        req = jax.lax.all_to_all(buckets, "tensor", 0, 0, tiled=True)
        got = jnp.take(tab, req, axis=0)              # (t, n, dim) local rows
        # rows BACK: bucket j of the result is what device j answered
        back = jax.lax.all_to_all(got, "tensor", 0, 0, tiled=True)
        semb = back[sorted_owner, pos]                # sorted request order
        emb = jnp.zeros_like(semb).at[order].set(semb)  # original order
        vals = emb * wl.reshape(-1)[:, None]
        seg = jnp.repeat(jnp.arange(b, dtype=jnp.int32), slots)
        return jax.ops.segment_sum(vals, seg, num_segments=b)

    fused = shard_map_compat(body, mesh, in_specs=(table_spec, ids_spec,
                                                   ids_spec),
                             out_specs=out_spec, check_vma=False)

    def lookup(table, ids, weights):
        return fused(table, ids.astype(jnp.int32),
                     weights.astype(table.dtype))
    return lookup


def _reference_table_grad(rows: int, ids: jnp.ndarray, weights: jnp.ndarray,
                          grad_bags: jnp.ndarray) -> jnp.ndarray:
    """Unsharded sparse table gradient: scatter-add each bag cotangent
    into the rows its ids touched (dBag/dRow is the weight)."""
    dim = grad_bags.shape[-1]
    b, slots = ids.shape
    vals = (grad_bags[:, None, :] * weights[..., None]).reshape(-1, dim)
    return jnp.zeros((rows, dim), grad_bags.dtype).at[
        _flat_ids(ids)].add(vals)


def make_sparse_grad(mesh):
    """``(table_like, ids, weights, grad_bags) -> grad_table`` with the
    gradient born row-sharded AND the cross-device exchange kept
    SPARSE: the (ids, weighted cotangents) — O(batch) bytes — are
    all-gathered over the data axes, then each device scatter-adds the
    full batch's contributions for the rows it owns. The obvious
    alternative (scatter the local batch shard, psum the dense grad
    over data) moves O(table) bytes per axis per step — for a table
    that by design exceeds a chip, that psum IS the step time."""
    t = tensor_axis_size(mesh)
    if mesh is None or t <= 1 or not mmlconfig.get("embed.fused_lookup"):
        return lambda tab, ids, w, g: _reference_table_grad(
            tab.shape[0], ids, w, g)
    table_spec, ids_spec, _ = embedding_lookup_specs(mesh)
    from mmlspark_tpu.parallel.sharding import active_batch_axes
    data_axes = active_batch_axes(mesh)

    def body(tab, idl, wl, gl):
        rows_per_shard = tab.shape[0]
        dim = gl.shape[-1]
        if data_axes:
            # sparse exchange: every device sees every (id, cotangent)
            # pair; tiled gather along the batch dim keeps global batch
            # order, so the scatter below adds in the reference order
            idl = jax.lax.all_gather(idl, data_axes, axis=0, tiled=True)
            wl = jax.lax.all_gather(wl, data_axes, axis=0, tiled=True)
            gl = jax.lax.all_gather(gl, data_axes, axis=0, tiled=True)
        flat = _flat_ids(idl)
        owner = flat // rows_per_shard
        local = flat - owner * rows_per_shard
        mine = owner == jax.lax.axis_index("tensor")
        vals = (gl[:, None, :] * wl[..., None]).reshape(-1, dim)
        vals = jnp.where(mine[:, None], vals, 0.0)
        rows = jnp.where(mine, local, 0)
        # every data replica scatters the SAME full-batch contributions,
        # so the grad comes out replicated over data with no psum
        return jnp.zeros_like(tab).at[rows].add(vals)   # lax.scatter-add

    sharded = shard_map_compat(
        body, mesh, in_specs=(table_spec, ids_spec, ids_spec, ids_spec),
        out_specs=table_spec, check_vma=False)

    def grad_fn(table_like, ids, weights, grad_bags):
        return sharded(table_like, ids.astype(jnp.int32),
                       weights.astype(grad_bags.dtype), grad_bags)
    return grad_fn


def sparse_table_grads(mesh, table: jnp.ndarray, ids: jnp.ndarray,
                       weights: jnp.ndarray,
                       grad_bags: jnp.ndarray) -> jnp.ndarray:
    """One-shot convenience over :func:`make_sparse_grad`."""
    return make_sparse_grad(mesh)(table, ids, weights, grad_bags)


def make_bag_lookup(mesh=None):
    """A DIFFERENTIABLE bag lookup for this mesh: forward is the fused
    all-to-all path (reference path when unsharded), backward is the
    sparse scatter-add gradient — so a flax module calling this trains
    through ``jax.grad``/``DistributedTrainer`` with the table gradient
    computed sparse and sharded, never as a dense dL/dTable matmul.

    ``weights`` are treated as constants (they are pad masks and
    frequency features, not trainables): their cotangent is zero, which
    is what lets the backward pass skip re-materializing the gathered
    rows entirely — the residuals are just ``(ids, weights)``.
    """
    lookup = make_fused_lookup(mesh)
    grad_fn = make_sparse_grad(mesh)

    @jax.custom_vjp
    def bag_lookup(table, ids, weights):
        return lookup(table, ids, weights)

    def fwd(table, ids, weights):
        # the table rides the residuals for its SHAPE only (the sparse
        # grad never reads its values — XLA DCEs the dependency); it is
        # the same buffer the surrounding step already keeps live
        return lookup(table, ids, weights), (table, ids, weights)

    def bwd(res, grad_bags):
        table, ids, weights = res
        grad_table = grad_fn(table, ids, weights, grad_bags)
        zero_ids = np.zeros(ids.shape, jax.dtypes.float0) \
            if jnp.issubdtype(ids.dtype, jnp.integer) \
            else jnp.zeros_like(ids)
        return grad_table, zero_ids, jnp.zeros_like(weights)

    bag_lookup.defvjp(fwd, bwd)
    return bag_lookup


class EmbeddingCollection:
    """A named set of row-sharded tables plus their lookup/update
    machinery, bound to one mesh (or none, for the single-device
    reference).

    Usage::

        coll = EmbeddingCollection([EmbeddingTable("user", 100_000, 64),
                                    EmbeddingTable("item", 200_000, 64)],
                                   mesh=mesh)
        tables = coll.place(coll.init(seed=0))       # sharded residency
        bags = coll.lookup(tables, {"user": (ids, w), "item": (ids2, w2)})
        grads = coll.grads(tables, batch, grad_bags)  # scatter-add, sharded
        tables = coll.sgd_update(tables, grads, lr=0.05)
    """

    def __init__(self, tables: Sequence[EmbeddingTable], mesh=None,
                 dtype=jnp.float32):
        names = [t.name for t in tables]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate table names: {names}")
        self.tables: Dict[str, EmbeddingTable] = {t.name: t for t in tables}
        self.mesh = mesh
        self.dtype = dtype
        self._lookup = make_fused_lookup(mesh)
        self._grad = make_sparse_grad(mesh)

    # -- residency -----------------------------------------------------------
    def init(self, seed: int = 0) -> Dict[str, np.ndarray]:
        """Host-side init (scaled-normal rows, pad row zero), PADDED to
        the mesh's shard multiple — the one set of values every mesh
        shape loads, the way test_mesh2d's host init keeps topologies
        comparable."""
        out: Dict[str, np.ndarray] = {}
        for name, spec in sorted(self.tables.items()):
            rng = np.random.default_rng((seed, hash(name) & 0xFFFF))
            arr = rng.normal(0.0, spec.dim ** -0.5,
                             size=(spec.padded_rows(self.mesh), spec.dim))
            arr = arr.astype(np.dtype(self.dtype))
            arr[PAD_ID] = 0.0
            arr[spec.rows:] = 0.0       # shard-padding rows
            out[name] = arr
        return out

    def place(self, host_tables: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Host arrays -> mesh placement in ONE hop per table: each chip
        receives only its row shard (``device_put`` against the
        NamedSharding), so a table bigger than one chip's HBM never
        materializes a full copy on any device."""
        if self.mesh is None:
            return {k: jnp.asarray(v) for k, v in host_tables.items()}
        sh = embedding_table_sharding(self.mesh)
        with self.mesh:
            return {k: jax.device_put(v, sh)
                    for k, v in host_tables.items()}

    # -- compute -------------------------------------------------------------
    def lookup(self, tables: Dict[str, Any],
               batch: Dict[str, Tuple[Any, Any]]) -> Dict[str, jnp.ndarray]:
        """Fused sharded bag lookup per table; ``batch`` maps table name
        to ``(ids, weights)`` of shape (b, slots)."""
        return {name: self._lookup(tables[name], ids, w)
                for name, (ids, w) in batch.items()}

    def grads(self, tables: Dict[str, Any],
              batch: Dict[str, Tuple[Any, Any]],
              grad_bags: Dict[str, jnp.ndarray]) -> Dict[str, Any]:
        return {name: self._grad(tables[name], ids, w, grad_bags[name])
                for name, (ids, w) in batch.items()}

    def sgd_update(self, tables: Dict[str, Any], grads: Dict[str, Any],
                   lr: float) -> Dict[str, Any]:
        """The sparse-update half of a train step: row-sharded
        ``table - lr * grad``, shapes and shardings preserved so the
        result re-donates into the next step."""
        return {name: tables[name] - lr * grads[name] for name in tables}

    # -- accounting ----------------------------------------------------------
    def logical_bytes(self) -> int:
        """Bytes of the full (unsharded) tables — the number that must
        EXCEED one chip's budget for the workload to be honest about
        crossing the chip (bench lane's ``crosses_chip``). Byte math for
        DEVICE arrays stays in observability/memory.py (Rule 11); this
        is spec arithmetic over the declared shapes."""
        itemsize = jnp.zeros((), self.dtype).dtype.itemsize
        return sum(t.padded_rows(self.mesh) * t.dim * itemsize
                   for t in self.tables.values())


class RowResidency:
    """Frequency-capped per-row hot pool over one host master table.

    PR 17's eviction is whole-table: the registry LRU drops a model's
    ENTIRE ``kind="table"`` ledger line when the warm set overflows
    ``runtime.device_cache_mb``. That is the right lever when the table
    fits one budget slot, and the wrong one when it doesn't — a table
    that is 10x the budget can still serve from residency because real
    id traffic is Zipfian: a small hot set covers most lookups. This
    pool is the per-row refinement: a bounded pool of hot rows over a
    host master, admitting rows on first touch and evicting the
    COLDEST rows first when full.

    "Frequency-capped": each resident row keeps an access counter
    capped at ``freq_cap``; eviction victims sort by
    ``(capped_frequency, last_touch)`` ascending — cold-and-stale rows
    go first. The cap bounds how long a HISTORICALLY hot row can
    outrank a NEWLY hot one: past ~``freq_cap`` touches every hot row
    looks equally hot and recency breaks the tie, so a shifted working
    set turns the pool over in O(capacity) admissions instead of never
    (the classic uncapped-LFU failure).

    Ledger contract (the PR 17 invariant, kept at row granularity):
    resident bytes are re-published to the process ledger as
    ``kind="table"`` under ``model`` after every admit/evict, and
    :meth:`close` frees the pool and reconciles the line to ZERO.
    Lookups are bit-identical to indexing the master directly — rows
    are admitted by copy, never transformed.
    """

    def __init__(self, model: str, master: np.ndarray,
                 capacity_rows: int, freq_cap: int = 15, ledger=None):
        if capacity_rows <= 0:
            raise ValueError(f"capacity_rows must be > 0, got "
                             f"{capacity_rows}")
        if freq_cap <= 0:
            raise ValueError(f"freq_cap must be > 0, got {freq_cap}")
        from mmlspark_tpu.observability import memory as devmem
        self.model = str(model)
        self._master = master
        self._cap = int(capacity_rows)
        self._freq_cap = int(freq_cap)
        self._ledger = ledger if ledger is not None else devmem.get_ledger()
        self._row_bytes = devmem.nbytes_of(master.shape[1:], master.dtype)
        self._pool = np.zeros((self._cap,) + master.shape[1:], master.dtype)
        self._slot: Dict[int, int] = {}      # id -> pool slot
        self._freq: Dict[int, int] = {}      # id -> capped touch count
        self._touch: Dict[int, int] = {}     # id -> logical tick
        self._free = list(range(self._cap - 1, -1, -1))
        self._tick = 0
        self._closed = False
        self.evictions = 0
        self.misses = 0
        self.hits = 0
        self._charge()

    def _charge(self) -> None:
        self._ledger.set_bytes(self.model, "table",
                               len(self._slot) * self._row_bytes)

    def _evict_cold(self, n: int) -> None:
        # coldest first: lowest capped frequency, then stalest touch —
        # deterministic id tiebreak so two runs evict identically
        victims = sorted(self._slot,
                         key=lambda i: (self._freq[i], self._touch[i], i))
        for rid in victims[:n]:
            self._free.append(self._slot.pop(rid))
            del self._freq[rid], self._touch[rid]
            self.evictions += 1

    def lookup(self, ids: Sequence[int]) -> np.ndarray:
        """Rows for ``ids`` (host-order, bit-identical to
        ``master[ids]``), touching/admitting each id through the pool."""
        if self._closed:
            raise RuntimeError(f"RowResidency {self.model!r} is closed")
        out = np.empty((len(ids),) + self._master.shape[1:],
                       self._master.dtype)
        for j, rid in enumerate(ids):
            rid = int(rid)
            self._tick += 1
            slot = self._slot.get(rid)
            if slot is None:
                self.misses += 1
                if not self._free:
                    self._evict_cold(1)
                slot = self._free.pop()
                self._pool[slot] = self._master[rid]
                self._slot[rid] = slot
                self._freq[rid] = 1
            else:
                self.hits += 1
                self._freq[rid] = min(self._freq[rid] + 1, self._freq_cap)
            self._touch[rid] = self._tick
            out[j] = self._pool[slot]
        self._charge()
        return out

    # -- observability -------------------------------------------------------
    @property
    def resident_rows(self) -> int:
        return len(self._slot)

    def resident_bytes(self) -> int:
        return len(self._slot) * self._row_bytes

    def stats(self) -> Dict[str, int]:
        return {"resident_rows": len(self._slot),
                "capacity_rows": self._cap,
                "resident_bytes": self.resident_bytes(),
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}

    def close(self) -> None:
        """Free the pool and reconcile the ledger line to ZERO — same
        close contract as a registry eviction, at row granularity."""
        if self._closed:
            return
        self._closed = True
        self._slot.clear()
        self._freq.clear()
        self._touch.clear()
        self._free = list(range(self._cap - 1, -1, -1))
        self._charge()
