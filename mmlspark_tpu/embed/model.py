"""DLRM-lite recommender: sparse embedding bags + dense towers + dot
interaction, as one zoo architecture (``recommender_dlrm``).

The wire format is ONE packed float32 row per example —
``[dense features | slots ids per sparse feature ...]`` — chosen so the
recommender rides the ENTIRE existing serving stack unchanged: the
micro-batcher coalesces packed rows like any tabular input, the
registry AOT-compiles one program per batch bucket, and the router
fails over without knowing tables exist. Ids travel as float32 (exact
up to 2^24 — far beyond any table this repo can hold) and are cast
back to int32 on device; slot id 0 is the pad, its weight is 0.

The embedding params are named ``<feature>_embedding``, which lands
them on ``parallel/sharding.py``'s ``.*embedding$`` rule: under any
tensor-axis mesh — ``DistributedTrainer``'s or a serving
``meshSpec`` — the tables are row-sharded with NO recommender-specific
plumbing anywhere in trainer, checkpointer, or registry. Training can
inject the fused all-to-all lookup (``lookup_fn=make_bag_lookup(mesh)``)
for the explicit bucketized path + scatter-add sparse gradient;
serving keeps the default gather (GSPMD partitions it against the
sharded table) so the architecture stays serializable by name.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.embed.tables import PAD_ID, bag_lookup_reference
from mmlspark_tpu.models.zoo import register_model
from mmlspark_tpu.utils import config as mmlconfig


class DLRM(nn.Module):
    """Two-tower DLRM-lite over packed rows (see module docstring)."""
    dense_dim: int
    tables: Tuple[Tuple[str, int], ...]    # ((name, rows), ...) in slot order
    embed_dim: int = 16
    slots: int = 4
    bottom: Tuple[int, ...] = (32,)
    top: Tuple[int, ...] = (32,)
    num_classes: int = 1
    lookup_fn: Optional[Callable] = None   # None = reference gather (GSPMD)

    @nn.compact
    def __call__(self, x):
        dense = x[:, :self.dense_dim]
        h = dense
        for i, width in enumerate(self.bottom):
            h = nn.relu(nn.Dense(width, name=f"bottom_fc{i}")(h))
        feats = [nn.Dense(self.embed_dim, name="bottom_out")(h)]
        lookup = self.lookup_fn or bag_lookup_reference
        off = self.dense_dim
        for name, rows in self.tables:
            ids = x[:, off:off + self.slots].astype(jnp.int32)
            off += self.slots
            weights = (ids != PAD_ID).astype(jnp.float32)
            table = self.param(
                f"{name}_embedding",
                nn.initializers.normal(stddev=self.embed_dim ** -0.5),
                (rows, self.embed_dim), jnp.float32)
            feats.append(lookup(table, ids, weights))
        stack = jnp.stack(feats, axis=1)            # (B, F, D)
        # dot interaction: pairwise feature affinities, upper triangle
        dots = jnp.einsum("bfd,bgd->bfg", stack, stack)
        f = stack.shape[1]
        iu, ju = np.triu_indices(f, k=1)
        z = jnp.concatenate([feats[0], dots[:, iu, ju]], axis=1)
        self.sow("intermediates", "interaction", z)
        for i, width in enumerate(self.top):
            z = nn.relu(nn.Dense(width, name=f"top_fc{i}")(z))
        return nn.Dense(self.num_classes, name="head")(z)


def padded_rows(rows: int) -> int:
    # embed.row_multiple (default 8): the shard granule — any tensor
    # axis up to it divides every padded table evenly
    m = int(mmlconfig.get("embed.row_multiple"))
    return -(-int(rows) // m) * m


def pack_rows(dense: np.ndarray, sparse: Sequence[np.ndarray]) -> np.ndarray:
    """Host-side wire packing: float32 ``[dense | ids...]`` rows. Each
    sparse block is (B, slots) int ids (0 = pad)."""
    parts = [np.asarray(dense, np.float32)]
    parts += [np.asarray(ids, np.float32) for ids in sparse]
    return np.concatenate(parts, axis=1)


@register_model("recommender_dlrm")
def recommender_dlrm(dense_dim: int = 8,
                     tables: Any = (("user", 1024), ("item", 2048)),
                     embed_dim: int = 16, slots: int = 4,
                     bottom=(32,), top=(32,), num_classes: int = 1,
                     lookup_fn: Optional[Callable] = None):
    """Zoo builder. ``tables`` is ``((name, rows), ...)``; rows round up
    to the shard multiple so any tensor axis divides them. JSON-decoded
    specs arrive as lists — normalized here so serialized stages
    rebuild the same module."""
    tabs = tuple((str(n), padded_rows(r)) for n, r in tables)
    width = dense_dim + len(tabs) * slots
    return dict(
        module=DLRM(dense_dim=dense_dim, tables=tabs, embed_dim=embed_dim,
                    slots=slots, bottom=tuple(bottom), top=tuple(top),
                    num_classes=num_classes, lookup_fn=lookup_fn),
        input_shape=(width,),
        feature_layer="interaction",
        feature_dim=None,
        layer_names=["interaction", "head"],
    )
