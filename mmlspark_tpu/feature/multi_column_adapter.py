"""MultiColumnAdapter: apply a unary stage over many column pairs.

Re-expression of ``multi-column-adapter/src/main/scala/MultiColumnAdapter.scala``:
takes a base stage with inputCol/outputCol params plus parallel lists of
input and output column names, and applies a per-pair copy of the stage in
sequence (``transform`` at ``MultiColumnAdapter.scala:91-99``).

Beyond the reference (which only accepts Transformers), an Estimator base is
supported via :meth:`MultiColumnAdapter.fit`, returning a PipelineModel of
the per-column fitted models — this is what lets Featurize one-hot many
categorical columns with a single ValueIndexer config.
"""
from __future__ import annotations

from typing import List, Tuple

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import AnyParam, ListParam, ParamException
from mmlspark_tpu.core.pipeline import Estimator, PipelineModel, Transformer
from mmlspark_tpu.core.schema import Schema, SchemaError
from mmlspark_tpu.core.serialization import register_stage


def _check_unary(stage) -> None:
    names = {p.name for p in stage.params()}
    if "inputCol" not in names or "outputCol" not in names:
        raise ParamException(
            "baseStage must be a unary stage with inputCol and outputCol "
            f"params; {type(stage).__name__} has {sorted(names)}")


@register_stage
class MultiColumnAdapter(Estimator):
    """Applies ``baseStage`` to every (inputCols[i] -> outputCols[i]) pair.

    ``transform`` works directly when the base is a Transformer (reference
    behavior); ``fit`` additionally supports Estimator bases.
    """

    baseStage = AnyParam("baseStage", "unary stage applied to every column pair")
    inputCols = ListParam("inputCols", "input column names", [])
    outputCols = ListParam("outputCols", "output column names", [])

    def _pairs(self) -> List[Tuple[str, str]]:
        ins, outs = self.get("inputCols"), self.get("outputCols")
        if len(ins) != len(outs):
            raise ParamException(
                f"inputCols ({len(ins)}) and outputCols ({len(outs)}) must "
                "have the same length")
        if not ins:
            raise ParamException("inputCols is empty")
        return list(zip(ins, outs))

    def _per_pair(self, in_col: str, out_col: str):
        stage = self.get("baseStage").copy()
        return stage.set_params(inputCol=in_col, outputCol=out_col)

    def _verify(self, frame: Frame) -> None:
        outs = [o for _, o in self._pairs()]
        if len(set(outs)) != len(outs):
            raise ParamException(f"duplicate output column names: {outs}")
        for in_col, out_col in self._pairs():
            if in_col not in frame.schema:
                raise SchemaError(f"frame does not contain input column {in_col!r}")
            if out_col in frame.schema:
                raise SchemaError(f"frame already contains output column {out_col!r}")

    def fit(self, frame: Frame) -> PipelineModel:
        base = self.get("baseStage")
        _check_unary(base)
        self._verify(frame)
        # Each pair reads only original columns (outputs are verified absent),
        # so every stage fits directly on the input frame — no intermediate
        # transforms materialized.
        fitted: List[Transformer] = []
        for in_col, out_col in self._pairs():
            stage = self._per_pair(in_col, out_col)
            fitted.append(stage.fit(frame) if isinstance(stage, Estimator)
                          else stage)
        return PipelineModel(stages=fitted)

    def transform(self, frame: Frame) -> Frame:
        """Direct transform path for Transformer bases (reference semantics)."""
        base = self.get("baseStage")
        _check_unary(base)
        if isinstance(base, Estimator):
            raise ParamException(
                "baseStage is an Estimator; use fit() instead of transform()")
        self._verify(frame)
        for in_col, out_col in self._pairs():
            frame = self._per_pair(in_col, out_col).transform(frame)
        return frame

    def transform_schema(self, schema: Schema) -> Schema:
        base = self.get("baseStage")
        if isinstance(base, Estimator):
            raise ParamException(
                "baseStage is an Estimator; output schema is only known "
                "after fit()")
        for in_col, out_col in self._pairs():
            schema = self._per_pair(in_col, out_col).transform_schema(schema)
        return schema
