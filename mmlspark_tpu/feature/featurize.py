"""Featurize / AssembleFeatures: automatic featurization of arbitrary frames.

Re-expression of the reference's auto-featurizer
(``featurize/src/main/scala/{Featurize,AssembleFeatures}.scala``):

- Per-column classification (``AssembleFeatures.scala:146-193``):
  numeric -> cast to float + NaN-row cleaning; string -> tokenize + murmur3
  HashingTF + count-based slot selection (the BitSet-OR reduce at ``:198-224``
  becomes a set-union scan); categorical (metadata) -> one-hot (optional);
  vector -> passthrough with NaN cleaning.
- Assembly preserves the reference's FastVectorAssembler ordering contract:
  categorical parts FIRST (``core/spark/src/main/scala/FastVectorAssembler.scala:35-100``),
  then numeric, then vectors, then hashed-text slots.
- Output metadata records per-source slot ranges so downstream stages (and
  the judge) can audit the feature layout.

TPU-first notes: the assembled features column is a dense 2-D float32 array
per partition — the layout that streams straight into a sharded ``jax.Array``
batch; slot selection keeps hashed-text width = |active slots|, not 2^18.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    BooleanParam, DictParam, HasFeaturesCol, IntParam, ListParam,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, PipelineModel
from mmlspark_tpu.core.schema import ColumnSchema, DType, Schema, SchemaError
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.ops.hashing import hash_token_rows, project_slots

# Reference defaults (Featurize.scala:14-19)
NUM_FEATURES_DEFAULT = 1 << 18
NUM_FEATURES_TREE_OR_NN = 1 << 12


def tokenize(text: Optional[str]) -> List[str]:
    """Spark Tokenizer semantics: lowercase, split on whitespace."""
    if text is None:
        return []
    return [t for t in text.lower().split() if t]


@register_stage
class Featurize(Estimator):
    """Map of outputCol -> inputCols; one AssembleFeatures per output vector.

    Reference: ``Featurize.scala:26-92``.
    """

    featureColumns = DictParam(
        "featureColumns", "map of output feature column to input columns")
    numberOfFeatures = IntParam(
        "numberOfFeatures", "hash space size for string columns",
        NUM_FEATURES_DEFAULT, validator=lambda v: v > 0)
    oneHotEncodeCategoricals = BooleanParam(
        "oneHotEncodeCategoricals", "one hot encode categoricals", True)

    def fit(self, frame: Frame) -> PipelineModel:
        stages = []
        for out_col, in_cols in self.get("featureColumns").items():
            stage = AssembleFeatures(
                featuresCol=out_col,
                columnsToFeaturize=list(in_cols),
                numberOfFeatures=self.numberOfFeatures,
                oneHotEncodeCategoricals=self.oneHotEncodeCategoricals,
            )
            stages.append(stage.fit(frame))
        return PipelineModel(stages=stages)


@register_stage
class AssembleFeatures(HasFeaturesCol, Estimator):
    columnsToFeaturize = ListParam("columnsToFeaturize", "input columns")
    numberOfFeatures = IntParam(
        "numberOfFeatures", "hash space size for string columns",
        NUM_FEATURES_DEFAULT, validator=lambda v: v > 0)
    oneHotEncodeCategoricals = BooleanParam(
        "oneHotEncodeCategoricals", "one hot encode categoricals", True)

    def fit(self, frame: Frame) -> "AssembleFeaturesModel":
        schema = frame.schema
        cat_cols: List[Tuple[str, int]] = []     # (name, one-hot width)
        numeric_cols: List[str] = []
        clean_cols: List[str] = []               # NaN-row cleaning
        vector_cols: List[Tuple[str, int]] = []  # (name, dim)
        hash_cols: List[str] = []

        for name in self.get("columnsToFeaturize"):
            col = schema[name]
            if col.is_categorical:
                cmap = col.categorical
                if self.oneHotEncodeCategoricals:
                    cat_cols.append((name, cmap.num_levels))
                else:
                    numeric_cols.append(name)
            elif col.dtype in (DType.FLOAT32, DType.FLOAT64):
                numeric_cols.append(name)
                clean_cols.append(name)
            elif col.dtype.is_numeric:
                numeric_cols.append(name)
            elif col.dtype == DType.STRING:
                hash_cols.append(name)
            elif col.dtype == DType.TOKENS:
                hash_cols.append(name)
            elif col.dtype == DType.VECTOR:
                if col.dim is None:
                    raise SchemaError(f"vector column {name!r} has unknown dim")
                vector_cols.append((name, col.dim))
                clean_cols.append(name)
            else:
                raise SchemaError(
                    f"cannot featurize column {name!r} of type {col.dtype.value}")

        # Slot selection for hashed text: union of active slots over the data
        # (the BitSet-OR reduce, AssembleFeatures.scala:198-224). Scan only the
        # rows that survive the same NaN cleaning transform will apply,
        # otherwise dropped rows leave permanently-zero slots.
        active_slots = np.zeros(0, np.int64)
        if hash_cols:
            if clean_cols:
                frame = frame.na_drop([c for c in clean_cols if c in schema])
            nf = self.numberOfFeatures
            parts_slots = []
            for p in frame.partitions:
                for name in hash_cols:
                    is_tokens = schema[name].dtype == DType.TOKENS
                    rows = (p[name] if is_tokens
                            else [tokenize(v) for v in p[name]])
                    slots, _ = hash_token_rows(rows, nf)
                    parts_slots.append(slots)
            active_slots = np.unique(np.concatenate(parts_slots))

        model = AssembleFeaturesModel(featuresCol=self.featuresCol)
        model._state = {
            "cat_cols": [[n, w] for n, w in cat_cols],
            "numeric_cols": numeric_cols,
            "clean_cols": clean_cols,
            "vector_cols": [[n, d] for n, d in vector_cols],
            "hash_cols": hash_cols,
            "hash_col_is_tokens": [
                schema[n].dtype == DType.TOKENS for n in hash_cols],
            "active_slots": np.asarray(active_slots, dtype=np.int64),
            "num_features": self.numberOfFeatures,
        }
        return model


@register_stage
class AssembleFeaturesModel(HasFeaturesCol, Model):
    """Fitted featurizer: emits one dense float32 features column.

    Layout (reference FastVectorAssembler contract — categoricals first):
        [one-hot(cat_1) .. one-hot(cat_k) | numerics | vectors | hashed slots]
    """

    def _layout(self) -> Tuple[List[Tuple[str, int, int, str]], int]:
        """[(source, start, stop, kind)], total_dim."""
        s = self._state
        layout, off = [], 0
        for name, width in s["cat_cols"]:
            layout.append((name, off, off + width, "onehot"))
            off += width
        for name in s["numeric_cols"]:
            layout.append((name, off, off + 1, "numeric"))
            off += 1
        for name, dim in s["vector_cols"]:
            layout.append((name, off, off + dim, "vector"))
            off += dim
        n_slots = len(s["active_slots"])
        if s["hash_cols"]:
            layout.append(("+".join(s["hash_cols"]), off, off + n_slots, "hashed"))
            off += n_slots
        return layout, off

    def transform(self, frame: Frame) -> Frame:
        s = self._state
        clean = [c for c in s["clean_cols"] if c in frame.schema]
        if clean:
            frame = frame.na_drop(clean)
        layout, total = self._layout()
        active_slots = np.asarray(s["active_slots"], dtype=np.int64)
        nf = int(s["num_features"])

        def assemble(p) -> np.ndarray:
            n = len(p[next(iter(frame.schema.names))]) if frame.schema.names else 0
            out = np.zeros((n, total), dtype=np.float32)
            for name, width in s["cat_cols"]:
                start = next(l[1] for l in layout if l[0] == name and l[3] == "onehot")
                idx = np.asarray(p[name], dtype=np.int64)
                valid = (idx >= 0) & (idx < width)
                rows = np.nonzero(valid)[0]
                out[rows, start + idx[valid]] = 1.0
            for name in s["numeric_cols"]:
                start = next(l[1] for l in layout if l[0] == name and l[3] == "numeric")
                out[:, start] = np.asarray(p[name], dtype=np.float32)
            for name, dim in s["vector_cols"]:
                start = next(l[1] for l in layout if l[0] == name and l[3] == "vector")
                out[:, start:start + dim] = np.asarray(p[name], dtype=np.float32)
            if s["hash_cols"]:
                start = next(l[1] for l in layout if l[3] == "hashed")
                for name, is_tok in zip(s["hash_cols"],
                                        s["hash_col_is_tokens"]):
                    rows = (p[name] if is_tok
                            else [tokenize(v) for v in p[name]])
                    slots, row_ptr = hash_token_rows(rows, nf)
                    rids = np.repeat(np.arange(n, dtype=np.int64),
                                     np.diff(row_ptr))
                    pos, ok = project_slots(active_slots, slots)
                    # accumulate counts (a slot can repeat within a row)
                    np.add.at(out, (rids[ok], start + pos[ok]), 1.0)
            return out

        col = ColumnSchema(
            self.featuresCol, DType.VECTOR, total,
            metadata={"feature_layout": [[n, a, b, k] for n, a, b, k in layout],
                      "assembled": True})
        return frame.with_column(col, assemble)

    def transform_schema(self, schema: Schema) -> Schema:
        layout, total = self._layout()
        return schema.add(ColumnSchema(
            self.featuresCol, DType.VECTOR, total,
            metadata={"feature_layout": [[n, a, b, k] for n, a, b, k in layout],
                      "assembled": True}))
