"""Word2Vec: skip-gram word embeddings trained with a jitted JAX step.

Capability parity with the reference's use of Spark ML Word2Vec (notebook
``notebooks/samples/202 - Amazon Book Reviews - Word2Vec.ipynb``): fit a
tokens column -> per-word vectors; transform averages word vectors per row;
``find_synonyms`` does cosine top-k.

TPU-first notes: Spark's implementation is hierarchical-softmax over a
per-partition Scala loop. Here training is skip-gram with NEGATIVE SAMPLING
— two embedding matrices updated by a single jitted step whose inner loop is
a ``lax.scan`` over minibatches, so the whole epoch is one XLA program of
gather + (B,D)x(D,K) matmuls that tile onto the MXU. Negatives draw from the
classic unigram^0.75 table precomputed on host.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    FloatParam, HasInputCol, HasOutputCol, IntParam,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.schema import ColumnSchema, DType, SchemaError
from mmlspark_tpu.core.serialization import register_stage

_TABLE_SIZE = 1 << 16


def _build_vocab(rows, min_count: int) -> Tuple[List[str], np.ndarray]:
    from collections import Counter
    counts: Counter = Counter()
    for row in rows:
        counts.update(row)
    vocab = sorted([w for w, c in counts.items() if c >= min_count],
                   key=lambda w: (-counts[w], w))
    freqs = np.asarray([counts[w] for w in vocab], dtype=np.float64)
    return vocab, freqs


def _flat_ids(rows, index: Dict[str, int]) -> Tuple[np.ndarray, np.ndarray]:
    """(ids, row_ids) over all in-vocab tokens, corpus-flattened."""
    ids: List[int] = []
    row_ids: List[int] = []
    for r, row in enumerate(rows):
        for t in row:
            i = index.get(t)
            if i is not None:
                ids.append(i)
                row_ids.append(r)
    return np.asarray(ids, np.int32), np.asarray(row_ids, np.int64)


def _skipgram_pairs(rows, index: Dict[str, int], window: int,
                    rng: np.random.Generator) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized skip-gram pair generation with word2vec's dynamic window.

    Each center draws an effective window b in [1, window]; context j pairs
    with center i iff |i-j| <= b_i within the same row. One masked shift of
    the corpus-flat id array per offset replaces the reference-era per-row
    nested Python loop — O(window) numpy passes over the corpus.
    """
    ids, row_ids = _flat_ids(rows, index)
    if ids.size < 2:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    spans = rng.integers(1, window + 1, size=ids.size)
    centers, contexts = [], []
    for d in range(1, window + 1):
        if d >= ids.size:
            break
        same_row = row_ids[:-d] == row_ids[d:]
        # center on the left of the pair: include iff its span reaches d
        m = same_row & (spans[:-d] >= d)
        centers.append(ids[:-d][m])
        contexts.append(ids[d:][m])
        # center on the right of the pair
        m = same_row & (spans[d:] >= d)
        centers.append(ids[d:][m])
        contexts.append(ids[:-d][m])
    c = np.concatenate(centers) if centers else np.zeros(0, np.int32)
    x = np.concatenate(contexts) if contexts else np.zeros(0, np.int32)
    return c.astype(np.int32), x.astype(np.int32)


@register_stage
class Word2Vec(HasInputCol, HasOutputCol, Estimator):
    vectorSize = IntParam("vectorSize", "embedding dimension", 100,
                          validator=lambda v: v > 0)
    windowSize = IntParam("windowSize", "max skip-gram window", 5,
                          validator=lambda v: v >= 1)
    minCount = IntParam("minCount", "minimum token frequency", 5,
                        validator=lambda v: v >= 1)
    maxIter = IntParam("maxIter", "training epochs", 1,
                       validator=lambda v: v >= 1)
    stepSize = FloatParam("stepSize", "SGD learning rate", 0.025)
    numNegatives = IntParam("numNegatives", "negative samples per pair", 5,
                            validator=lambda v: v >= 1)
    batchSize = IntParam("batchSize", "pairs per step", 1024,
                         validator=lambda v: v > 0)
    seed = IntParam("seed", "random seed", 0)

    def fit(self, frame: Frame) -> "Word2VecModel":
        import jax
        import jax.numpy as jnp

        if frame.schema[self.inputCol].dtype != DType.TOKENS:
            raise SchemaError(
                f"Word2Vec: input column {self.inputCol!r} must be tokens")
        rows = frame.column(self.inputCol)
        vocab, freqs = _build_vocab(rows, self.minCount)
        if not vocab:
            raise SchemaError(
                f"Word2Vec: no token appears >= minCount={self.minCount} times")
        index = {w: i for i, w in enumerate(vocab)}
        host_rng = np.random.default_rng(self.seed)
        centers, contexts = _skipgram_pairs(rows, index, self.windowSize, host_rng)

        dim, v = self.vectorSize, len(vocab)
        if centers.size == 0:  # degenerate corpus: random init, no training
            w_in = host_rng.normal(0, 1.0 / dim, (v, dim)).astype(np.float32)
            return self._make_model(vocab, w_in)

        # unigram^0.75 negative-sampling table
        p = freqs ** 0.75
        p /= p.sum()
        table = host_rng.choice(v, size=_TABLE_SIZE, p=p).astype(np.int32)

        batch = min(self.batchSize, centers.size)
        # ceil so the remainder trains too (wrap-padded; duplicates are
        # harmless for SGD and the shuffle differs per epoch)
        n_batches = -(-centers.size // batch)
        neg = self.numNegatives
        lr = self.stepSize

        n_pairs = int(centers.size)

        def epoch(params, c_all, x_all, table_d, key):
            w_in, w_out = params
            # Device-side per-epoch shuffle + wrap-pad: the pair arrays
            # transfer host->HBM ONCE before the first epoch, and every
            # later epoch is pure on-device gather — the same residency
            # contract as DeviceEpochCache (a host permutation here would
            # re-ship the whole epoch every iteration).
            key, kp = jax.random.split(key)
            perm = jax.random.permutation(kp, n_pairs)
            idx = jnp.take(perm, jnp.arange(padded) % n_pairs)
            c_all = jnp.take(c_all, idx)
            x_all = jnp.take(x_all, idx)

            def step(carry, cb_xb):
                w_in, w_out, key = carry
                cb, xb = cb_xb
                key, k1 = jax.random.split(key)
                neg_idx = jnp.take(
                    table_d,
                    jax.random.randint(k1, (batch, neg), 0, _TABLE_SIZE), axis=0)

                def loss_fn(w_in, w_out):
                    vc = w_in[cb]                       # (B, D)
                    uo = w_out[xb]                      # (B, D)
                    un = w_out[neg_idx]                 # (B, K, D)
                    pos = jnp.sum(vc * uo, axis=-1)     # (B,)
                    negs = jnp.einsum("bd,bkd->bk", vc, un)
                    # SUM over the batch = classic per-pair SGD accumulated
                    # into one update (mean would shrink steps by 1/B)
                    return -(jax.nn.log_sigmoid(pos).sum()
                             + jax.nn.log_sigmoid(-negs).sum())

                loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                    w_in, w_out)
                return (w_in - lr * grads[0], w_out - lr * grads[1], key), loss

            cb = c_all.reshape(n_batches, batch)
            xb = x_all.reshape(n_batches, batch)
            (w_in, w_out, _), losses = jax.lax.scan(
                step, (w_in, w_out, key), (cb, xb))
            return (w_in, w_out), losses.mean()

        epoch_jit = jax.jit(epoch)
        key = jax.random.PRNGKey(self.seed)
        w_in = jnp.asarray(
            host_rng.uniform(-0.5 / dim, 0.5 / dim, (v, dim)).astype(np.float32))
        w_out = jnp.zeros((v, dim), jnp.float32)
        params = (w_in, w_out)
        padded = n_batches * batch
        # ONE transfer each for the pair stream and the negative table;
        # epochs re-permute on device (see epoch() above)
        c_dev, x_dev = jnp.asarray(centers), jnp.asarray(contexts)
        table_dev = jnp.asarray(table)
        for it in range(self.maxIter):
            key, sub = jax.random.split(key)
            params, _ = epoch_jit(params, c_dev, x_dev, table_dev, sub)
        return self._make_model(vocab, np.asarray(params[0]))

    def _make_model(self, vocab: List[str], vectors: np.ndarray) -> "Word2VecModel":
        model = Word2VecModel(inputCol=self.inputCol, outputCol=self.outputCol,
                              vectorSize=self.vectorSize)
        model.set_params(vocabulary=list(vocab))
        model._set_state({"vectors": vectors.astype(np.float32)})
        return model


@register_stage
class Word2VecModel(HasInputCol, HasOutputCol, Model):
    from mmlspark_tpu.core.params import ListParam as _ListParam
    vectorSize = IntParam("vectorSize", "embedding dimension", 100)
    vocabulary = _ListParam("vocabulary", "ordered vocabulary", [])

    @property
    def vectors(self) -> np.ndarray:
        return self._get_state()["vectors"]

    def get_vectors(self) -> Dict[str, np.ndarray]:
        return {w: self.vectors[i] for i, w in enumerate(self.get("vocabulary"))}

    def transform(self, frame: Frame) -> Frame:
        """Average the vectors of in-vocab tokens per row (Spark semantics);
        rows with no known token map to the zero vector."""
        if frame.schema[self.inputCol].dtype != DType.TOKENS:
            raise SchemaError(
                f"Word2VecModel: input column {self.inputCol!r} must be tokens")
        index = {w: i for i, w in enumerate(self.get("vocabulary"))}
        vecs = self.vectors
        dim = vecs.shape[1]
        rows = frame.column(self.inputCol)
        out = np.zeros((len(rows), dim), dtype=np.float32)
        ids, row_ids = _flat_ids(rows, index)
        if ids.size:
            np.add.at(out, row_ids, vecs[ids])
            counts = np.bincount(row_ids, minlength=len(rows)).astype(np.float32)
            out /= np.maximum(counts, 1.0)[:, None]
        return frame.with_column_values(
            ColumnSchema(self.outputCol, DType.VECTOR, dim=dim), out)

    def transform_schema(self, schema):
        return schema.add(ColumnSchema(self.outputCol, DType.VECTOR,
                                       dim=self.vectorSize))

    def find_synonyms(self, word: str, num: int) -> List[Tuple[str, float]]:
        vocab = self.get("vocabulary")
        index = {w: i for i, w in enumerate(vocab)}
        if word not in index:
            raise KeyError(f"{word!r} not in vocabulary")
        vecs = self.vectors
        q = vecs[index[word]]
        norms = np.linalg.norm(vecs, axis=1) * (np.linalg.norm(q) + 1e-12) + 1e-12
        sims = vecs @ q / norms
        order = np.argsort(-sims)
        out = []
        for i in order:
            if vocab[i] != word:
                out.append((vocab[i], float(sims[i])))
            if len(out) >= num:
                break
        return out
