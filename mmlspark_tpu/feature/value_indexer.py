"""ValueIndexer / IndexToValue: categorical level indexing with null handling.

Re-expression of the reference's StringIndexer generalization
(``value-indexer/src/main/scala/ValueIndexer.scala:67-169``,
``IndexToValue.scala:27-70``):

- ``fit`` collects distinct values of Int/Long/Double/String/Bool columns,
  sorts them (nulls last), and produces a model mapping level -> index.
- null/NaN map to ``num_levels``; unseen values map to ``num_levels`` when no
  null level exists, else ``num_levels + 1`` (exact reference semantics,
  ``ValueIndexer.scala:145-169``).
- The output column carries the CategoricalMap in its metadata, which is what
  ``IndexToValue`` and the evaluators read back.

:class:`HashIndexer` is the VOCABULARY-FREE sibling for embedding-table
ids (the recommender path): no fit pass, no level list to ship — any
categorical value hashes to a stable bucket in ``[1, numBuckets)`` via
the same Spark-parity murmur3 the text featurizers use, and null/NaN
map to 0, ``embed.tables.PAD_ID`` — the reserved all-zero pad row whose
lookup weight is 0. Where ``ValueIndexer`` must see the whole column to
sort levels (and breaks on unseen values), ``HashIndexer`` indexes
streams it has never seen, which is what an online scoring path needs.
"""
from __future__ import annotations

import math
from typing import Any, List

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (HasInputCol, HasOutputCol, IntParam,
                                      ListParam)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import CategoricalMap, ColumnSchema, DType, SchemaError
from mmlspark_tpu.core.serialization import register_stage


def _is_nanlike(v: Any) -> bool:
    return v is None or (isinstance(v, (float, np.floating)) and math.isnan(v))


@register_stage
class ValueIndexer(HasInputCol, HasOutputCol, Estimator):
    """Collect distinct values of a column and index them as a categorical."""

    def fit(self, frame: Frame) -> "ValueIndexerModel":
        dtype = frame.schema[self.inputCol].dtype
        if dtype in (DType.VECTOR, DType.IMAGE, DType.BINARY, DType.TOKENS):
            raise SchemaError(f"unsupported categorical type {dtype.value}")
        distinct = frame.distinct_values(self.inputCol)
        has_null = any(_is_nanlike(v) for v in distinct)
        levels = sorted(
            (v.item() if isinstance(v, np.generic) else v
             for v in distinct if not _is_nanlike(v)))
        model = ValueIndexerModel(
            inputCol=self.inputCol, outputCol=self.outputCol)
        model._state = {"levels": levels, "has_null_level": has_null,
                        "input_dtype": dtype.value}
        return model


@register_stage
class ValueIndexerModel(HasInputCol, HasOutputCol, Model):
    @property
    def categorical_map(self) -> CategoricalMap:
        return CategoricalMap(self._state["levels"],
                              bool(self._state["has_null_level"]))

    def transform(self, frame: Frame) -> Frame:
        cmap = self.categorical_map
        num = cmap.num_levels
        unknown = num if not cmap.has_null_level else num + 1

        def index_part(p):
            arr = p[self.inputCol]
            out = np.empty(len(arr), dtype=np.int32)
            for i, v in enumerate(arr):
                if _is_nanlike(v):
                    out[i] = num
                else:
                    key = v.item() if isinstance(v, np.generic) else v
                    out[i] = cmap.get_index(key, default=unknown)
            return out

        col = ColumnSchema(self.outputCol, DType.INT32,
                           metadata={"categorical": cmap.to_metadata(),
                                     "original_dtype": self._state["input_dtype"]})
        return frame.with_column(col, index_part)

    def transform_schema(self, schema):
        cmap = self.categorical_map
        return schema.add(ColumnSchema(
            self.outputCol, DType.INT32,
            metadata={"categorical": cmap.to_metadata(),
                      "original_dtype": self._state["input_dtype"]}))


@register_stage
class HashIndexer(HasInputCol, HasOutputCol, Transformer):
    """Stateless categorical-to-id hashing for embedding tables.

    ``numBuckets`` is the table's row count INCLUDING the reserved pad
    row: real values land in ``[1, numBuckets)`` (murmur3 of the value's
    canonical string, Spark seed — stable across processes and restarts,
    unlike Python's salted ``hash``), null/NaN land on 0 (the pad row,
    masked to zero weight by the bag lookup). Collisions are the
    accepted trade for never shipping a vocabulary; size ``numBuckets``
    to the table, not the cardinality.
    """

    numBuckets = IntParam(
        "numBuckets", "embedding-table rows incl. the pad row 0; real "
        "ids land in [1, numBuckets)", 1 << 16,
        validator=lambda v: v >= 2)

    def transform(self, frame: Frame) -> Frame:
        dtype = frame.schema[self.inputCol].dtype
        if dtype in (DType.VECTOR, DType.IMAGE, DType.BINARY, DType.TOKENS):
            raise SchemaError(f"unsupported categorical type {dtype.value}")
        from mmlspark_tpu.ops.hashing import murmur3_batch
        buckets = int(self.numBuckets)

        def index_part(p):
            arr = p[self.inputCol]
            keys, real_pos = [], []
            out = np.zeros(len(arr), dtype=np.int32)   # nulls stay on pad
            for i, v in enumerate(arr):
                if _is_nanlike(v):
                    continue
                key = v.item() if isinstance(v, np.generic) else v
                keys.append(_canonical_str(key))
                real_pos.append(i)
            if keys:
                h = murmur3_batch(keys).astype(np.int64)
                out[real_pos] = 1 + (h % np.int64(buckets - 1))
            return out

        return frame.with_column(
            ColumnSchema(self.outputCol, DType.INT32,
                         metadata={"hash_buckets": buckets, "pad_id": 0}),
            index_part)

    def transform_schema(self, schema):
        return schema.add(ColumnSchema(
            self.outputCol, DType.INT32,
            metadata={"hash_buckets": int(self.numBuckets), "pad_id": 0}))


def _canonical_str(v: Any) -> str:
    """One spelling per value across dtypes: ints never pick up a float
    suffix (``3`` and ``3.0`` hash identically — a column that arrives
    int64 in training and float64 in serving must agree), bools hash as
    their ints."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


@register_stage
class IndexToValue(HasInputCol, HasOutputCol, Transformer):
    """Inverse of ValueIndexerModel via the CategoricalMap in column metadata.

    Reference: ``value-indexer/src/main/scala/IndexToValue.scala:27-70``.
    """

    def transform(self, frame: Frame) -> Frame:
        in_schema = frame.schema[self.inputCol]
        cmap = in_schema.categorical
        if cmap is None:
            raise SchemaError(
                f"column {self.inputCol!r} has no categorical metadata")
        orig = DType(in_schema.metadata.get("original_dtype", DType.STRING.value))

        def invert(p):
            arr = p[self.inputCol]
            out: List[Any] = []
            for idx in arr:
                i = int(idx)
                out.append(cmap.get_level(i) if 0 <= i < cmap.num_levels else None)
            return out

        return frame.with_column(ColumnSchema(self.outputCol, orig), invert)
