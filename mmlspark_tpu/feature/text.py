"""Text featurization: tokenizer, stop words, n-grams, HashingTF, IDF,
and the TextFeaturizer convenience estimator chaining them.

Re-expression of the reference's text pipeline
(``text-featurizer/src/main/scala/TextFeaturizer.scala``): each stage is
optional and auto-chained input->output exactly like the reference's
``fit`` (``TextFeaturizer.scala:230-290``); intermediate columns are dropped
from the output frame (``TextFeaturizerModel.transform``). Hashing is the
Spark-parity murmur3 of :mod:`mmlspark_tpu.ops.hashing`.

TPU-first notes: HashingTF's 2^18 hash space is never materialized densely.
The fitted model records the ACTIVE slot set seen at fit time (the same
count-based compaction AssembleFeatures uses, mirroring the reference's
BitSet-OR + VectorSlicer at ``AssembleFeatures.scala:198-224``) and emits a
dense float32 matrix of width |active slots| — the layout that streams
straight into a sharded ``jax.Array``. IDF weighting is a vectorized numpy
pass over that compact matrix.
"""
from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    BooleanParam, HasInputCol, HasOutputCol, IntParam, ListParam, Param,
    StringParam,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import ColumnSchema, DType, SchemaError
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.ops.hashing import hash_token_rows, project_slots, tf_csr

# A standard English stop-word list (the classic Glasgow IR list that Spark's
# StopWordsRemover also ships). Public-domain word list.
ENGLISH_STOP_WORDS = (
    "a about above after again against all am an and any are aren't as at be "
    "because been before being below between both but by can't cannot could "
    "couldn't did didn't do does doesn't doing don't down during each few for "
    "from further had hadn't has hasn't have haven't having he he'd he'll "
    "he's her here here's hers herself him himself his how how's i i'd i'll "
    "i'm i've if in into is isn't it it's its itself let's me more most "
    "mustn't my myself no nor not of off on once only or other ought our ours "
    "ourselves out over own same shan't she she'd she'll she's should "
    "shouldn't so some such than that that's the their theirs them themselves "
    "then there there's these they they'd they'll they're they've this those "
    "through to too under until up very was wasn't we we'd we'll we're we've "
    "were weren't what what's when when's where where's which while who who's "
    "whom why why's with won't would wouldn't you you'd you'll you're you've "
    "your yours yourself yourselves"
).split()

STOP_WORD_LANGUAGES = {"english": ENGLISH_STOP_WORDS}


def _require_dtype(frame: Frame, col: str, expected: DType, stage: str) -> None:
    actual = frame.schema[col].dtype
    if actual != expected:
        raise SchemaError(
            f"{stage}: input column {col!r} must be {expected.value}, "
            f"got {actual.value}")


def _token_rows(frame: Frame, col: str) -> List[List[str]]:
    """Token column values with null rows normalized to [] (a TOKENS column
    may store None per the Frame storage rules)."""
    return [row if row is not None else [] for row in frame.column(col)]


@register_stage
class RegexTokenizer(HasInputCol, HasOutputCol, Transformer):
    """String -> tokens via regex gaps/matches.

    Parity with Spark's RegexTokenizer as configured by the reference
    (``TextFeaturizer.scala:240-245``): ``gaps`` decides whether ``pattern``
    matches delimiters (split) or tokens (findall); ``minTokenLength``
    filters; ``toLowercase`` applies before tokenizing.
    """

    gaps = BooleanParam("gaps", "pattern matches gaps (split) vs tokens", True)
    pattern = StringParam("pattern", "regex for delimiters or tokens", r"\s+")
    minTokenLength = IntParam("minTokenLength", "minimum token length", 0,
                              validator=lambda v: v >= 0)
    toLowercase = BooleanParam("toLowercase", "lowercase before tokenizing", True)

    def transform(self, frame: Frame) -> Frame:
        _require_dtype(frame, self.inputCol, DType.STRING, "RegexTokenizer")
        regex = re.compile(self.pattern)
        gaps, min_len, lower = self.gaps, self.minTokenLength, self.toLowercase

        def tok(text: Optional[str]) -> List[str]:
            if text is None:
                return []
            if lower:
                text = text.lower()
            toks = regex.split(text) if gaps else regex.findall(text)
            return [t for t in toks if len(t) >= min_len and t]

        values = [tok(v) for v in frame.column(self.inputCol)]
        return frame.with_column_values(
            ColumnSchema(self.outputCol, DType.TOKENS), values)

    def transform_schema(self, schema):
        return schema.add(ColumnSchema(self.outputCol, DType.TOKENS))


@register_stage
class StopWordsRemover(HasInputCol, HasOutputCol, Transformer):
    """Filters stop words out of a tokens column.

    Reference config surface: ``TextFeaturizer.scala:246-256`` (case
    sensitivity + language presets + custom list).
    """

    caseSensitive = BooleanParam("caseSensitive", "case sensitive comparison", False)
    stopWords = ListParam("stopWords", "words to filter out",
                          list(ENGLISH_STOP_WORDS))

    def transform(self, frame: Frame) -> Frame:
        _require_dtype(frame, self.inputCol, DType.TOKENS, "StopWordsRemover")
        words = self.stopWords
        if self.caseSensitive:
            stop = frozenset(words)
            values = [[t for t in row if t not in stop]
                      for row in _token_rows(frame, self.inputCol)]
        else:
            stop = frozenset(w.lower() for w in words)
            values = [[t for t in row if t.lower() not in stop]
                      for row in _token_rows(frame, self.inputCol)]
        return frame.with_column_values(
            ColumnSchema(self.outputCol, DType.TOKENS), values)

    def transform_schema(self, schema):
        return schema.add(ColumnSchema(self.outputCol, DType.TOKENS))


@register_stage
class NGram(HasInputCol, HasOutputCol, Transformer):
    """Tokens -> space-joined n-grams (Spark NGram semantics: rows shorter
    than n produce an empty array)."""

    n = IntParam("n", "number of tokens per n-gram", 2,
                 validator=lambda v: v >= 1)

    def transform(self, frame: Frame) -> Frame:
        _require_dtype(frame, self.inputCol, DType.TOKENS, "NGram")
        n = self.n
        values = [[" ".join(row[i:i + n]) for i in range(len(row) - n + 1)]
                  for row in _token_rows(frame, self.inputCol)]
        return frame.with_column_values(
            ColumnSchema(self.outputCol, DType.TOKENS), values)

    def transform_schema(self, schema):
        return schema.add(ColumnSchema(self.outputCol, DType.TOKENS))


@register_stage
class HashingTF(HasInputCol, HasOutputCol, Estimator):
    """Tokens -> term-frequency vectors in a murmur3 hash space.

    Estimator (unlike Spark's stateless transformer) because by default the
    fitted model compacts the 2^18 hash space to the active slots seen at fit
    — the TPU-first dense layout. Slot indices are bit-identical to Spark's
    (``ops/hashing.py``), so a term's position within the active-slot ordering
    is auditable against the reference's pinned indices
    (``core/ml/src/test/scala/HashingTFSpec.scala:22-29``).

    ``compact=False`` restores Spark's stateless fixed-width contract: the
    output vector is always ``numFeatures`` wide and terms unseen at fit
    still land in their slot — use it when fitted models must stay
    column-compatible across datasets (e.g. serving with novel vocabulary).
    With the default ``compact=True``, unseen-at-fit terms are DROPPED at
    transform: width tracks the training corpus.

    NOTE: the output column is DENSE, so ``compact=False`` materializes
    n_rows x numFeatures float32 — pair it with a modest ``numFeatures``
    (e.g. 2^12), not the 2^18 default; transform raises rather than OOM.
    """

    numFeatures = IntParam("numFeatures", "hash space size", 1 << 18,
                           validator=lambda v: v > 0)
    binary = BooleanParam("binary", "clamp term counts to 1", False)
    compact = BooleanParam(
        "compact", "compact output to fit-time active slots (False = "
        "Spark-parity fixed numFeatures width)", True)

    def fit(self, frame: Frame) -> "HashingTFModel":
        _require_dtype(frame, self.inputCol, DType.TOKENS, "HashingTF")
        model = HashingTFModel(
            inputCol=self.inputCol, outputCol=self.outputCol,
            numFeatures=self.numFeatures, binary=self.binary,
            compact=self.compact)
        if self.compact:
            slots, _ = hash_token_rows(
                _token_rows(frame, self.inputCol), self.numFeatures)
            model._set_state({"slots": np.unique(slots)})
        else:  # stateless Spark behavior needs no fit-time scan
            model._set_state({"slots": np.zeros(0, np.int64)})
        return model


@register_stage
class HashingTFModel(HasInputCol, HasOutputCol, Model):
    numFeatures = IntParam("numFeatures", "hash space size", 1 << 18)
    binary = BooleanParam("binary", "clamp term counts to 1", False)
    compact = BooleanParam(
        "compact", "compact output to fit-time active slots (False = "
        "Spark-parity fixed numFeatures width)", True)

    @property
    def slots(self) -> np.ndarray:
        return self._get_state()["slots"]

    @property
    def width(self) -> int:
        return len(self.slots) if self.compact else self.numFeatures

    def transform(self, frame: Frame) -> Frame:
        _require_dtype(frame, self.inputCol, DType.TOKENS, "HashingTFModel")
        rows = _token_rows(frame, self.inputCol)
        width = self.width
        if len(rows) * width > (1 << 31):  # dense output: fail with guidance
            raise SchemaError(
                f"HashingTFModel: dense output {len(rows)}x{width} exceeds "
                "2^31 elements (~8 GB); lower numFeatures or use "
                "compact=True so width tracks the training corpus")
        out = np.zeros((len(rows), width), dtype=np.float32)
        if width:
            row_ptr, slots, counts = tf_csr(rows, self.numFeatures)
            rids = np.repeat(np.arange(len(rows), dtype=np.int64),
                             np.diff(row_ptr))
            vals = (np.ones_like(counts, np.float32) if self.binary
                    else counts.astype(np.float32))
            if self.compact:
                pos, ok = project_slots(self.slots, slots)
                out[rids[ok], pos[ok]] = vals[ok]  # unseen-at-fit slots dropped
            else:
                out[rids, slots] = vals
        return frame.with_column_values(
            ColumnSchema(self.outputCol, DType.VECTOR, dim=width), out)

    def transform_schema(self, schema):
        return schema.add(
            ColumnSchema(self.outputCol, DType.VECTOR, dim=self.width))


@register_stage
class IDF(HasInputCol, HasOutputCol, Estimator):
    """Inverse-document-frequency weighting over TF vectors.

    Spark formula: idf = log((numDocs + 1) / (docFreq + 1)); slots with
    docFreq < minDocFreq get weight 0 (``TextFeaturizer.scala:258-262``
    configures minDocFreq on Spark's IDF).
    """

    minDocFreq = IntParam("minDocFreq", "minimum docs a term must appear in", 1,
                          validator=lambda v: v >= 0)

    def fit(self, frame: Frame) -> "IDFModel":
        col = frame.schema[self.inputCol]
        if col.dtype != DType.VECTOR:
            raise SchemaError(f"IDF: input column {self.inputCol!r} must be "
                              f"vector, got {col.dtype.value}")
        mat = np.asarray(frame.column(self.inputCol), dtype=np.float32)
        n_docs = mat.shape[0]
        doc_freq = (mat != 0).sum(axis=0)
        idf = np.log((n_docs + 1.0) / (doc_freq + 1.0)).astype(np.float32)
        idf[doc_freq < self.minDocFreq] = 0.0
        model = IDFModel(inputCol=self.inputCol, outputCol=self.outputCol,
                         minDocFreq=self.minDocFreq)
        model._set_state({"idf": idf})
        return model


@register_stage
class IDFModel(HasInputCol, HasOutputCol, Model):
    minDocFreq = IntParam("minDocFreq", "minimum docs a term must appear in", 1)

    @property
    def idf(self) -> np.ndarray:
        return self._get_state()["idf"]

    def transform(self, frame: Frame) -> Frame:
        idf = self.idf
        mat = np.asarray(frame.column(self.inputCol), dtype=np.float32)
        if mat.shape[1] != idf.shape[0]:
            raise SchemaError(
                f"IDFModel: vector width {mat.shape[1]} != fitted {idf.shape[0]}")
        out = (mat * idf[None, :]).astype(np.float32)
        return frame.with_column_values(
            ColumnSchema(self.outputCol, DType.VECTOR, dim=out.shape[1]), out)

    def transform_schema(self, schema):
        return schema.add(
            ColumnSchema(self.outputCol, DType.VECTOR, dim=len(self.idf)))


@register_stage
class TextFeaturizer(HasInputCol, HasOutputCol, Estimator):
    """One-line text pipeline: tokenize -> stop words -> n-grams -> TF -> IDF,
    every stage optional, auto-chained.

    Parity with ``TextFeaturizer.scala:140-290``: the same param surface
    (tokenizer gaps/pattern/minTokenLength/toLowercase, stop-word case
    sensitivity/language/custom list, nGramLength, binary/numFeatures,
    useIDF/minDocFreq), the same auto-detection of ``useTokenizer`` from the
    input column type, and the same intermediate-column dropping.
    """

    useTokenizer = Param("useTokenizer", "whether to tokenize the input",
                         None, dtype=bool)
    tokenizerGaps = BooleanParam("tokenizerGaps", "regex splits on gaps", True)
    minTokenLength = IntParam("minTokenLength", "minimum token length", 0)
    tokenizerPattern = StringParam(
        "tokenizerPattern", "regex for delimiters or tokens", r"\s+")
    toLowercase = BooleanParam("toLowercase", "lowercase before tokenizing", True)
    useStopWordsRemover = BooleanParam(
        "useStopWordsRemover", "remove stop words from tokens", False)
    caseSensitiveStopWords = BooleanParam(
        "caseSensitiveStopWords", "case sensitive stop word match", False)
    defaultStopWordLanguage = StringParam(
        "defaultStopWordLanguage",
        "stop word language preset; 'custom' uses the stopWords param",
        "english", domain=list(STOP_WORD_LANGUAGES) + ["custom"])
    stopWords = ListParam("stopWords", "custom stop words", [])
    useNGram = BooleanParam("useNGram", "enumerate n-grams", False)
    nGramLength = IntParam("nGramLength", "n-gram size", 2)
    binary = BooleanParam("binary", "clamp term counts to 1", False)
    numFeatures = IntParam("numFeatures", "hash space size", 1 << 18)
    useIDF = BooleanParam("useIDF", "scale TF by IDF", True)
    minDocFreq = IntParam("minDocFreq", "IDF minimum document frequency", 1)

    def fit(self, frame: Frame) -> "TextFeaturizerModel":
        use_tok = self.get("useTokenizer")
        if use_tok is None:  # auto-detect from column type (fit():232-236)
            use_tok = frame.schema[self.inputCol].dtype == DType.STRING
        stages = []
        if use_tok:
            stages.append(RegexTokenizer(
                gaps=self.tokenizerGaps, pattern=self.tokenizerPattern,
                minTokenLength=self.minTokenLength, toLowercase=self.toLowercase))
        if self.useStopWordsRemover:
            lang = self.defaultStopWordLanguage
            words = (self.stopWords if lang == "custom"
                     else STOP_WORD_LANGUAGES[lang])
            stages.append(StopWordsRemover(
                caseSensitive=self.caseSensitiveStopWords,
                stopWords=list(words)))
        if self.useNGram:
            stages.append(NGram(n=self.nGramLength))
        stages.append(HashingTF(numFeatures=self.numFeatures, binary=self.binary))
        if self.useIDF:
            stages.append(IDF(minDocFreq=self.minDocFreq))

        if not use_tok and frame.schema[self.inputCol].dtype != DType.TOKENS:
            raise SchemaError(
                f"TextFeaturizer: input column {self.inputCol!r} is "
                f"{frame.schema[self.inputCol].dtype.value}; it looks like "
                "your data is not tokenized, try useTokenizer=True")

        # Auto-chain input/output columns (fit():267-285) through unused
        # temp names, last stage writes outputCol.
        in_col = self.inputCol
        tmp_cols: List[str] = []
        fitted = []
        cur = frame
        for i, stage in enumerate(stages):
            is_last = i == len(stages) - 1
            out_col = self.outputCol if is_last else f"{self.uid}__tmp{i}"
            if not is_last:
                tmp_cols.append(out_col)
            stage.set_params(inputCol=in_col, outputCol=out_col)
            model = stage.fit(cur) if isinstance(stage, Estimator) else stage
            if not is_last:  # no frame pass needed beyond the last stage
                cur = model.transform(cur)
            fitted.append(model)
            in_col = out_col
        model = TextFeaturizerModel(
            inputCol=self.inputCol, outputCol=self.outputCol)
        model.set_params(stages=fitted, colsToDrop=tmp_cols)
        return model


@register_stage
class TextFeaturizerModel(HasInputCol, HasOutputCol, Model):
    from mmlspark_tpu.core.params import AnyParam as _AnyParam
    stages = _AnyParam("stages", "fitted chain of text stages", default=[])
    colsToDrop = ListParam("colsToDrop", "intermediate columns to drop", [])

    def transform(self, frame: Frame) -> Frame:
        for stage in self.get("stages"):
            frame = stage.transform(frame)
        drop = [c for c in self.get("colsToDrop") if c in frame.schema.names]
        return frame.drop(*drop) if drop else frame

    def transform_schema(self, schema):
        for stage in self.get("stages"):
            schema = stage.transform_schema(schema)
        drop = [c for c in self.get("colsToDrop") if c in schema.names]
        return schema.drop(drop) if drop else schema
