"""Per-image ops backing the ImageTransformer stages, in vectorized numpy.

Counterparts of the reference's OpenCV stage set
(``image-transformer/src/main/scala/ImageTransformer.scala:23-154``):
resize / crop / colorformat / blur / threshold / gaussiankernel, plus flip
and normalize. Host-side numpy handles ragged pre-resize sizes; once images
are uniform, the batched fused path (``mmlspark_tpu.ops.pallas_preprocess``)
takes over on device.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# colorformat codes (subset of OpenCV's, same names)
BGR2GRAY = "bgr2gray"
GRAY2BGR = "gray2bgr"
BGR2RGB = "bgr2rgb"
RGB2BGR = "rgb2bgr"

THRESH_BINARY = "binary"
THRESH_BINARY_INV = "binary_inv"
THRESH_TRUNC = "trunc"
THRESH_TOZERO = "tozero"
THRESH_TOZERO_INV = "tozero_inv"


def _resize_stack(stack: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize of a uniform (N, H, W, C) stack, half-pixel centers
    (OpenCV INTER_LINEAR convention). One vectorized gather/lerp for the
    whole stack — the per-image loop is the hot-path sin."""
    n, h, w = stack.shape[:3]
    ys = (np.arange(height) + 0.5) * h / height - 0.5
    xs = (np.arange(width) + 0.5) * w / width - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[None, :, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, None, :, None]
    f = stack.astype(np.float32)
    r0, r1 = f[:, y0], f[:, y1]
    top = r0[:, :, x0] * (1 - wx) + r0[:, :, x1] * wx
    bot = r1[:, :, x0] * (1 - wx) + r1[:, :, x1] * wx
    out = top * (1 - wy) + bot * wy
    if stack.dtype == np.uint8:
        out = np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return out


def resize(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize, half-pixel centers (OpenCV INTER_LINEAR convention)."""
    h, w = img.shape[:2]
    if (h, w) == (height, width):
        return img
    squeeze = img.ndim == 2
    out = _resize_stack(img[None, :, :, None] if squeeze else img[None],
                        height, width)[0]
    return out[:, :, 0] if squeeze else out


def resize_many(imgs, height: int, width: int):
    """Resize a ragged list of images, batching every same-(shape, dtype)
    group through ONE vectorized ``_resize_stack`` call. Order preserved."""
    out = [None] * len(imgs)
    groups: dict = {}
    for i, im in enumerate(imgs):
        if im.shape[:2] == (height, width):
            out[i] = im
        else:
            groups.setdefault((im.shape, str(im.dtype)), []).append(i)
    for (shape, _), idxs in groups.items():
        stack = np.stack([imgs[i] for i in idxs])
        squeeze = len(shape) == 2
        res = _resize_stack(stack[..., None] if squeeze else stack,
                            height, width)
        for j, i in enumerate(idxs):
            out[i] = res[j, :, :, 0] if squeeze else res[j]
    return out


def crop(img: np.ndarray, x: int, y: int, height: int, width: int) -> np.ndarray:
    h, w = img.shape[:2]
    if y < 0 or x < 0 or y + height > h or x + width > w:
        raise ValueError(f"crop ({x},{y},{width}x{height}) outside {w}x{h}")
    return img[y:y + height, x:x + width]


def center_crop(img: np.ndarray, height: int, width: int) -> np.ndarray:
    h, w = img.shape[:2]
    return crop(img, (w - width) // 2, (h - height) // 2, height, width)


def color_format(img: np.ndarray, fmt: str) -> np.ndarray:
    if fmt == BGR2GRAY:
        # OpenCV luma weights for BGR order
        gray = (img[..., 0] * 0.114 + img[..., 1] * 0.587
                + img[..., 2] * 0.299)
        out = np.rint(gray) if img.dtype == np.uint8 else gray
        return out.astype(img.dtype)[..., None]
    if fmt == GRAY2BGR:
        ch = img if img.ndim == 2 else img[..., 0]
        return np.repeat(ch[..., None], 3, axis=-1)
    if fmt in (BGR2RGB, RGB2BGR):
        return img[..., ::-1]
    raise ValueError(f"unknown color format {fmt!r}")


def blur(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Box blur with BORDER_REFLECT_101-ish edge handling via edge padding."""
    kh, kw = int(height), int(width)
    img_f = img.astype(np.float32)
    if img_f.ndim == 2:
        img_f = img_f[:, :, None]
    out = _separable_filter(img_f, np.full(kh, 1.0 / kh, np.float32),
                            np.full(kw, 1.0 / kw, np.float32))
    if img.dtype == np.uint8:
        out = np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return out if img.ndim == 3 else out[:, :, 0]


def gaussian_kernel_1d(aperture: int, sigma: float) -> np.ndarray:
    """OpenCV getGaussianKernel: sigma<=0 -> 0.3*((ksize-1)*0.5 - 1) + 0.8."""
    if sigma <= 0:
        sigma = 0.3 * ((aperture - 1) * 0.5 - 1) + 0.8
    xs = np.arange(aperture, dtype=np.float64) - (aperture - 1) / 2
    k = np.exp(-(xs ** 2) / (2 * sigma * sigma))
    return (k / k.sum()).astype(np.float32)


def gaussian_blur(img: np.ndarray, aperture: int, sigma: float) -> np.ndarray:
    """Reference GaussianKernel stage: filter2D with a 1-D vertical gaussian
    kernel (a COLUMN filter, not a full 2-D gaussian)."""
    k = gaussian_kernel_1d(aperture, sigma)
    img_f = img.astype(np.float32)
    if img_f.ndim == 2:
        img_f = img_f[:, :, None]
    out = _separable_filter(img_f, k, np.ones(1, np.float32))
    if img.dtype == np.uint8:
        out = np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return out if img.ndim == 3 else out[:, :, 0]


def threshold(img: np.ndarray, thresh: float, max_val: float,
              ttype: str = THRESH_BINARY) -> np.ndarray:
    x = img.astype(np.float32)
    if ttype == THRESH_BINARY:
        out = np.where(x > thresh, max_val, 0.0)
    elif ttype == THRESH_BINARY_INV:
        out = np.where(x > thresh, 0.0, max_val)
    elif ttype == THRESH_TRUNC:
        out = np.minimum(x, thresh)
    elif ttype == THRESH_TOZERO:
        out = np.where(x > thresh, x, 0.0)
    elif ttype == THRESH_TOZERO_INV:
        out = np.where(x > thresh, 0.0, x)
    else:
        raise ValueError(f"unknown threshold type {ttype!r}")
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def flip(img: np.ndarray, horizontal: bool = True) -> np.ndarray:
    return img[:, ::-1] if horizontal else img[::-1]


def _separable_filter(img: np.ndarray, kcol: np.ndarray,
                      krow: np.ndarray) -> np.ndarray:
    """Apply column then row 1-D filters with edge padding (H, W, C)."""
    kh, kw = len(kcol), len(krow)
    ph, pw = kh // 2, kw // 2
    padded = np.pad(img, ((ph, kh - 1 - ph), (0, 0), (0, 0)), mode="edge")
    out = np.zeros_like(img)
    for i, kv in enumerate(kcol):
        out += kv * padded[i:i + img.shape[0]]
    if kw > 1:
        padded = np.pad(out, ((0, 0), (pw, kw - 1 - pw), (0, 0)), mode="edge")
        out2 = np.zeros_like(out)
        for i, kv in enumerate(krow):
            out2 += kv * padded[:, i:i + img.shape[1]]
        out = out2
    return out
