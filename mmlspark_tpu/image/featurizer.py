"""ImageFeaturizer: transfer-learning featurization of image columns.

Re-expression of ``image-featurizer/src/main/scala/ImageFeaturizer.scala:85-128``:
composes (a) resize to the model's input dims, (b) unroll to a vector,
(c) JaxModel scoring with ``cutOutputLayers`` selecting how many layers to
cut off the end — 0 scores the head, 1 emits the pooled feature layer
(the ``layerNames`` contract of the model zoo / downloader schema).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    AnyParam, DictParam, HasInputCol, HasOutputCol, IntParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import DType, SchemaError
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.image.transformer import ImageTransformer, UnrollImage
from mmlspark_tpu.models.jax_model import JaxModel
from mmlspark_tpu.models.zoo import build_model

# input frame -> {prep fingerprint: unrolled frame}. Repeat featurization
# of the SAME frame (transfer-learning fit loops, benchmark trials)
# re-did the host resize/unroll AND produced a fresh intermediate frame,
# which also defeated JaxModel's deviceCache (keyed on frame identity).
# Memoizing the prepared frame makes the second pass pure compute: host
# prep skipped, device upload reused. Weak keys: the memo dies with the
# input frame, like models/residency.
import weakref  # noqa: E402

_PREP_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


@register_stage
class ImageFeaturizer(HasInputCol, HasOutputCol, Transformer):
    architecture = StringParam("architecture", "model zoo architecture", "")
    architectureArgs = DictParam("architectureArgs",
                                 "architecture builder kwargs", {})
    cutOutputLayers = IntParam(
        "cutOutputLayers", "how many layers to cut from the end "
        "(0 = head logits, 1 = feature layer)", 1,
        validator=lambda v: v >= 0)
    miniBatchSize = IntParam("miniBatchSize", "scoring batch size", 512)
    meshSpec = AnyParam(
        "meshSpec", "shard the scoring net over a device mesh (MeshSpec / "
        "axis-size dict / Mesh; None = single-device) — model-parallel "
        "featurization for backbones one chip cannot hold; forwarded to "
        "the internal JaxModel", None)
    computeDtype = StringParam(
        "computeDtype", "backbone compute + feature wire precision "
        "(forwarded to the internal JaxModel): 'bfloat16' runs the "
        "convs/matmuls MXU-native and fetches embeddings at half the "
        "bytes — the TPU-idiomatic choice for transfer-learning "
        "features; 'float32' is exact", "float32",
        domain=("float32", "bfloat16"))

    def __init__(self, uid=None, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "features")
        super().__init__(uid, **kwargs)

    def set_model(self, architecture: str, params=None, seed: int = 0,
                  input_mean=None, input_std=None,
                  **arch_kwargs) -> "ImageFeaturizer":
        """``input_mean``/``input_std``: the normalization the net was
        trained with (per-channel or scalar) — fused on device ahead of
        the first layer (JaxModel.set_model owns the plumbing)."""
        self.set_params(architecture=architecture,
                        architectureArgs=dict(arch_kwargs))
        jm = JaxModel()
        jm.set_model(architecture, params=params, seed=seed,
                     input_mean=input_mean, input_std=input_std,
                     **arch_kwargs)
        self._state = {k: v for k, v in jm._state.items()
                       if k in ("params", "input_mu", "input_sigma")}
        self._jm_cache = None  # new params -> stale scoring model
        return self

    def set_model_from_downloader(self, downloader, name: str):
        schema = downloader.repo.find_by_name(name)
        return self.set_model(schema.architecture,
                              params=downloader.load_params(name),
                              input_mean=schema.inputMean or None,
                              input_std=schema.inputStd or None,
                              **schema.architectureArgs)

    def transform(self, frame: Frame) -> Frame:
        if not self.architecture or "params" not in self._get_state():
            raise SchemaError("ImageFeaturizer: call set_model() first")
        spec = build_model(self.architecture, **self.get("architectureArgs"))
        in_shape = spec["input_shape"]
        if len(in_shape) != 3:
            raise SchemaError(
                f"architecture {self.architecture!r} is not an image model")
        layer_names = list(spec["layer_names"])
        cut = self.cutOutputLayers
        if cut >= len(layer_names):
            raise SchemaError(
                f"cutOutputLayers={cut} but model has {len(layer_names)} "
                f"named layers {layer_names}")
        node = "" if cut == 0 else layer_names[-(cut + 1)]

        tmp_vec = frame.schema.find_unused_name("_unrolled")
        in_dtype = frame.schema[self.inputCol].dtype
        prep_key = (self.inputCol, tuple(int(v) for v in in_shape))
        entry = _PREP_CACHE.get(frame)
        if entry is not None and prep_key in entry:
            unrolled, device_pre = entry[prep_key]
        else:
            # Fast path — the north-star fusion: when the column holds
            # uniform uint8 HWC images, skip the host resize entirely. Raw
            # uint8 crosses host->HBM (1/4 the bytes of fp32) and
            # reshape+bilinear-resize run ON DEVICE fused into the scoring
            # jit, ahead of the first conv. One pass collects
            # (shape, dtype); the result also answers the general path's
            # wire-format question (binary input decodes to uint8, so only
            # float IMAGE values force the float32 unroll). The scan (and
            # everything after it) runs once per frame: the memo key only
            # needs the input column and target shape.
            variants = ({(v.data.shape, v.data.dtype)
                         for p in frame.partitions
                         for v in p[self.inputCol]}
                        if in_dtype == DType.IMAGE else set())
            all_u8 = (in_dtype != DType.IMAGE
                      or all(dt == np.dtype(np.uint8) for _, dt in variants))
            fused = (len(variants) == 1 and all_u8
                     and len(next(iter(variants))[0]) == 3
                     and next(iter(variants))[0][2] == in_shape[2])
            device_pre = {}
            if fused:
                src_shape = next(iter(variants))[0]
                unrolled = UnrollImage(inputCol=self.inputCol,
                                       outputCol=tmp_vec,
                                       outputDtype="uint8").transform(frame)
                device_pre = {"srcShape": [int(v) for v in src_shape],
                              "resize": [int(in_shape[0]), int(in_shape[1])]}
            else:
                # General path: ragged sizes / float data / gray images
                # resize on host (batched by shape group), then unroll.
                tmp_img = frame.schema.find_unused_name("_resized")
                resized = ImageTransformer(inputCol=self.inputCol,
                                           outputCol=tmp_img) \
                    .resize(in_shape[0], in_shape[1]).transform(frame)
                # uint8 wire format when the data allows it: 4x less
                # host->HBM traffic; JaxModel casts to float on device.
                # Float image data (user-built ImageValue) keeps the
                # lossless float32 unroll.
                unrolled = UnrollImage(
                    inputCol=tmp_img, outputCol=tmp_vec,
                    outputDtype="uint8" if all_u8 else "float32") \
                    .transform(resized).drop(tmp_img)
            if entry is None:
                # single-frame policy (same as models/residency): a NEW
                # frame evicts other frames' memoized unrolls, bounding
                # host RAM at ~one unrolled dataset, not one per frame
                # ever featurized
                _PREP_CACHE.clear()
                entry = _PREP_CACHE.setdefault(frame, {})
            entry[prep_key] = (unrolled, device_pre)
        # The scoring JaxModel is cached across transform() calls: a fresh
        # one per call would pay the jit compile (20-40s on TPU) every time.
        key = (self.architecture, repr(self.get("architectureArgs")), node,
               self.miniBatchSize, repr(device_pre),
               repr(self.get("meshSpec")), self.get("computeDtype"))
        jm = getattr(self, "_jm_cache", None)
        if jm is None or getattr(self, "_jm_key", None) != key:
            jm = JaxModel(inputCol=tmp_vec, outputCol=self.outputCol,
                          miniBatchSize=self.miniBatchSize,
                          outputNodeName=node,
                          devicePreprocess=device_pre,
                          computeDtype=self.get("computeDtype"),
                          meshSpec=self.get("meshSpec"))
            jm.set_params(architecture=self.architecture,
                          architectureArgs=self.get("architectureArgs"))
            jm._state = {"params": self._state["params"]}
            for k in ("input_mu", "input_sigma"):
                if k in self._state:
                    jm._state[k] = self._state[k]
            self._jm_cache, self._jm_key = jm, key
        else:
            jm.set_params(inputCol=tmp_vec, outputCol=self.outputCol)
        out = jm.transform(unrolled)
        return out.drop(tmp_vec)
