"""ImageTransformer + UnrollImage: declarative per-row image pipelines.

Re-expression of ``image-transformer/src/main/scala/ImageTransformer.scala``:
the stage list is a JSON-able param (the reference's ArrayMapParam), stages
apply in order per image, and the transformer accepts image OR raw binary
input (decoding first, ``transform`` ``:272-304``).

UnrollImage converts an image row to a flat float32 vector
(``UnrollImage.scala:18-42``). TPU-first difference, deliberate: unroll
order is HWC (XLA's native NHWC conv layout) rather than the reference's
CHW, and the uint8->float conversion needs no sign fixup because the bytes
never pass through a signed JVM byte array.

Placement decision: these stages run on HOST (vectorized numpy, shape-
grouped batching) because their contract is host-value -> host-value — a
device round trip per stage would pay host->HBM->host twice for elementwise
work. The DEVICE versions of the hot path (resize + requantize + normalize)
live where they can fuse into a consumer's jit instead: JaxModel's
``devicePreprocess`` / ``ops.pallas_preprocess``, which ImageFeaturizer
routes uniform uint8 inputs through automatically — same half-pixel and
uint8-rounding semantics, pinned by tests, so host and device paths are
interchangeable.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    HasInputCol, HasOutputCol, ListParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import ColumnSchema, DType, ImageValue, SchemaError
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.image import ops
from mmlspark_tpu.io.codecs import decode_image

STAGE_REGISTRY = {
    "resize": lambda img, p: ops.resize(img, p["height"], p["width"]),
    "crop": lambda img, p: ops.crop(img, p["x"], p["y"], p["height"], p["width"]),
    "centercrop": lambda img, p: ops.center_crop(img, p["height"], p["width"]),
    "colorformat": lambda img, p: ops.color_format(img, p["format"]),
    "blur": lambda img, p: ops.blur(img, p["height"], p["width"]),
    "threshold": lambda img, p: ops.threshold(
        img, p["threshold"], p["maxVal"], p.get("type", ops.THRESH_BINARY)),
    "gaussiankernel": lambda img, p: ops.gaussian_blur(
        img, p["appertureSize"], p["sigma"]),
    "flip": lambda img, p: ops.flip(img, p.get("horizontal", True)),
}


@register_stage
class ImageTransformer(HasInputCol, HasOutputCol, Transformer):
    stages = ListParam("stages", "ordered list of stage descriptor dicts", [])

    def __init__(self, uid=None, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(uid, **kwargs)

    # -- fluent builders (reference ImageTransformer setters) ---------------
    def _add(self, stage: Dict[str, Any]) -> "ImageTransformer":
        self.set("stages", list(self.stages) + [stage])
        return self

    def resize(self, height: int, width: int):
        return self._add({"op": "resize", "height": height, "width": width})

    def crop(self, x: int, y: int, height: int, width: int):
        return self._add({"op": "crop", "x": x, "y": y,
                          "height": height, "width": width})

    def center_crop(self, height: int, width: int):
        return self._add({"op": "centercrop", "height": height, "width": width})

    def color_format(self, fmt: str):
        return self._add({"op": "colorformat", "format": fmt})

    def blur(self, height: int, width: int):
        return self._add({"op": "blur", "height": height, "width": width})

    def threshold(self, threshold: float, max_val: float,
                  ttype: str = ops.THRESH_BINARY):
        return self._add({"op": "threshold", "threshold": threshold,
                          "maxVal": max_val, "type": ttype})

    def gaussian_kernel(self, aperture_size: int, sigma: float):
        return self._add({"op": "gaussiankernel",
                          "appertureSize": aperture_size, "sigma": sigma})

    def flip(self, horizontal: bool = True):
        return self._add({"op": "flip", "horizontal": horizontal})

    # -- transform ----------------------------------------------------------
    def transform(self, frame: Frame) -> Frame:
        in_col = frame.schema[self.inputCol]
        stages = list(self.stages)
        for s in stages:
            if s.get("op") not in STAGE_REGISTRY:
                raise SchemaError(f"unknown image stage {s.get('op')!r}")

        def run(p):
            arr = p[self.inputCol]
            paths: List[Optional[str]] = []
            datas: List[np.ndarray] = []
            for i, v in enumerate(arr):
                if in_col.dtype == DType.BINARY:
                    data = decode_image(v)
                    if data is None:
                        raise SchemaError(
                            f"undecodable bytes at row {i}; use read_images "
                            "to drop undecodable files instead")
                    paths.append(None)
                    datas.append(data)
                elif in_col.dtype == DType.IMAGE:
                    paths.append(v.path)
                    datas.append(v.data)
                else:
                    raise SchemaError(
                        f"column {self.inputCol!r} is {in_col.dtype.value}, "
                        "need image or binary")
            # Columnar execution: each stage sweeps the whole partition, so
            # resize batches every same-shape group through one vectorized
            # call instead of a per-image Python loop.
            for s in stages:
                if s["op"] == "resize":
                    datas = ops.resize_many(datas, s["height"], s["width"])
                else:
                    datas = [STAGE_REGISTRY[s["op"]](d, s) for d in datas]
            out = np.empty(len(arr), dtype=np.object_)
            for i, (pth, data) in enumerate(zip(paths, datas)):
                out[i] = ImageValue(path=pth, data=data)
            return out

        return frame.with_column(
            ColumnSchema(self.outputCol, DType.IMAGE), run)


@register_stage
class UnrollImage(HasInputCol, HasOutputCol, Transformer):
    """image -> flat vector (HWC order), requires uniform sizes.

    ``outputDtype='float32'`` (default) matches the reference's
    image->DenseVector contract (``UnrollImage.scala:18-42``);
    ``'uint8'`` keeps the raw bytes — 4x less host->HBM traffic when the
    consumer (JaxModel) casts on device, the fused-preprocess fast path.
    """

    outputDtype = StringParam("outputDtype", "unrolled element type",
                              "float32", domain=("float32", "uint8"))

    def __init__(self, uid=None, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "features")
        super().__init__(uid, **kwargs)

    def transform(self, frame: Frame) -> Frame:
        # Determine the uniform image shape globally first so empty
        # partitions can still emit correctly-dimensioned (0, N) blocks.
        shapes = {v.data.shape for p in frame.partitions
                  for v in p[self.inputCol]}
        if len(shapes) > 1:
            raise SchemaError(
                f"unroll requires uniform image sizes, got {shapes}; "
                "resize first")
        dim = int(np.prod(next(iter(shapes)))) if shapes else 0
        dtype = np.uint8 if self.outputDtype == "uint8" else np.float32
        if dtype == np.uint8:
            bad = {v.data.dtype for p in frame.partitions
                   for v in p[self.inputCol]} - {np.dtype(np.uint8)}
            if bad:
                raise SchemaError(
                    f"outputDtype='uint8' would truncate {sorted(map(str, bad))} "
                    "image data; use the default float32")

        def unroll(p):
            arr = p[self.inputCol]
            if len(arr) == 0:
                return np.zeros((0, dim), dtype)
            return np.stack([v.data.reshape(-1).astype(dtype)
                             for v in arr])

        return frame.with_column(
            ColumnSchema(self.outputCol, DType.VECTOR, dim or None), unroll)
