"""Random Frame generation for fuzzing and property tests.

Re-expression of the reference's random-dataset generator
(``core/test/datagen/src/main/scala/GenerateDataset.scala:27-64``): a seeded
generator produces frames with randomly chosen column kinds under caller
constraints, so save/load fuzzing and pipeline fuzzing never depend on real
data (SURVEY.md §4 "key fixture idea").
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.frame import Frame

COLUMN_KINDS = ("int", "float", "double", "bool", "string", "tokens", "vector")

_WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india juliet "
          "kilo lima mike november oscar papa quebec romeo sierra tango").split()


def random_column(kind: str, n_rows: int, rng: np.random.Generator,
                  missing_ratio: float = 0.0, vector_dim: int = 4):
    """One random column of the given kind; object kinds honor missing_ratio."""
    miss = rng.uniform(0, 1, n_rows) < missing_ratio
    if kind == "int":
        return rng.integers(-100, 100, n_rows).astype(np.int32)
    if kind == "float":
        vals = rng.normal(0, 10, n_rows).astype(np.float32)
        vals[miss] = np.nan
        return vals
    if kind == "double":
        vals = rng.normal(0, 10, n_rows).astype(np.float64)
        vals[miss] = np.nan
        return vals
    if kind == "bool":
        return rng.uniform(0, 1, n_rows) > 0.5
    if kind == "string":
        return [None if m else rng.choice(_WORDS) for m in miss]
    if kind == "tokens":
        return [None if m else
                [str(w) for w in rng.choice(_WORDS, size=rng.integers(0, 6))]
                for m in miss]
    if kind == "vector":
        return rng.normal(0, 1, (n_rows, vector_dim)).astype(np.float32)
    raise ValueError(f"unknown column kind {kind!r}")


def generate_frame(n_rows: int = 32, n_cols: int = 4, seed: int = 0,
                   kinds: Optional[Sequence[str]] = None,
                   missing_ratio: float = 0.0,
                   num_partitions: int = 2,
                   with_label: Optional[str] = None,
                   n_classes: int = 2) -> Frame:
    """Random frame with ``n_cols`` columns of random (or given) kinds.

    ``with_label``: add a ``"label"`` column — "class" (int in [0,n_classes))
    or "real" (float). Column names are ``col0..colN``.
    """
    rng = np.random.default_rng(seed)
    data: Dict[str, object] = {}
    for i in range(n_cols):
        kind = kinds[i % len(kinds)] if kinds else rng.choice(COLUMN_KINDS)
        data[f"col{i}"] = random_column(str(kind), n_rows, rng, missing_ratio)
    if with_label == "class":
        data["label"] = rng.integers(0, n_classes, n_rows).astype(np.int32)
    elif with_label == "real":
        data["label"] = rng.normal(0, 1, n_rows).astype(np.float64)
    return Frame.from_dict(data, num_partitions=num_partitions)
