"""Seeded, virtual-time, OPEN-LOOP workload generation (docs/OBSERVABILITY.md).

The bench and chaos drivers used to be closed loops: every client waited
for its previous reply before offering the next request, so the moment
the fleet wedged, the offered load politely stopped — and the recorded
latency stopped with it. That is the coordinated-omission failure mode
(Tene, "How NOT to Measure Latency"; Schroeder et al., "Open Versus
Closed: A Cautionary Tale", NSDI'06): the p99 of a stalled system looks
*better* because the stall suppressed the samples that would have shown
it.

This module is the other half of the fix (the measurement half lives in
:mod:`mmlspark_tpu.observability.goodput`): a workload is a pure,
seeded function ``(seed, Trace) -> [Arrival, ...]`` — every request's
INTENDED arrival time decided before the system under test runs, so the
driver can always answer "when should this have arrived?" no matter how
the system behaves. Properties:

- **Arrival processes** — ``poisson`` (non-homogeneous, Lewis–Shedler
  thinning against the trace's rate curve) and ``pareto`` (heavy-tailed
  inter-arrival gaps, the bursty regime a memoryless process smooths
  away).
- **Trace shapes** — ``constant``, ``diurnal`` (sinusoidal rate swing),
  and ``spike`` (flash crowd: ``rate * spike_factor`` inside a window).
- **Tenant mixes** and open-loop **multi-turn sessions**: a session's
  turn ``k`` is scheduled at ``t0 + k * think_s`` from the session's
  own intent, never from the previous reply.
- **Shared-prefix prompt populations** (:class:`PromptPopulation`):
  Zipf-weighted prefix reuse for the decode lanes.
- **Zipf-hot recommender payloads** (:func:`zipf_ids`,
  :func:`recommender_rows`): packed ``[dense | ids]`` scoring rows with
  the hot-user/hot-item skew the embedding lanes serve under.
- **Virtual time** — schedules are data; :class:`EventQueue` and the
  two reference simulators walk them in virtual time, so ~10^5–10^6
  virtual users cost heap events, not threads, and compose with the
  injectable clock the rest of the stack runs on
  (:func:`mmlspark_tpu.observability.events.set_clock`).
- **Byte-identical replay** — same ``(seed, trace)`` -> the same
  schedule, asserted via :func:`schedule_fingerprint` (sha256 over the
  canonical serialization).

Chaos scenarios and bench lanes construct load ONLY through this
vocabulary (lint rule 16, ``reliability/lint.py``); a deliberate
hand-rolled exception marks the line ``# lint: allow-handload``.
"""
from __future__ import annotations

import hashlib
import heapq
import math
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Arrival", "Trace", "rate_at", "peak_rate", "generate",
    "schedule_fingerprint", "bucket_counts", "feature_rows",
    "token_prompts", "zipf_ids", "recommender_rows",
    "PromptPopulation", "EventQueue",
    "simulate_open_loop", "simulate_closed_loop", "run_open_loop",
]


@dataclass(frozen=True, order=True)
class Arrival:
    """One intended request: WHEN it should arrive, decided up front."""
    t: float                 # intended arrival time, seconds from trace t0
    index: int               # position in schedule order (ties broken here)
    tenant: str = "default"
    session: str = ""        # session id when the trace is multi-turn
    turn: int = 0            # 0-based turn within the session

    @property
    def trace_id(self) -> str:
        if self.session:
            return f"{self.session}.t{self.turn}"
        return f"q{self.index:06d}"


@dataclass(frozen=True)
class Trace:
    """The declarative workload spec — everything but the seed.

    ``rate`` is the base arrivals/second; the shape modulates it over
    ``duration_s``. ``session_turns > 1`` turns each first arrival into
    a session whose later turns land ``think_s`` apart (open-loop).
    """
    duration_s: float
    rate: float
    shape: str = "constant"           # constant | diurnal | spike
    process: str = "poisson"          # poisson | pareto
    spike_start_s: float = 0.0
    spike_len_s: float = 0.0
    spike_factor: float = 1.0
    diurnal_period_s: float = 0.0     # 0 -> one full period over the trace
    diurnal_amplitude: float = 0.5    # fraction of rate swung by the sine
    pareto_alpha: float = 1.5         # tail shape; mean requires alpha > 1
    tenants: Tuple[Tuple[str, float], ...] = (("default", 1.0),)
    session_turns: int = 1            # max turns per session (uniform draw)
    think_s: float = 0.0              # inter-turn gap for sessions

    def describe(self) -> Dict[str, Any]:
        d = {"duration_s": self.duration_s, "rate": self.rate,
             "shape": self.shape, "process": self.process,
             "tenants": dict(self.tenants)}
        if self.shape == "spike":
            d.update(spike_start_s=self.spike_start_s,
                     spike_len_s=self.spike_len_s,
                     spike_factor=self.spike_factor)
        if self.shape == "diurnal":
            d.update(diurnal_period_s=self.diurnal_period_s or
                     self.duration_s,
                     diurnal_amplitude=self.diurnal_amplitude)
        if self.process == "pareto":
            d["pareto_alpha"] = self.pareto_alpha
        if self.session_turns > 1:
            d.update(session_turns=self.session_turns,
                     think_s=self.think_s)
        return d


def rate_at(trace: Trace, t: float) -> float:
    """Instantaneous offered rate (arrivals/s) at trace time ``t``."""
    if trace.shape == "spike":
        if trace.spike_start_s <= t < trace.spike_start_s + trace.spike_len_s:
            return trace.rate * trace.spike_factor
        return trace.rate
    if trace.shape == "diurnal":
        period = trace.diurnal_period_s or trace.duration_s
        swing = math.sin(2.0 * math.pi * t / max(period, 1e-9))
        return max(0.0, trace.rate * (1.0 + trace.diurnal_amplitude * swing))
    if trace.shape == "constant":
        return trace.rate
    raise ValueError(f"unknown trace shape {trace.shape!r}")


def peak_rate(trace: Trace) -> float:
    """Upper bound of the rate curve — the thinning envelope."""
    if trace.shape == "spike":
        return trace.rate * max(1.0, trace.spike_factor)
    if trace.shape == "diurnal":
        return trace.rate * (1.0 + max(0.0, trace.diurnal_amplitude))
    return trace.rate


def _arrival_times(trace: Trace, rng: random.Random) -> List[float]:
    """First-turn arrival times over ``[0, duration_s)``.

    ``poisson``: Lewis–Shedler thinning — candidates from a homogeneous
    process at the peak rate, kept with probability ``rate_at/peak``.
    ``pareto``: heavy-tailed gaps scaled so the LOCAL mean inter-arrival
    matches ``1/rate_at`` (bursts plus long silences at the same average
    load a Poisson trace offers).
    """
    lam = peak_rate(trace)
    if lam <= 0:
        return []
    out: List[float] = []
    t = 0.0
    if trace.process == "poisson":
        while True:
            t += rng.expovariate(lam)
            if t >= trace.duration_s:
                break
            if rng.random() * lam <= rate_at(trace, t):
                out.append(t)
    elif trace.process == "pareto":
        alpha = trace.pareto_alpha
        if alpha <= 1.0:
            raise ValueError("pareto_alpha must be > 1 (finite mean gap)")
        mean = alpha / (alpha - 1.0)
        while True:
            local = rate_at(trace, t)
            gap = (rng.paretovariate(alpha) / mean) / max(local, 1e-9)
            t += gap
            if t >= trace.duration_s:
                break
            out.append(t)
    else:
        raise ValueError(f"unknown arrival process {trace.process!r}")
    return out


def _pick_tenant(tenants: Sequence[Tuple[str, float]],
                 rng: random.Random) -> str:
    total = sum(w for _, w in tenants)
    x = rng.random() * total
    acc = 0.0
    for name, w in tenants:
        acc += w
        if x < acc:
            return name
    return tenants[-1][0]


def generate(trace: Trace, seed: int) -> List[Arrival]:
    """The whole point: ``(seed, trace) -> schedule``, byte-identical on
    replay. Arrivals come back time-sorted with ``index`` equal to their
    position; a multi-turn trace interleaves sessions' later turns into
    the same timeline (heap merge — the event queue, not per-user
    threads)."""
    rng = random.Random(seed)
    firsts = _arrival_times(trace, rng)
    heap: List[Tuple[float, int, int, str, str]] = []
    for i, t in enumerate(firsts):
        tenant = _pick_tenant(trace.tenants, rng)
        if trace.session_turns > 1:
            sess = f"s{i:05d}"
            turns = rng.randint(1, trace.session_turns)
            for k in range(turns):
                heapq.heappush(
                    heap, (t + k * trace.think_s, i, k, tenant, sess))
        else:
            heapq.heappush(heap, (t, i, 0, tenant, ""))
    out: List[Arrival] = []
    while heap:
        t, _, turn, tenant, sess = heapq.heappop(heap)
        out.append(Arrival(t=t, index=len(out), tenant=tenant,
                           session=sess, turn=turn))
    return out


def schedule_fingerprint(schedule: Sequence[Arrival]) -> str:
    """sha256 over the canonical serialization — two schedules with the
    same fingerprint ARE the same schedule (the replay contract the
    bench asserts)."""
    h = hashlib.sha256()
    for a in schedule:
        h.update(f"{a.t:.9f}|{a.index}|{a.tenant}|{a.session}|{a.turn}\n"
                 .encode())
    return h.hexdigest()


def bucket_counts(schedule: Sequence[Arrival], bucket_s: float,
                  min_buckets: int = 0) -> List[int]:
    """Arrivals per ``bucket_s`` window of intended time — the per-round
    offered load the virtual-round drivers consume."""
    if bucket_s <= 0:
        raise ValueError("bucket_s must be positive")
    n = min_buckets
    if schedule:
        n = max(n, int(schedule[-1].t / bucket_s) + 1)
    counts = [0] * n
    for a in schedule:
        counts[int(a.t / bucket_s)] += 1
    return counts


# -- payload populations -----------------------------------------------------

def feature_rows(n: int, rows: int, dim: int, seed: int) -> List[Any]:
    """The scoring lanes' request payloads: ``n`` float32 arrays of shape
    ``(rows, dim)`` from one seeded generator — the single construction
    every chaos/bench scoring stream shares."""
    import numpy as np
    xrng = np.random.default_rng(seed)
    return [xrng.normal(0, 1, (rows, dim)).astype(np.float32)
            for _ in range(n)]


def zipf_ids(n: int, *, rows: int, seed: int,
             zipf_s: float = 1.1) -> Any:
    """``n`` embedding-row ids in ``[1, rows)`` with Zipf-weighted
    popularity (id 1 hottest) — the skew real recommender traffic has,
    where a few hot users/items dominate every lookup batch. Returns an
    int32 numpy array; id 0 (the pad row, ``embed.tables.PAD_ID``) is
    never drawn. Same ``(seed, rows, zipf_s)`` -> the same id stream."""
    import numpy as np
    if rows < 2:
        raise ValueError("rows must be >= 2 (id 0 is the reserved pad)")
    ranks = np.arange(1, rows, dtype=np.float64)
    w = 1.0 / ranks ** zipf_s
    rng = np.random.default_rng(seed)
    ids = rng.choice(np.arange(1, rows, dtype=np.int64), size=n,
                     p=w / w.sum())
    return ids.astype(np.int32)


def recommender_rows(n: int, *, dense: int,
                     tables: Sequence[Tuple[int, int]], seed: int,
                     zipf_s: float = 1.1) -> Any:
    """``n`` packed recommender scoring rows — float32
    ``[dense features | slots ids per table]``, the ``embed.model`` wire
    format — with Zipf-hot ids per sparse feature. ``tables`` is
    ``((rows, slots), ...)`` in slot order; ids are exact in float32 up
    to 2^24. One seeded construction shared by the bench serve phase and
    the chaos recommender scenario (lint Rule 16)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    cols = [rng.normal(0.0, 1.0, (n, dense)).astype(np.float32)]
    for j, (rows, slots) in enumerate(tables):
        ids = zipf_ids(n * slots, rows=rows, seed=seed + 1000 * (j + 1),
                       zipf_s=zipf_s)
        cols.append(ids.reshape(n, slots).astype(np.float32))
    return np.concatenate(cols, axis=1)


def token_prompts(n: int, rng: random.Random, *, vocab: int = 200,
                  min_len: int = 3, max_len: int = 8) -> List[List[int]]:
    """Independent token prompts for the decode lanes (uniform vocab,
    uniform length). Takes the caller's ``random.Random`` so a scenario's
    downstream draws stay on its seeded stream."""
    return [[rng.randrange(1, vocab) for _ in range(rng.randint(min_len,
                                                                max_len))]
            for _ in range(n)]


class PromptPopulation:
    """Zipf-weighted shared-prefix prompt population.

    ``prefixes`` system prompts of ``prefix_tokens`` tokens each;
    :meth:`sample` picks one by Zipf rank (rank 0 hottest) and appends a
    fresh uniform tail — the reuse pattern that makes prefix caches
    earn their keep."""

    def __init__(self, rng: random.Random, *, prefixes: int = 1,
                 prefix_tokens: int = 8, vocab: int = 200,
                 zipf_s: float = 1.1):
        self.vocab = vocab
        self._rng = rng
        self._prefixes = [[rng.randrange(1, vocab)
                           for _ in range(prefix_tokens)]
                          for _ in range(prefixes)]
        weights = [1.0 / (k + 1) ** zipf_s for k in range(prefixes)]
        total = sum(weights)
        self._cum = []
        acc = 0.0
        for w in weights:
            acc += w / total
            self._cum.append(acc)

    def prefix(self, rank: int) -> List[int]:
        return list(self._prefixes[rank])

    def sample(self, *, tail_tokens: int = 2) -> List[int]:
        x = self._rng.random()
        rank = next((i for i, c in enumerate(self._cum) if x < c),
                    len(self._cum) - 1)
        return self.prefix(rank) + [self._rng.randrange(1, self.vocab)
                                    for _ in range(tail_tokens)]


# -- virtual-time drivers ----------------------------------------------------

class EventQueue:
    """Deterministic virtual-time event loop: push ``(t, fn)``, pop in
    time order (FIFO among equal times), the clock jumping event to
    event. This is what lets a million virtual users cost a million heap
    entries instead of a million threads."""

    def __init__(self, t0: float = 0.0):
        self.now = float(t0)
        self._seq = 0
        self._heap: List[Tuple[float, int, Callable[[float], Any]]] = []

    def push(self, t: float, fn: Callable[[float], Any]) -> None:
        heapq.heappush(self._heap, (max(float(t), self.now), self._seq, fn))
        self._seq += 1

    def __len__(self) -> int:
        return len(self._heap)

    def run(self, until: Optional[float] = None) -> int:
        """Dispatch events in time order; returns how many ran."""
        ran = 0
        while self._heap:
            if until is not None and self._heap[0][0] > until:
                break
            t, _, fn = heapq.heappop(self._heap)
            self.now = t
            fn(t)
            ran += 1
        return ran


def _skip_stalls(t: float, stalls: Sequence[Tuple[float, float]]) -> float:
    for s0, s1 in stalls:
        if s0 <= t < s1:
            t = s1
    return t


def simulate_open_loop(schedule: Sequence[Arrival], service_s: float, *,
                       stalls: Sequence[Tuple[float, float]] = (),
                       ) -> List[Dict[str, float]]:
    """Reference single-FIFO-server simulation, OPEN loop: every arrival
    joins the queue at its intended time regardless of what the server
    is doing; ``stalls`` are windows where the server makes no progress.
    Latency is measured from the INTENDED arrival — the honest number.
    """
    q = EventQueue()
    free = {"t": 0.0}
    out: List[Dict[str, float]] = []

    def _arrive(a: Arrival):
        def run(_t: float) -> None:
            start = _skip_stalls(max(a.t, free["t"]), stalls)
            done = start + service_s
            free["t"] = done
            out.append({"trace_id": a.trace_id, "arrival_t": a.t,
                        "start_t": start, "done_t": done,
                        "latency_s": done - a.t})
        return run

    for a in schedule:
        q.push(a.t, _arrive(a))
    q.run()
    return out


def simulate_closed_loop(schedule: Sequence[Arrival], service_s: float, *,
                         stalls: Sequence[Tuple[float, float]] = (),
                         clients: int = 1) -> List[Dict[str, float]]:
    """The SAME schedule through ``clients`` closed-loop clients: a
    client sends its next request only after its previous reply, and
    latency is measured from the throttled SEND time. This is the
    coordinated-omission-blind measurement the old drivers made — kept
    as a reference so tests can show exactly what it hides."""
    free = {"t": 0.0}
    client_free = [0.0] * max(1, clients)
    out: List[Dict[str, float]] = []
    for i, a in enumerate(schedule):
        c = i % len(client_free)
        send = max(a.t, client_free[c])        # the omission: send waits
        start = _skip_stalls(max(send, free["t"]), stalls)
        done = start + service_s
        free["t"] = done
        client_free[c] = done
        out.append({"trace_id": a.trace_id, "arrival_t": a.t,
                    "send_t": send, "done_t": done,
                    "latency_s": done - send})
    return out


def run_open_loop(schedule: Sequence[Arrival],
                  submit: Callable[[Arrival], Any], *,
                  clock: Optional[Callable[[], float]] = None,
                  sleep: Optional[Callable[[float], None]] = None) -> float:
    """Walk a schedule in WALL time: sleep until each intended arrival,
    then call ``submit(arrival)`` — never gated on replies, so a stalled
    system keeps receiving (and keeps being measured). ``submit`` should
    be non-blocking (e.g. ``Server.submit_async``); a blocking transport
    degrades to wrk2-style pacing, which stays honest as long as latency
    is measured from ``t0 + arrival.t``. Returns ``t0``."""
    import time as _time
    clock = clock or _time.perf_counter
    sleep = sleep or _time.sleep
    t0 = clock()
    for a in schedule:
        delay = (t0 + a.t) - clock()
        if delay > 0:
            sleep(delay)
        submit(a)
    return t0
