"""Deterministic fault injection: named sites + an Nth-hit trigger plan.

The reference's failure story was an exit-code check on the CNTK subprocess
(SURVEY.md §5) — nothing reproduced a failure. Here failure REPRODUCTION is
the primitive: production code threads zero-cost ``fault_site("name")``
hooks through its crash-relevant points (downloader fetches, checkpoint
save/restore, reader I/O, the train step), and a test installs a
:class:`FaultPlan` that triggers an exact action on the exact Nth hit of a
site. Crash-mid-download, crash-mid-checkpoint-write, and transient network
errors replay bit-for-bit — no monkeypatching, no sleeps, no flakes.

Instrumented sites (grep for ``fault_site(`` to confirm the live list):

- ``downloader.manifest`` / ``downloader.fetch`` — before each urlopen
- ``downloader.payload``  — carries the fetched bytes (truncatable)
- ``checkpoint.save``     — before the orbax save dispatch
- ``checkpoint.save.commit`` — after dispatch, before the commit wait
- ``checkpoint.restore``  — before the orbax restore
- ``readers.read``        — carries each binary file/zip-entry payload
- ``data.list``           — before the input pipeline lists/shards files
- ``data.shuffle``        — before each shuffle window permutes
- ``data.decode``         — before each record enters the decode pool
- ``trainer.train_step``  — before each sharded train step
- ``serve.enqueue``       — before a request enters the admission queue
- ``serve.batch``         — after a micro-batch is dequeued, pre-padding
- ``serve.score``         — before the batch hits the compiled program

Usage::

    with FaultPlan(FaultSpec("checkpoint.save", on_hit=3)):
        run_training()          # 3rd checkpoint save raises InjectedFault

With no plan installed, ``fault_site`` is a single global read — cheap
enough for the train-step hot path.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple, Type, Union

from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.faults")
_LOCK = threading.Lock()
_ACTIVE: Optional["FaultPlan"] = None


class InjectedFault(RuntimeError):
    """The default exception a triggered ``raise`` fault throws."""


def fault_site(name: str, payload: Any = None) -> Any:
    """Mark a named fault-injection point.

    Returns ``payload`` unchanged (possibly transformed by a triggered
    ``truncate`` fault) or raises per the active :class:`FaultPlan`. A
    no-op returning ``payload`` when no plan is installed.
    """
    plan = _ACTIVE
    if plan is None:
        return payload
    return plan.hit(name, payload)


def active_plan() -> Optional["FaultPlan"]:
    return _ACTIVE


@dataclass
class FaultSpec:
    """One trigger rule: fire ``action`` on hits ``on_hit`` through
    ``on_hit + times - 1`` (1-based) of ``site``.

    Actions: ``"raise"`` throws ``exc`` (an instance, an exception class,
    or None for :class:`InjectedFault`); ``"truncate"`` keeps the first
    ``fraction`` of the site's payload (simulating a cut connection or
    partial write); ``"delay"`` sleeps ``delay`` seconds (simulating a
    stalled link, for timeout paths).
    """

    site: str
    on_hit: int = 1
    times: int = 1
    action: str = "raise"
    exc: Union[BaseException, Type[BaseException], None] = None
    fraction: float = 0.5
    delay: float = 0.0

    def triggers(self, n: int) -> bool:
        return self.on_hit <= n < self.on_hit + self.times

    def make_exc(self, site: str, n: int) -> BaseException:
        if self.exc is None:
            return InjectedFault(f"injected fault at {site} (hit {n})")
        if isinstance(self.exc, type):
            return self.exc(f"injected fault at {site} (hit {n})")
        return self.exc


class FaultPlan:
    """Process-wide deterministic fault schedule (context manager).

    Counts hits per site under a lock (deterministic for any serial code
    path) and applies every matching :class:`FaultSpec` in order. Plans do
    not nest — a second concurrent plan would make hit counts ambiguous, so
    entering while one is active raises. ``triggered`` records each fired
    ``(site, hit, action)`` for test assertions; ``sleep`` is injectable so
    delay faults don't slow the suite.
    """

    def __init__(self, *specs: FaultSpec,
                 sleep: Callable[[float], None] = time.sleep):
        self.specs: List[FaultSpec] = list(specs)
        self.hits: Dict[str, int] = {}
        self.triggered: List[Tuple[str, int, str]] = []
        self._sleep = sleep

    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        with _LOCK:
            if _ACTIVE is not None:
                raise RuntimeError(
                    "a FaultPlan is already active; plans do not nest")
            _ACTIVE = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        global _ACTIVE
        with _LOCK:
            _ACTIVE = None
        return False

    def hit(self, name: str, payload: Any = None) -> Any:
        with _LOCK:
            n = self.hits.get(name, 0) + 1
            self.hits[name] = n
        for spec in self.specs:
            if spec.site != name or not spec.triggers(n):
                continue
            self.triggered.append((name, n, spec.action))
            _LOG.info("fault %r fired at %s (hit %d)", spec.action, name, n)
            # a triggered fault is rare by construction: safe to count/emit
            from mmlspark_tpu.observability import (events,
                                                    metrics as obsmetrics)
            obsmetrics.counter("reliability.fault_hits").inc()
            if events.events_enabled():
                events.emit("event", "fault.hit", site=name, hit=n,
                            action=spec.action)
            if spec.action == "delay":
                self._sleep(spec.delay)
            elif spec.action == "truncate":
                if payload is None:
                    raise InjectedFault(
                        f"truncate fault at payload-less site {name}")
                payload = payload[:int(len(payload) * spec.fraction)]
            elif spec.action == "raise":
                raise spec.make_exc(name, n)
            else:
                raise ValueError(f"unknown fault action {spec.action!r}")
        return payload
