"""Seeded chaos harness: deterministic fault schedules + a verdict.

The tentpole scenario (``mmlspark-tpu chaos --seed N``):

1. **reference** — an uninterrupted :class:`ResilientTrainLoop` run on a
   tiny deterministic problem (params are a pure function of the seed);
2. **chaos** — the same run under a :class:`FaultPlan` *generated from the
   seed*: at least one mid-run kill (``trainer.train_step`` or
   ``checkpoint.save``), maybe a poisoned restore (exercising the
   quarantine-and-fall-back path), maybe tiny injected delays. Every
   ``InjectedFault`` that escapes the loop is "the process died"; the
   harness restarts the loop the way an operator (or a supervisor) would
   rerun the program, until the run completes;
3. **serve** — an HTTP server over a registry model takes traffic while
   seeded ``serve.*`` faults fire; ``/healthz`` is polled throughout and
   must answer every time, then the server drains and a second ``close()``
   proves idempotence.

Invariants asserted (the verdict JSON records each one):

- ``params_bit_identical``   — chaos-run final params == reference params,
  with the trainer's device-resident metrics ring active and its flush
  interval deliberately misaligned with the checkpoint interval (a flush
  boundary that changed the stream would break this bit-for-bit check);
- ``final_checkpoint_loads`` — a FRESH checkpointer restores the last step
  and it matches the in-memory state (no corrupt checkpoint survived);
- ``server_stays_live``      — every ``/healthz`` poll answered 200;
- ``no_unhandled_exceptions``— nothing escaped outside the injected
  fault channel.

Everything derives from ``seed`` — two runs with the same seed produce the
same fault schedule, the same kill points, and the same verdict, which is
what makes a red chaos run *debuggable* instead of an anecdote.
"""
from __future__ import annotations

import itertools
import json
import os
import random
from typing import Any, Callable, Dict, List, Optional

from mmlspark_tpu.reliability.faults import (FaultPlan, FaultSpec,
                                             InjectedFault)
from mmlspark_tpu.testing import loadgen
from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.chaos")

VERDICT_FILE = "chaos_verdict.json"

# Registered scenarios (name -> one-line description). The CLI dispatches
# through this registry; an unknown --scenario prints it and exits 2
# instead of tracebacking.
SCENARIOS: Dict[str, str] = {
    "train": "kill+resume training to bit-identical params, then serve "
             "under injected faults",
    "fleet": "kill one in-process replica of an N-wide fleet under fire; "
             "zero dropped requests, scores bit-identical",
    "decode": "kill a replica mid-generation; every sequence completes "
              "via failover-restart with bit-identical tokens",
    "host": "SIGKILL a real worker PROCESS under fire; supervisor "
            "warm-restarts it from the shared compile cache, and a "
            "crash-looper ends breaker-open, not flapping",
    "fleet_sharded": "the fleet scenario with every replica's model "
                     "2-D mesh-sharded (data x tensor); same zero-drop "
                     "+ bit-identical invariants through the kill",
    "decode_sharded": "the decode scenario with a mesh-sharded model + "
                      "head-sharded KV arena; failover token-identical "
                      "and the HBM ledger reconciles PER SHARD",
    "autopilot": "seeded load spike + replica kill, twice: a static fleet "
                 "vs the same fleet under the autopilot; the autopilot "
                 "must shed strictly less, recover weights/replicas, and "
                 "never flap (asserted from autopilot.* events alone)",
    "elastic": "SIGKILL a worker mid autopilot-driven PROCESS scale-up; "
               "zero failed requests, the half-spawned slot completes or "
               "is reaped (never a zombie), the new worker comes up warm "
               "with zero compiles, and both pilots' event logs replay "
               "byte-identical",
    "recommender": "kill a replica mid-scoring with row-sharded embedding "
                   "tables resident; zero failed requests, scores "
                   "bit-identical to an unsharded single server, and the "
                   "HBM ledger's kind=\"table\" lines reconcile to zero "
                   "on close",
    "fleetprefix": "kill the replica holding the hottest advertised "
                   "prefix chains mid-stream; zero failed requests, "
                   "survivors absorb the sessions, tokens bit-identical "
                   "to a single server, and the prefix hit rate recovers "
                   "with zero new compiles",
    "reshard": "SIGKILL a replica MID-RESHARD while the fleet moves to a "
               "new mesh placement under fire; zero failed requests, "
               "scores bit-identical to an untouched reference on both "
               "placements, the survivors finish the reshard, and the "
               "HBM ledger reconciles to zero on close (no orphan "
               "params/kv bytes from the dead replica or the old "
               "placement)",
}

# the 2-D topology the *_sharded scenarios run on: tensor=2 model axis,
# data absorbs the rest, so the SAME string fits a 4-chip host (2x2) and
# the CI's forced-8-CPU-device emulation (4x2) — a mesh must multiply to
# the device count exactly
SHARDED_MESH = "data=-1,tensor=2"

# Sites the TRAIN phase draws its schedule from. `trainer.train_step` /
# `checkpoint.save` raises are kills (the loop restarts); a
# `checkpoint.restore` raise poisons the newest checkpoint ONCE, forcing
# the quarantine-and-fall-back path on resume; delays exercise timeout
# plumbing without changing any numerics.
TRAIN_KILL_SITES = ("trainer.train_step", "checkpoint.save")
TRAIN_DELAY_SITES = ("checkpoint.save.commit", "checkpoint.restore")
# SERVE-phase fault sites (see faults.py's site inventory).
SERVE_FAULT_SITES = ("serve.enqueue", "serve.batch", "serve.score")

_DIM = 8


class ChaosError(RuntimeError):
    """The scenario itself failed to make progress (distinct from an
    injected fault, which is the scenario working as designed)."""


# -- plan generation ---------------------------------------------------------

def generate_train_plan(seed: int, total_steps: int,
                        sleep: Optional[Callable[[float], None]] = None
                        ) -> FaultPlan:
    """A randomized-but-deterministic fault schedule for the train phase.

    Always contains at least one kill so the resume path is exercised;
    hit counts accumulate across restarts (the plan stays installed), so
    later kills land in the *resumed* run.
    """
    rng = random.Random(seed)
    specs: List[FaultSpec] = []
    # guaranteed kill, mid-run: never on hit 1 (a run that dies before any
    # checkpoint proves nothing about resume)
    site = rng.choice(TRAIN_KILL_SITES)
    if site == "trainer.train_step":
        specs.append(FaultSpec(site, on_hit=rng.randint(2, total_steps)))
    else:
        specs.append(FaultSpec(site, on_hit=rng.randint(1, 2)))
    # optional second kill, landing during the resumed run's replay
    if rng.random() < 0.5:
        specs.append(FaultSpec(
            "trainer.train_step",
            on_hit=total_steps + rng.randint(1, total_steps)))
    # optional poisoned restore: the FIRST restore after the kill fails,
    # forcing quarantine of the newest step and fall-back to the previous
    if rng.random() < 0.5:
        specs.append(FaultSpec("checkpoint.restore", on_hit=1))
    # optional tiny delays (timeout plumbing, not numerics)
    for delay_site in TRAIN_DELAY_SITES:
        if rng.random() < 0.5:
            specs.append(FaultSpec(delay_site, on_hit=rng.randint(1, 3),
                                   action="delay", delay=0.001))
    kwargs = {"sleep": sleep} if sleep is not None else {}
    return FaultPlan(*specs, **kwargs)


def generate_serve_plan(seed: int, requests: int) -> FaultPlan:
    """Seeded faults for the serve phase: a couple of scoring/admission
    failures, few enough that the per-model circuit breaker (default
    threshold 5 consecutive) never opens — the invariant under test is
    *liveness*, not breaker behavior."""
    rng = random.Random(seed ^ 0x5EEDED)
    specs = [FaultSpec("serve.score", on_hit=rng.randint(2, max(2, requests // 2)))]
    if rng.random() < 0.5:
        specs.append(FaultSpec("serve.enqueue",
                               on_hit=rng.randint(2, max(2, requests - 1))))
    return FaultPlan(*specs)


# -- deterministic tiny workload --------------------------------------------

def _make_trainer():
    import optax
    from mmlspark_tpu.parallel.mesh import MeshSpec, make_mesh
    from mmlspark_tpu.parallel.trainer import DistributedTrainer
    mesh = make_mesh(MeshSpec(data=-1))

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return ((pred - batch["y"]) ** 2).mean()

    return DistributedTrainer(loss_fn, optax.adam(1e-2), mesh=mesh)


def _init_params():
    import jax.numpy as jnp
    return {"w": jnp.ones((_DIM, _DIM), jnp.float32) * 0.1,
            "b": jnp.zeros((_DIM,), jnp.float32)}


def _batch_fn(seed: int) -> Callable[[int], Dict[str, Any]]:
    import numpy as np

    def batch(step: int) -> Dict[str, Any]:
        x = loadgen.feature_rows(1, 16, _DIM, (seed << 20) + step)[0]
        return {"x": x, "y": (x * 0.5).astype(np.float32)}

    return batch


def _bit_identical(a: Any, b: Any) -> bool:
    import jax
    import numpy as np
    fa, ta = jax.tree_util.tree_flatten(jax.device_get(a))  # lint: allow-sync
    fb, tb = jax.tree_util.tree_flatten(jax.device_get(b))  # lint: allow-sync
    if ta != tb:
        return False
    return all(np.array_equal(x, y) for x, y in zip(fa, fb))


# -- scenario phases ---------------------------------------------------------

def _run_loop_to_completion(ckdir: str, batch_fn, total_steps: int,
                            save_every: int, max_restarts: int) -> Any:
    """Run a ResilientTrainLoop to completion, restarting on every escaped
    InjectedFault exactly the way a supervisor reruns a killed program.
    The active FaultPlan's hit counters persist across restarts, so the
    schedule is deterministic end-to-end."""
    from mmlspark_tpu.parallel.checkpoint import TrainCheckpointer
    from mmlspark_tpu.reliability.resilient import ResilientTrainLoop
    restarts = 0
    while True:
        loop = ResilientTrainLoop(_make_trainer(), TrainCheckpointer(ckdir),
                                  _init_params, save_every=save_every)
        try:
            state = loop.run(batch_fn, total_steps)
            loop.ckpt.close()
            return state, restarts
        except InjectedFault as e:
            restarts += 1
            _LOG.info("chaos kill #%d (%s); restarting the loop", restarts, e)
            try:
                loop.ckpt.close()
            except Exception as close_err:
                # a kill mid-save can leave the manager wedged; a fresh
                # checkpointer supersedes it on the next restart
                _LOG.debug("post-kill checkpointer close failed: %s",
                           close_err)
            if restarts > max_restarts:
                raise ChaosError(
                    f"loop did not complete within {max_restarts} restarts "
                    "(fault schedule never drains?)") from e


def _final_checkpoint_loads(ckdir: str, expect_state: Any,
                            total_steps: int) -> bool:
    """A FRESH checkpointer must list the final step and restore it to
    exactly the in-memory final state — proving no corrupt checkpoint
    survived the chaos run as the newest step."""
    from mmlspark_tpu.parallel.checkpoint import TrainCheckpointer
    ckpt = TrainCheckpointer(ckdir)
    try:
        if ckpt.latest_step() != total_steps:
            _LOG.warning("final checkpoint check: latest_step=%s != %d",
                         ckpt.latest_step(), total_steps)
            return False
        restored = ckpt.restore(_make_trainer(), _init_params)
        return _bit_identical(restored, expect_state)
    finally:
        ckpt.close()


def _quarantined(ckdir: str) -> List[str]:
    try:
        return sorted(n for n in os.listdir(ckdir)
                      if n.startswith("corrupt-"))
    except OSError:
        return []


def _serve_phase(seed: int, requests: int,
                 errors: List[str]) -> Dict[str, Any]:
    """Serve traffic under seeded faults; returns phase facts including
    whether every /healthz poll answered."""
    import threading
    import urllib.request

    import numpy as np

    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.serve.http import serve_http
    from mmlspark_tpu.serve.server import ServeError, Server

    model = JaxModel(inputCol="x", outputCol="y", miniBatchSize=8)
    model.set_model("mlp_tabular", input_dim=_DIM, hidden=[16],
                    num_classes=3, seed=seed & 0xFFFF)
    server = Server({"chaos": model}, max_batch=4, queue_depth=32)
    httpd, addr = serve_http(server, port=0)
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                                   name="mmlspark-tpu-chaos-http")
    http_thread.start()

    polls_ok = 0
    polls_bad = 0

    def poll(allow=("ok", "draining")) -> None:
        nonlocal polls_ok, polls_bad
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/healthz", timeout=5) as resp:
                body = json.loads(resp.read().decode())
                if resp.status == 200 and body.get("status") in allow:
                    polls_ok += 1
                else:
                    polls_bad += 1
        except Exception as e:
            polls_bad += 1
            errors.append(f"healthz poll failed: {type(e).__name__}: {e}")

    stream = loadgen.feature_rows(requests, 3, _DIM, seed)
    served = 0
    injected = 0
    plan = generate_serve_plan(seed, requests)
    with plan:
        for i in range(requests):
            x = stream[i]
            try:
                y = server.submit("chaos", x, timeout=30)
                if np.asarray(y).shape[0] == 3:
                    served += 1
                else:
                    errors.append(f"request {i}: wrong result shape")
            except (InjectedFault, ServeError):
                injected += 1  # seeded fault surfacing is the design
            except Exception as e:
                errors.append(
                    f"request {i}: unexpected {type(e).__name__}: {e}")
            if i % 3 == 0:
                poll()
    poll()
    server.drain(reason="chaos scenario complete")
    # the endpoint must still ANSWER after the drain; with the
    # liveness/readiness split it now truthfully reports "closed"
    poll(allow=("ok", "draining", "closed"))
    server.close()  # idempotence: second close is a no-op
    httpd.shutdown()
    httpd.server_close()
    if served == 0:
        errors.append("serve phase completed zero requests")
    return {"requests": requests, "served": served,
            "injected_failures": injected, "faults": plan.triggered,
            "healthz_ok": polls_ok, "healthz_bad": polls_bad}


# -- fleet scenario ----------------------------------------------------------

def run_fleet_scenario(seed: int, outdir: str, replicas: int = 3,
                       requests: int = 24,
                       mesh: str = "") -> Dict[str, Any]:
    """Kill a replica under fire; the fleet must not drop a request.

    1. **reference** — the full request stream scored on a single
       :class:`~mmlspark_tpu.serve.server.Server` over the same model:
       the numerics ground truth.
    2. **fleet** — the same stream through a ``replicas``-wide
       :class:`~mmlspark_tpu.serve.fleet.Fleet`; at a seeded point
       mid-stream one seeded replica is killed without drain (in-flight
       work fails retryably, health goes dead). The client wraps
       ``router.submit`` in a :class:`RetryPolicy`, exactly as a real
       client rides out a consolidated shed.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``zero_failed_requests``  — every request eventually scored; the
      only acceptable non-successes are sheds the retry layer absorbed;
    - ``scores_bit_identical`` — fleet results == single-server results,
      row for row, through the kill and the failover;
    - ``failover_observed``    — the kill actually forced at least one
      failover (otherwise the scenario proved nothing);
    - ``replicas_stay_probed`` — every health probe round answered for
      every replica (dead replicas ANSWER dead; probing never wedges).

    The scenario also runs the observability stack against itself: a
    :class:`~mmlspark_tpu.observability.aggregate.FleetScraper` +
    :class:`~mmlspark_tpu.observability.slo.SloEngine` pair on a virtual
    clock (30 s per request round, so burn windows slide inside a
    seconds-long run) watches the whole incident, and a **recovery
    phase** keeps healthy traffic flowing until the incident leaves both
    windows. Four more invariants come from that aggregated view alone:

    - ``readiness_flip_observed`` — the kill shows up as a ready-count
      drop in the scraped fleet view (and never before the kill);
    - ``slo_burn_on_kill``        — availability burn crosses the fast
      threshold after the kill (failovers count as budget burn even
      though the retry layer hid them from the client);
    - ``slo_clears_after_recovery`` — burn decays back below threshold
      once healthy traffic has aged the incident out of the windows;
    - ``no_false_breach``         — ``slo.breach`` never fires before
      the kill and is clear again at the end.

    The verdict's ``schedule`` (kill point, killed replica, per-request
    serving replica, failover count) is a pure function of ``seed`` —
    two same-seed runs must produce byte-identical schedules, which is
    what the tier-1 smoke test asserts.
    """
    import numpy as np

    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.observability.aggregate import FleetScraper
    from mmlspark_tpu.observability.slo import SloEngine
    from mmlspark_tpu.reliability.retry import RetryPolicy
    from mmlspark_tpu.serve.fleet import Fleet
    from mmlspark_tpu.serve.server import Server

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {
        "seed": seed, "scenario": "fleet_sharded" if mesh else "fleet",
        "replicas": replicas, "requests": requests, "mesh": mesh}

    rng = random.Random(seed ^ 0xF1EE7)
    # the kill lands right after a probe round: the next probe is then a
    # full probe-interval of submits away, and a WRR walk that long over
    # `replicas` candidates is GUARANTEED to route onto the dead replica
    # first — failover discovers every kill, for every seed
    probe_every = max(4, replicas + 1)
    kill_at = -(-rng.randint(requests // 3, (2 * requests) // 3)
                // probe_every) * probe_every
    kill_at = min(kill_at, max(requests - probe_every, 0))
    kill_idx = rng.randrange(replicas)

    # sharded variant: the SAME scenario, but every replica's copy of the
    # model scores over a 2-D (data x tensor) mesh — the kill and the
    # failover must not care that each chip holds only a param shard
    model = JaxModel(inputCol="x", outputCol="y", miniBatchSize=8,
                     **({"meshSpec": mesh} if mesh else {}))
    model.set_model("mlp_tabular", input_dim=_DIM, hidden=[16],
                    num_classes=3, seed=seed & 0xFFFF)
    stream = loadgen.feature_rows(requests, 2, _DIM, seed)

    # phase 1: single-server reference (same model object -> same programs)
    ref_server = Server({"chaos": model}, max_batch=4, queue_depth=32)
    try:
        reference = [np.asarray(ref_server.submit("chaos", x, timeout=30))
                     for x in stream]
    finally:
        ref_server.close()

    # phase 2: the same stream through the fleet, with a seeded mid-stream
    # kill. Sequential blocking submits keep the router's WRR walk (and so
    # the whole schedule) deterministic.
    fleet = Fleet({"chaos": model}, replicas=replicas,
                  server_kwargs={"max_batch": 4, "queue_depth": 32})
    route_log: List[str] = []
    fleet.router.route_log = route_log
    client_retry = RetryPolicy(max_attempts=4, base_delay=0.0, jitter=0.0,
                               name="chaos.fleet.client", seed=seed)
    results: List[Optional[Any]] = []
    failed = 0
    probe_rounds: List[Dict[str, str]] = []

    # the SLO watcher: one virtual-clock scrape per request round (30 s of
    # virtual time each), so the 5-minute fast window is 10 rounds wide and
    # the whole burn/recover cycle fits inside a seconds-long scenario
    vclock = {"t": 1000.0}
    scraper = FleetScraper(fleet, clock=lambda: vclock["t"])
    engine = SloEngine(clock=lambda: vclock["t"],
                       fast_window_s=300.0, slow_window_s=900.0)
    slo_trace: List[Dict[str, Any]] = []

    def observe_fleet() -> None:
        snap = scraper.scrape()
        status = engine.observe(scraper.slo_sample(snap))
        slo_trace.append({
            "t": vclock["t"],
            "ready": sum(1 for r in snap["replicas"].values()
                         if r.get("ready")),
            "burning": any(s["burning"] for s in status),
            "breaching": any(s["breaching"] for s in status),
        })
        vclock["t"] += 30.0

    try:
        for i, x in enumerate(stream):
            # probe BEFORE this round's kill: the kill must be discovered
            # by failover (a live request landing on the dead replica),
            # not pre-empted by a health probe in the same iteration —
            # with the probe leading, the dead replica stays in rotation
            # for the next few submits and the WRR walk is guaranteed to
            # reach it before the next probe round.
            if i % probe_every == 0:
                probe_rounds.append(fleet.router.probe())
            if i == kill_at:
                fleet.kill(kill_idx)  # lint: allow-actuate
            try:
                results.append(np.asarray(
                    client_retry.call(fleet.submit, "chaos", x)))
            except Exception as e:
                failed += 1
                results.append(None)
                errors.append(
                    f"request {i}: {type(e).__name__}: {e}")
            observe_fleet()
        probe_rounds.append(fleet.router.probe())
        # phase 3: recovery — healthy traffic while the virtual clock ages
        # the incident out of both burn windows (10 rounds x 120 s > the
        # 900 s slow window); the engine must come back clean
        for x in itertools.islice(itertools.cycle(stream), 10):
            fleet.router.probe()
            client_retry.call(fleet.submit, "chaos", x)
            vclock["t"] += 90.0  # on top of observe_fleet's own 30 s
            observe_fleet()
        stats = fleet.stats()
    finally:
        fleet.close()

    identical = all(
        r is not None and np.array_equal(r, ref)
        for r, ref in zip(results, reference))
    probed_ok = bool(probe_rounds) and all(
        len(round_) == replicas for round_ in probe_rounds)
    failovers = int(stats["failovers"])
    shed = sum(int(s.get("shed", 0))
               for s in stats["servers"].values())

    verdict["schedule"] = {
        "kill_at": kill_at, "kill_replica": f"r{kill_idx}",
        "route_log": route_log, "failovers": failovers,
    }
    verdict["fleet"] = {
        "served": sum(1 for r in results if r is not None),
        "failed": failed, "shed": shed,
        "probe_rounds": len(probe_rounds),
        "final_states": probe_rounds[-1] if probe_rounds else {},
    }

    # the incident as the aggregated view saw it: trace index == request
    # index through the stream (one scrape per round), then 10 recovery
    # rounds. The kill lands at trace index ``kill_at`` (kill precedes
    # that round's submit, so its scrape already sees the dead replica).
    pre_kill = slo_trace[:kill_at]
    post_kill = slo_trace[kill_at:]
    tail = slo_trace[-3:]
    burn_observed = any(e["burning"] for e in post_kill)
    breach_observed = any(e["breaching"] for e in post_kill)
    slo_clean_after = all(not e["burning"] and not e["breaching"]
                          for e in tail)
    no_false_breach = (all(not e["breaching"] for e in pre_kill)
                       and slo_clean_after)
    ready_flip = (all(e["ready"] == replicas for e in pre_kill)
                  and any(e["ready"] < replicas for e in post_kill))
    verdict["slo"] = {
        "kill_trace_index": kill_at,
        "burn_observed": burn_observed,
        "breach_observed": breach_observed,
        "clean_at_end": slo_clean_after,
        "trace": slo_trace,
    }
    invariants = {
        "zero_failed_requests": failed == 0,
        "scores_bit_identical": identical,
        "failover_observed": failovers >= 1,
        "replicas_stay_probed": probed_ok,
        "no_unhandled_exceptions": not errors,
        "readiness_flip_observed": ready_flip,
        "slo_burn_on_kill": burn_observed,
        "slo_clears_after_recovery": slo_clean_after,
        "no_false_breach": no_false_breach,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos fleet verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.fleet.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


def run_recommender_scenario(seed: int, outdir: str, replicas: int = 3,
                             requests: int = 24) -> Dict[str, Any]:
    """Kill a replica mid-scoring with SHARDED EMBEDDING TABLES resident.

    The fleet scenario's zero-drop + bit-identity contract, on the
    recommender subsystem (docs/RECOMMENDER.md): every replica serves a
    DLRM whose embedding tables are row-sharded over the 2-D
    ``data x tensor`` mesh (:data:`SHARDED_MESH`), scoring a seeded
    Zipf-id stream drawn from :func:`loadgen.recommender_rows`. At a
    seeded point mid-stream one seeded replica dies without drain.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``zero_failed_requests``   — every request eventually scored
      through the client :class:`RetryPolicy`;
    - ``scores_bit_identical``   — fleet results == an UNSHARDED
      single-device single-server reference, row for row, through the
      kill (the sharded-lookup numerics contract, under failover);
    - ``failover_observed``      — the kill forced >= 1 failover;
    - ``tables_charged_per_shard`` — while the fleet serves, the HBM
      ledger carries the model's ``kind="table"`` bytes at PER-SHARD
      size (tensor axis = 2 -> half the logical table bytes);
    - ``ledger_reconciles_on_close`` — after the fleet (and the
      reference server before it) closes, NO ``{model, kind}`` line
      survives: dead replicas' table shards must not leak in the fleet
      HBM view;
    - ``replicas_stay_probed``   — every probe round answers for every
      replica;
    - ``no_unhandled_exceptions``.

    The schedule (kill point, victim, failover count) is a pure function
    of ``seed`` — the tier-1 smoke test asserts byte-identical replay.
    """
    import numpy as np

    from mmlspark_tpu.embed.model import padded_rows
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.observability import memory as devmem
    from mmlspark_tpu.reliability.retry import RetryPolicy
    from mmlspark_tpu.serve.fleet import Fleet
    from mmlspark_tpu.serve.server import Server

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    dense_dim, slots, embed_dim = 8, 4, 8
    tables = (("user", 64), ("item", 128))
    verdict: Dict[str, Any] = {
        "seed": seed, "scenario": "recommender", "replicas": replicas,
        "requests": requests, "mesh": SHARDED_MESH,
        "tables": [list(t) for t in tables]}

    rng = random.Random(seed ^ 0x7AB1E5)
    # kill right after a probe round (see run_fleet_scenario: the WRR
    # walk then discovers the death by failover, for every seed)
    probe_every = max(4, replicas + 1)
    kill_at = -(-rng.randint(requests // 3, (2 * requests) // 3)
                // probe_every) * probe_every
    kill_at = min(kill_at, max(requests - probe_every, 0))
    kill_idx = rng.randrange(replicas)

    model_kw = dict(seed=seed & 0xFFFF, dense_dim=dense_dim,
                    tables=[list(t) for t in tables],
                    embed_dim=embed_dim, slots=slots,
                    bottom=[16], top=[16])
    stream = loadgen.recommender_rows(
        requests, dense=dense_dim,
        tables=tuple((rows, slots) for _, rows in tables), seed=seed)

    ledger = devmem.get_ledger()
    ledger.reset()
    # per-chip table residency the ledger must carry while serving:
    # padded rows x dim x 4 B, halved by the tensor=2 row-sharding
    expected_shard = sum(padded_rows(rows) * embed_dim * 4
                         for _, rows in tables) // 2

    # phase 1: UNSHARDED single-server reference — the numerics ground
    # truth the sharded fleet must match bit-for-bit
    ref_model = JaxModel(inputCol="x", outputCol="y", miniBatchSize=8)
    ref_model.set_model("recommender_dlrm", **model_kw)
    ref_server = Server({"rec": ref_model}, max_batch=4, queue_depth=32)
    try:
        reference = [np.asarray(ref_server.submit("rec", x, timeout=30))
                     for x in stream]
    finally:
        ref_server.close()
    ledger_after_ref = int(ledger.total())

    # phase 2: the same stream through the sharded fleet with a seeded
    # mid-stream kill; sequential submits keep the WRR walk deterministic
    model = JaxModel(inputCol="x", outputCol="y", miniBatchSize=8,
                     meshSpec=SHARDED_MESH)
    model.set_model("recommender_dlrm", **model_kw)
    fleet = Fleet({"rec": model}, replicas=replicas,
                  server_kwargs={"max_batch": 4, "queue_depth": 32})
    route_log: List[str] = []
    fleet.router.route_log = route_log
    client_retry = RetryPolicy(max_attempts=4, base_delay=0.0, jitter=0.0,
                               name="chaos.recommender.client", seed=seed)
    results: List[Optional[Any]] = []
    failed = 0
    probe_rounds: List[Dict[str, str]] = []
    table_line_mid = 0
    try:
        for i, x in enumerate(stream):
            if i % probe_every == 0:
                probe_rounds.append(fleet.router.probe())
            if i == kill_at:
                fleet.kill(kill_idx)  # lint: allow-actuate
            try:
                results.append(np.asarray(
                    client_retry.call(fleet.submit, "rec", x)))
            except Exception as e:
                failed += 1
                results.append(None)
                errors.append(f"request {i}: {type(e).__name__}: {e}")
        probe_rounds.append(fleet.router.probe())
        # survivors have re-mirrored their residency since the kill:
        # the model's table line sits at per-shard bytes, not logical
        table_line_mid = int(ledger.total(model="rec", kind="table"))
        stats = fleet.stats()
    finally:
        fleet.close()
    ledger_after_close = int(ledger.total())
    table_after_close = int(ledger.total(kind="table"))

    identical = all(
        r is not None and np.array_equal(r, ref)
        for r, ref in zip(results, reference))
    probed_ok = bool(probe_rounds) and all(
        len(round_) == replicas for round_ in probe_rounds)
    failovers = int(stats["failovers"])

    verdict["schedule"] = {
        "kill_at": kill_at, "kill_replica": f"r{kill_idx}",
        "route_log": route_log, "failovers": failovers,
    }
    verdict["fleet"] = {
        "served": sum(1 for r in results if r is not None),
        "failed": failed, "probe_rounds": len(probe_rounds),
    }
    verdict["ledger"] = {
        "table_bytes_serving": table_line_mid,
        "expected_shard_bytes": expected_shard,
        "after_reference_close": ledger_after_ref,
        "table_bytes_after_close": table_after_close,
        "total_bytes_after_close": ledger_after_close,
    }
    invariants = {
        "zero_failed_requests": failed == 0,
        "scores_bit_identical": identical,
        "failover_observed": failovers >= 1,
        "tables_charged_per_shard": table_line_mid == expected_shard,
        "ledger_reconciles_on_close": (ledger_after_ref == 0
                                       and ledger_after_close == 0
                                       and table_after_close == 0),
        "replicas_stay_probed": probed_ok,
        "no_unhandled_exceptions": not errors,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos recommender verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.recommender.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


def run_reshard_scenario(seed: int, outdir: str, replicas: int = 3,
                         requests: int = 24,
                         mesh_to: str = "4x2") -> Dict[str, Any]:
    """SIGKILL a replica MID-RESHARD; the elastic mesh loses nothing.

    The robustness half of ``Fleet.reshard`` (docs/SERVING.md): while the
    fleet moves every replica from the single-device placement onto
    ``mesh_to`` under fire, one seeded replica is killed without drain —
    timed to land INSIDE the reshard, after the first replica starts
    draining and before the victim's own turn in the swap order.

    1. **reference** — the full request stream scored on an untouched
       single :class:`~mmlspark_tpu.serve.server.Server`: the numerics
       ground truth for BOTH placements (the reshard contract is that
       placement never moves a bit).
    2. **fleet under fire** — the same stream through a
       ``replicas``-wide fleet; at a seeded request the reshard starts
       in a background thread, a watcher kills the victim the instant
       the first replica's router weight drops to zero (the reshard's
       first observable action), and the client keeps submitting through
       the whole reshard window behind a :class:`RetryPolicy`.
    3. **post-reshard** — the stream once more, wholly on the new
       placement.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``zero_failed_requests``   — no request failed in any phase: not
      during the swaps, not from the kill, not on the new placement;
    - ``scores_bit_identical``   — under-fire results == reference, row
      for row, through drain/swap/kill/failover;
    - ``scores_bit_identical_post_reshard`` — the resharded fleet still
      matches the reference bit-for-bit;
    - ``reshard_survived_kill``  — the survivors all finished
      (``status="resharded"``), the victim was recorded dead (``died`` /
      ``skipped_dead``), and the fleet landed on ``mesh_to``;
    - ``kill_landed_mid_reshard`` — the watcher really fired inside the
      reshard window;
    - ``fired_through_reshard``  — requests were served WHILE the
      reshard was in flight (zero-downtime is a claim about the whole
      window, not its endpoints);
    - ``params_charged_while_serving`` / ``ledger_reconciles_on_close``
      — the HBM ledger carried ``kind="params"`` bytes while serving
      and holds ZERO bytes of any kind after close: neither the dead
      replica nor the replaced old-placement entries leak;
    - ``victim_probed_dead``     — the router's probe answers ``dead``
      for the victim (dead replicas answer, never wedge);
    - ``no_unhandled_exceptions``.

    The schedule (reshard point, victim, per-replica statuses) is a pure
    function of ``seed`` — the tier-1 smoke test asserts byte-identical
    replay. The kill triggers off the FIRST replica's drain and the
    victim is never that replica, so the victim is already dead when the
    swap order reaches it: ``skipped_dead``, deterministically.
    """
    import threading
    import time as _time

    import numpy as np

    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.observability import memory as devmem
    from mmlspark_tpu.reliability.retry import RetryPolicy
    from mmlspark_tpu.serve.fleet import Fleet
    from mmlspark_tpu.serve.server import Server

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {
        "seed": seed, "scenario": "reshard", "replicas": replicas,
        "requests": requests, "mesh_to": mesh_to}

    rng = random.Random(seed ^ 0x4E5A4D)
    probe_every = max(4, replicas + 1)
    reshard_at = rng.randint(requests // 3, (2 * requests) // 3)
    victim = rng.randrange(1, replicas)

    model = JaxModel(inputCol="x", outputCol="y", miniBatchSize=8)
    model.set_model("mlp_tabular", input_dim=_DIM, hidden=[16],
                    num_classes=3, seed=seed & 0xFFFF)
    stream = loadgen.feature_rows(requests, 2, _DIM, seed)

    ledger = devmem.get_ledger()
    ledger.reset()

    # phase 1: untouched single-server reference
    ref_server = Server({"chaos": model}, max_batch=4, queue_depth=32)
    try:
        reference = [np.asarray(ref_server.submit("chaos", x, timeout=30))
                     for x in stream]
    finally:
        ref_server.close()
    ledger_after_ref = int(ledger.total())

    # phase 2: fire through the fleet with a background reshard and a
    # mid-reshard kill; sequential blocking submits keep the request
    # order (and so the bit-identity comparison) deterministic
    fleet = Fleet({"chaos": model}, replicas=replicas,
                  server_kwargs={"max_batch": 4, "queue_depth": 32})
    client_retry = RetryPolicy(max_attempts=4, base_delay=0.0, jitter=0.0,
                               name="chaos.reshard.client", seed=seed)
    results: List[Optional[Any]] = []
    post: List[Optional[Any]] = []
    failed = 0
    probe_rounds: List[Dict[str, str]] = []
    reshard_box: Dict[str, Any] = {}
    kill_box: Dict[str, Any] = {}
    fired_during = 0
    params_serving = 0

    def _do_reshard() -> None:
        try:
            reshard_box["report"] = fleet.reshard(  # lint: allow-actuate
                mesh_to, warm_x=stream[0])
        except Exception as e:
            reshard_box["err"] = e

    def _watch_and_kill() -> None:
        # the reshard's first observable action is draining replica 0
        # (router weight -> 0); the kill fires right then, while the
        # whole swap sequence is still ahead of the victim
        handle = fleet.router._handles[fleet.replicas[0].name]
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            if handle.weight == 0.0:
                fleet.kill(victim)  # lint: allow-actuate
                kill_box["killed"] = fleet.replicas[victim].name
                return
            _time.sleep(0.0005)

    reshard_t = threading.Thread(
        target=_do_reshard, daemon=True, name="mmlspark-tpu-chaos-reshard")
    watcher_t = threading.Thread(
        target=_watch_and_kill, daemon=True,
        name="mmlspark-tpu-chaos-reshard-kill")
    try:
        for i, x in enumerate(stream):
            if i % probe_every == 0:
                probe_rounds.append(fleet.router.probe())
            if i == reshard_at:
                watcher_t.start()
                reshard_t.start()
            try:
                results.append(np.asarray(
                    client_retry.call(fleet.submit, "chaos", x)))
            except Exception as e:
                failed += 1
                results.append(None)
                errors.append(f"request {i}: {type(e).__name__}: {e}")
            if reshard_t.is_alive():
                fired_during += 1
        # the reshard (fresh-placement compiles per survivor) usually
        # outlives a short stream: keep healthy traffic flowing until it
        # lands — zero-downtime is a claim about the WHOLE window
        spin = itertools.cycle(stream)
        spin_deadline = _time.monotonic() + 120
        while reshard_t.is_alive() and _time.monotonic() < spin_deadline:
            try:
                client_retry.call(fleet.submit, "chaos", next(spin))
                fired_during += 1
            except Exception as e:
                failed += 1
                errors.append(f"recovery: {type(e).__name__}: {e}")
        reshard_t.join(10)
        watcher_t.join(10)
        if reshard_t.is_alive():
            errors.append("reshard wedged: thread still alive")
        if "err" in reshard_box:
            e = reshard_box["err"]
            errors.append(f"reshard raised: {type(e).__name__}: {e}")
        probe_rounds.append(fleet.router.probe())
        params_serving = int(ledger.total(kind="params"))
        # phase 3: the stream once more, wholly on the new placement
        for i, x in enumerate(stream):
            try:
                post.append(np.asarray(
                    client_retry.call(fleet.submit, "chaos", x)))
            except Exception as e:
                failed += 1
                post.append(None)
                errors.append(f"post {i}: {type(e).__name__}: {e}")
    finally:
        fleet.close()
    ledger_after_close = int(ledger.total())
    params_after = int(ledger.total(kind="params"))
    kv_after = int(ledger.total(kind="kv"))

    identical = all(r is not None and np.array_equal(r, ref)
                    for r, ref in zip(results, reference))
    identical_post = all(r is not None and np.array_equal(r, ref)
                         for r, ref in zip(post, reference))
    report = reshard_box.get("report", {})
    statuses = [{"replica": r.get("replica"), "status": r.get("status")}
                for r in report.get("replicas", [])]
    victim_name = f"r{victim}"
    survivors_ok = (
        bool(statuses)
        and all(s["status"] == "resharded" for s in statuses
                if s["replica"] != victim_name)
        and all(s["status"] in ("died", "skipped_dead") for s in statuses
                if s["replica"] == victim_name)
        and report.get("mesh_shape") == mesh_to
        and getattr(fleet, "mesh_shape", "") == mesh_to)
    victim_dead = (probe_rounds
                   and probe_rounds[-1].get(victim_name) == "dead")

    verdict["schedule"] = {
        "reshard_at": reshard_at, "victim": victim_name,
        "statuses": statuses, "mesh_to": mesh_to,
        "resharded": report.get("resharded"),
    }
    verdict["fleet"] = {
        "served": sum(1 for r in results if r is not None),
        "failed": failed, "probe_rounds": len(probe_rounds),
    }
    verdict["ledger"] = {
        "after_reference_close": ledger_after_ref,
        "params_bytes_serving": params_serving,
        "params_bytes_after_close": params_after,
        "kv_bytes_after_close": kv_after,
        "total_bytes_after_close": ledger_after_close,
    }
    invariants = {
        "zero_failed_requests": failed == 0,
        "scores_bit_identical": identical,
        "scores_bit_identical_post_reshard": identical_post,
        "reshard_survived_kill": survivors_ok,
        "kill_landed_mid_reshard": "killed" in kill_box,
        "fired_through_reshard": fired_during > 0,
        "params_charged_while_serving": params_serving > 0,
        "ledger_reconciles_on_close": (ledger_after_ref == 0
                                       and ledger_after_close == 0
                                       and params_after == 0
                                       and kv_after == 0),
        "victim_probed_dead": bool(victim_dead),
        "no_unhandled_exceptions": not errors,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos reshard verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.reshard.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


# -- decode scenario ---------------------------------------------------------

def run_decode_scenario(seed: int, outdir: str, replicas: int = 2,
                        requests: int = 5,
                        mesh: str = "") -> Dict[str, Any]:
    """Kill a replica mid-GENERATION; every sequence still completes.

    Generation raises the stakes over the scoring-fleet scenario: a
    sequence killed mid-decode loses its KV pages and its sampled prefix
    — there is nothing to resume, only a RESTART from the prompt on a
    survivor. The invariant that makes that restart correct is seeded
    sampling: tokens are a pure function of (seed, position), so the
    survivor replays the exact stream the dead replica was producing.

    1. **reference** — every request generated on a single
       :class:`~mmlspark_tpu.serve.server.Server`: the token ground truth.
    2. **fleet** — the same requests through a ``replicas``-wide
       :class:`~mmlspark_tpu.serve.fleet.Fleet`. One seeded request is
       the victim: while it decodes (a seeded delay on the
       ``generate.step`` fault site keeps it in flight long enough to be
       observable), the harness watches per-replica decode-step counters
       and kills the replica that is actually stepping it. The router
       maps the death to a failover and restarts the sequence from its
       prompt on a survivor.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``all_sequences_complete`` — every request returned a finished
      token stream (``finish_reason`` length/stop), including the victim;
    - ``tokens_bit_identical``   — fleet tokens == single-server tokens
      for every request, THROUGH the kill and restart;
    - ``failover_observed``      — the kill really forced >= 1 failover;
    - ``no_unhandled_exceptions``— nothing escaped the router/retry
      channel.

    3. **shared-prefix kill** (phase 3) — two sequences ride the SAME
       cached system-prompt blocks (refcount > 1) and one is killed
       mid-stream while holding them. Invariants:

    - ``prefix_sharing_observed``   — the sharers really held common
      blocks with refcount > 1 when the kill landed;
    - ``prefix_refcounts_reconcile``— after the survivor finishes, the
      block ledger is empty (``used_blocks == 0``) and conservation
      holds (every block in exactly one of free/cached/refcounted);
    - ``no_leaked_kv_bytes``        — the HBM ledger's ``kind="kv"``
      charge still equals the arena's real byte footprint (the fixed
      arena neither grew nor lost accounting through the kill);
    - ``prefix_restart_bit_identical`` — resubmitting the killed request
      (the restart) and the surviving sharer both emit token streams
      bit-identical to a prefix-cache-OFF reference server.
    """
    import threading

    import numpy as np

    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.serve.fleet import Fleet
    from mmlspark_tpu.serve.server import Server
    from mmlspark_tpu.utils import config as mmlconfig

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {
        "seed": seed, "scenario": "decode_sharded" if mesh else "decode",
        "replicas": replicas, "requests": requests, "mesh": mesh}

    rng = random.Random(seed ^ 0xDEC0DE)
    kill_req = rng.randint(requests // 3, max(requests // 3,
                                              (2 * requests) // 3))
    prompts = loadgen.token_prompts(requests, rng, vocab=200,
                                    min_len=3, max_len=8)
    # the victim generates long enough that the kill lands mid-decode;
    # decode lengths are scenario parameters, not a payload stream
    max_new = [24 if i == kill_req else rng.randint(4, 8)  # lint: allow-handload
               for i in range(requests)]

    # a tiny arena keeps compile cost down; restore the config afterwards
    prior = {k: mmlconfig.get(k) for k in
             ("generate.max_seq_len", "generate.max_sequences",
              "generate.kv_block_tokens")}
    mmlconfig.set("generate.max_seq_len", 64)
    mmlconfig.set("generate.max_sequences", 4)
    mmlconfig.set("generate.kv_block_tokens", 8)
    # sharded variant: a 2-D (data x tensor) mesh-bound model whose KV
    # arena is head-sharded over the tensor axis — the kill, failover
    # restart, and shared-prefix ledger invariants must all hold with
    # every chip holding only its param + KV shard
    model = JaxModel(**({"meshSpec": mesh} if mesh else {})).set_model(
        "transformer_lm_tiny", seed=seed & 0xFFFF)

    reference: List[List[int]] = []
    results: List[Optional[Dict[str, Any]]] = []
    killed_replica = ""
    failovers = 0
    route_log: List[str] = []
    try:
        # phase 1: single-server token ground truth
        ref_server = Server({"lm": model})
        try:
            for i in range(requests):
                reference.append(ref_server.generate(
                    "lm", prompts[i], max_new_tokens=max_new[i],
                    seed=seed + i, timeout=60)["tokens"])
        finally:
            ref_server.close()

        # phase 2: the same requests through the fleet; the victim is
        # killed mid-decode and must complete via failover-restart
        fleet = Fleet({"lm": model}, replicas=replicas)
        fleet.router.route_log = route_log
        try:
            for i in range(requests):
                if i != kill_req:
                    try:
                        results.append(fleet.submit_generate(
                            "lm", prompts[i], max_new_tokens=max_new[i],
                            seed=seed + i))
                    except Exception as e:
                        results.append(None)
                        errors.append(
                            f"request {i}: {type(e).__name__}: {e}")
                    continue
                # victim request: client in a thread, kill from here the
                # moment a replica's decode-step counter moves for it
                base = {r.name: (r.server._lanes["lm"].steps
                                 if "lm" in r.server._lanes else 0)
                        for r in fleet.replicas}
                box: Dict[str, Any] = {}

                def _client(idx=i):
                    try:
                        box["out"] = fleet.submit_generate(
                            "lm", prompts[idx],
                            max_new_tokens=max_new[idx], seed=seed + idx)
                    except Exception as e:   # recorded, not swallowed
                        box["err"] = e

                plan = FaultPlan(FaultSpec(
                    "generate.step", on_hit=1, times=10_000,
                    action="delay", delay=0.002))
                with plan:
                    t = threading.Thread(
                        target=_client, daemon=True,
                        name="mmlspark-tpu-chaos-decode-client")
                    t.start()
                    import time as _time
                    deadline = _time.monotonic() + 30
                    while (not killed_replica
                           and _time.monotonic() < deadline):
                        for j, rep in enumerate(fleet.replicas):
                            lane = rep.server._lanes.get("lm")
                            if (lane is not None
                                    and lane.steps > base[rep.name]):
                                fleet.kill(j)  # lint: allow-actuate
                                killed_replica = rep.name
                                break
                        _time.sleep(0.0005)
                    t.join(60)
                if not killed_replica:
                    errors.append("kill never landed: no replica was "
                                  "observed decoding the victim")
                if t.is_alive():
                    errors.append(f"request {i}: victim client wedged")
                    results.append(None)
                elif "err" in box:
                    results.append(None)
                    errors.append(f"request {i} (victim): "
                                  f"{type(box['err']).__name__}: "
                                  f"{box['err']}")
                else:
                    results.append(box.get("out"))
            failovers = int(fleet.router.stats()["failovers"])
        finally:
            fleet.close()

        # phase 3: kill a sequence HOLDING SHARED PREFIX BLOCKS.
        # Deterministic single server, manually stepped (no threads): two
        # sharers ride one system prompt's cached KV; one dies mid-decode
        # with refcount > 1 on the shared blocks; the survivor and the
        # restarted victim must both stay bit-identical, and the block +
        # HBM ledgers must reconcile to the token.
        verdict["prefix"] = _run_shared_prefix_kill(
            model, rng, seed, errors)
    except Exception as e:
        errors.append(f"decode scenario: {type(e).__name__}: {e}")
    finally:
        for k, v in prior.items():
            mmlconfig.set(k, v)

    finished = [r is not None and r.get("finish_reason")
                in ("length", "stop") for r in results]
    identical = (len(results) == len(reference)
                 and all(r is not None and r["tokens"] == ref
                         for r, ref in zip(results, reference)))
    verdict["schedule"] = {
        "kill_request": kill_req, "killed_replica": killed_replica,
        "max_new": max_new, "route_log": route_log,
        "failovers": failovers,
    }
    verdict["decode"] = {
        "completed": sum(finished),
        "finish_reasons": [r.get("finish_reason") if r else None
                           for r in results],
        "ttft_ms": [round(r["ttft_ms"], 3) if r else None
                    for r in results],
    }
    invariants = {
        "all_sequences_complete": bool(results) and all(finished),
        "tokens_bit_identical": identical,
        "failover_observed": failovers >= 1,
        "no_unhandled_exceptions": not errors,
    }
    invariants.update(verdict.get("prefix", {}).get("invariants", {}))
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos decode verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.decode.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


def _run_shared_prefix_kill(model, rng, seed: int,
                            errors: List[str]) -> Dict[str, Any]:
    """Phase 3 of the decode scenario: kill a sequence that is HOLDING
    shared prefix blocks (refcount > 1) mid-stream.

    Deterministic by construction — one :class:`Server` stepped by hand,
    no threads, the kill landed at an exact step boundary — so a red
    verdict here is a real ledger bug, never scheduling noise. See
    :func:`run_decode_scenario` for the invariants.
    """
    from mmlspark_tpu.observability import memory as devmem
    from mmlspark_tpu.serve.server import Server
    from mmlspark_tpu.utils import config as mmlconfig

    bt = int(mmlconfig.get("generate.kv_block_tokens"))
    # one shared system prompt of 3 full KV blocks, from the shared-prefix
    # population vocabulary (rank-0 prefix of a 1-prefix population)
    sysp = loadgen.PromptPopulation(
        rng, prefixes=1, prefix_tokens=3 * bt, vocab=200).prefix(0)
    pa, pb = sysp + [11, 12], sysp + [21, 22]
    max_new = 10

    def _stepped(srv, lane, prompt, sd):
        fut = srv.submit_generate("lm", prompt, max_new_tokens=max_new,
                                  seed=sd)
        for _ in range(96):
            if fut.done():
                break
            lane.step()
        return fut.result(1)["tokens"]

    # independent token ground truth: a reference server with the
    # prefix cache OFF (no sharing anywhere in its decode path)
    prior = mmlconfig.get("generate.prefix_cache")
    mmlconfig.set("generate.prefix_cache", False)
    try:
        ref_srv = Server({"lm": model}, start=False)
        try:
            ref_lane = ref_srv.enable_generate("lm", start=False)
            ref_a = _stepped(ref_srv, ref_lane, pa, seed + 101)
            ref_b = _stepped(ref_srv, ref_lane, pb, seed + 102)
        finally:
            ref_srv.close()
    finally:
        mmlconfig.set("generate.prefix_cache", prior)

    sharing = reconciled = identical = leak_ok = False
    victim_surfaced = False
    shared_blocks = 0
    stats: Dict[str, Any] = {}
    srv = Server({"lm": model}, start=False)
    try:
        lane = srv.enable_generate("lm", start=False)
        kv = lane.gen.kv
        ledger = devmem.get_ledger()
        charged0 = ledger.total(model="lm", kind="kv")
        # warm the prefix index, then run both sharers together
        _stepped(srv, lane, sysp + [1], seed + 100)
        fa = srv.submit_generate("lm", pa, max_new_tokens=max_new,
                                 seed=seed + 101)
        fb = srv.submit_generate("lm", pb, max_new_tokens=max_new,
                                 seed=seed + 102)
        lane.step()          # both admitted, riding the cached prefix
        lane.step()          # ... and decoding: the kill lands MID-stream
        victim = next((s for s in lane.batcher.active if s.future is fa),
                      None)
        if victim is None:
            errors.append("prefix kill: victim never reached the batch")
        else:
            shared = [b for b in kv.blocks_for(victim.seq_id)
                      if kv.block_refcount(b) > 1]
            shared_blocks = len(shared)
            sharing = bool(shared)
            lane._fail_seq(victim, RuntimeError("chaos: killed mid-stream"))
            lane.batcher.leave(victim)
        for _ in range(96):  # the survivor decodes on, unperturbed
            if fb.done():
                break
            lane.step()
        toks_b = fb.result(1)["tokens"]
        try:
            fa.result(0)
        except RuntimeError:
            victim_surfaced = True   # the kill reported, not swallowed
        # the restart: resubmit the killed request from its prompt
        toks_a = _stepped(srv, lane, pa, seed + 101)
        identical = (toks_a == ref_a) and (toks_b == ref_b)
        reconciled = kv.used_blocks == 0 and kv.check_conservation()
        charged1 = ledger.total(model="lm", kind="kv")
        # per-SHARD footprint: for a head-sharded arena (decode_sharded)
        # the ledger charges what one chip actually holds, not the
        # logical total; equal to arena_bytes() when unsharded
        leak_ok = (charged1 == kv.arena_shard_bytes()
                   and charged1 == charged0)
        stats = {k: v for k, v in lane.stats().items()
                 if k.startswith(("prefix", "cow", "kv."))}
    except Exception as e:
        errors.append(f"prefix kill: {type(e).__name__}: {e}")
    finally:
        srv.close()
    return {
        "shared_blocks_at_kill": shared_blocks,
        "stats": stats,
        "invariants": {
            "prefix_sharing_observed": sharing,
            "prefix_refcounts_reconcile": reconciled,
            "no_leaked_kv_bytes": leak_ok,
            "prefix_restart_bit_identical": identical,
            "victim_error_surfaced": victim_surfaced,
        },
    }


# -- fleetprefix scenario ----------------------------------------------------

def run_fleetprefix_scenario(seed: int, outdir: str, replicas: int = 3,
                             requests: int = 12) -> Dict[str, Any]:
    """Kill the replica holding the HOTTEST advertised prefix chains.

    The affinity subsystem's chaos counterpart: prefix-digest routing
    deliberately concentrates a Zipf-hot system prompt's KV blocks on
    one replica — which makes that replica's death the worst case the
    "N replicas, one cache" story has to survive. The scenario builds
    exactly that concentration, then kills it mid-stream.

    1. **reference** — every request generated on a single
       :class:`~mmlspark_tpu.serve.server.Server`: the token ground
       truth (and the shared compile cache every fleet replica loads
       from — what makes ``steady_compiles_zero`` assertable).
    2. **warm** — a seeded Zipf :class:`~mmlspark_tpu.testing.loadgen.
       PromptPopulation` round through the fleet under plain WRR (no
       digests exist yet), then one :class:`FleetScraper` scrape pulls
       every replica's advertised chains into the router's
       :class:`~mmlspark_tpu.serve.affinity.AffinityState`.
    3. **kill** — a rank-0 (hottest prefix) victim request is submitted;
       affinity steers it to a deepest-chain leader, and the harness
       kills the replica actually decoding it mid-stream. Failover
       restarts the sequence from its prompt, re-scored against the
       SURVIVORS' digests.
    4. **recover** — a session-keyed round: every session lands on a
       survivor, re-uses cached prefixes, and compiles nothing.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``all_sequences_complete``  — every request (victim included)
      returned a finished stream: zero failed requests through the kill;
    - ``tokens_bit_identical``    — fleet tokens == single-server tokens
      for every request, through kill, failover, and session rounds;
    - ``victim_routed_to_leader`` — the kill landed on a replica the
      digest scoring named a deepest-chain leader for the victim prompt
      (the router concentrated the hot prefix where it claimed);
    - ``failover_observed``       — the kill really forced >= 1 failover;
    - ``sessions_absorbed``       — no post-kill request routed to the
      dead replica (session ring + candidate filter exclude it);
    - ``hit_rate_recovers``       — the recovery round re-used cached
      prefix blocks on survivors (summed per-request ``prefix_hits`` >
      0);
    - ``steady_compiles_zero``    — survivors absorbed the victim's
      sessions with ZERO new XLA compiles;
    - ``no_unhandled_exceptions`` — nothing escaped the router/retry
      channel.

    Everything — prompts, routing order, the victim, the verdict — is a
    pure function of ``seed``.
    """
    import threading
    import time as _time

    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.observability.aggregate import FleetScraper
    from mmlspark_tpu.serve import affinity as aff_mod
    from mmlspark_tpu.serve.fleet import Fleet
    from mmlspark_tpu.serve.kvcache import prefix_block_hashes
    from mmlspark_tpu.serve.server import Server
    from mmlspark_tpu.utils import config as mmlconfig

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {"seed": seed, "scenario": "fleetprefix",
                               "replicas": replicas, "requests": requests}

    rng = random.Random(seed ^ 0xAFF1)
    prior = {k: mmlconfig.get(k) for k in
             ("generate.max_seq_len", "generate.max_sequences",
              "generate.kv_block_tokens", "generate.advertise_top_k",
              "fleet.affinity_enabled", "fleet.affinity_min_depth",
              "runtime.compile_cache_dir")}
    mmlconfig.set("generate.max_seq_len", 64)
    mmlconfig.set("generate.max_sequences", 4)
    mmlconfig.set("generate.kv_block_tokens", 8)
    mmlconfig.set("generate.advertise_top_k", 8)
    mmlconfig.set("fleet.affinity_enabled", True)
    mmlconfig.set("fleet.affinity_min_depth", 1)
    mmlconfig.set("runtime.compile_cache_dir",
                  os.path.join(outdir, "compile_cache"))

    bt = 8
    pop = loadgen.PromptPopulation(rng, prefixes=3, prefix_tokens=2 * bt,
                                   vocab=200, zipf_s=1.2)
    warm_prompts = [pop.sample(tail_tokens=2) for _ in range(requests)]
    # the victim rides the HOTTEST prefix; a fixed tail keeps the prompt
    # a pure function of the population (itself a pure function of seed)
    victim_prompt = pop.prefix(0) + [5, 7]
    sess_prompts = [pop.sample(tail_tokens=2)
                    for _ in range(max(2, requests // 2))]

    def _rank(prompt: List[int]) -> int:
        return next(r for r in range(3)
                    if prompt[:2 * bt] == pop.prefix(r))

    # per-request decode lengths: scenario parameters, not a payload
    # stream; the victim decodes long enough for the kill to land
    warm_new = [rng.randint(4, 8) for _ in warm_prompts]  # lint: allow-handload
    sess_new = [rng.randint(4, 8) for _ in sess_prompts]  # lint: allow-handload
    victim_new = 24

    model = JaxModel().set_model("transformer_lm_tiny", seed=seed & 0xFFFF)

    reference: List[List[int]] = []
    results: List[Optional[Dict[str, Any]]] = []
    killed_replica = ""
    leaders: List[str] = []
    failovers = 0
    kill_at = -1
    compile_delta = -1
    recover_hits = -1
    route_log: List[str] = []
    all_prompts = warm_prompts + [victim_prompt] + sess_prompts
    all_new = warm_new + [victim_new] + sess_new
    try:
        # phase 1: single-server token ground truth (+ compile cache)
        ref_server = Server({"lm": model})
        try:
            for i, p in enumerate(all_prompts):
                reference.append(ref_server.generate(
                    "lm", p, max_new_tokens=all_new[i],
                    seed=seed + i, timeout=60)["tokens"])
        finally:
            ref_server.close()

        fleet = Fleet({"lm": model}, replicas=replicas)
        fleet.router.route_log = route_log
        scraper = FleetScraper(fleet)
        try:
            # phase 2: warm round (WRR — nothing advertised yet), then
            # one scrape publishes every replica's digest
            for i, p in enumerate(warm_prompts):
                try:
                    results.append(fleet.submit_generate(
                        "lm", p, max_new_tokens=warm_new[i],
                        seed=seed + i))
                except Exception as e:
                    results.append(None)
                    errors.append(f"warm {i}: {type(e).__name__}: {e}")
            scraper.scrape()
            aff = fleet.router.affinity
            kv_dtype = fleet.replicas[0].server.stats().get(
                "generate.lm.kv.kv_dtype", "float32")
            vh = prefix_block_hashes("lm", str(kv_dtype),
                                     victim_prompt, bt)
            scores = {r.name: aff_mod.score_digest(
                aff.digest_for(r.name, "lm"), vh)
                for r in fleet.replicas}
            best = max(scores.values())
            leaders = sorted(n for n, s in scores.items() if s == best)

            # phase 3: the victim decodes on a deepest-chain leader; the
            # harness kills whichever replica is actually stepping it
            vidx = len(warm_prompts)
            base = {r.name: (r.server._lanes["lm"].steps
                             if "lm" in r.server._lanes else 0)
                    for r in fleet.replicas}
            box: Dict[str, Any] = {}

            def _client():
                try:
                    box["out"] = fleet.submit_generate(
                        "lm", victim_prompt, max_new_tokens=victim_new,
                        seed=seed + vidx)
                except Exception as e:
                    box["err"] = e

            plan = FaultPlan(FaultSpec(
                "generate.step", on_hit=1, times=10_000,
                action="delay", delay=0.002))
            with plan:
                t = threading.Thread(
                    target=_client, daemon=True,
                    name="mmlspark-tpu-chaos-fleetprefix-client")
                t.start()
                deadline = _time.monotonic() + 30
                while (not killed_replica
                       and _time.monotonic() < deadline):
                    for j, rep in enumerate(fleet.replicas):
                        lane = rep.server._lanes.get("lm")
                        if (lane is not None
                                and lane.steps > base[rep.name]):
                            fleet.kill(j)  # lint: allow-actuate
                            killed_replica = rep.name
                            kill_at = len(route_log)
                            break
                    _time.sleep(0.0005)
                t.join(60)
            if not killed_replica:
                errors.append("kill never landed: no replica was "
                              "observed decoding the victim")
            if t.is_alive():
                errors.append("victim client wedged")
                results.append(None)
            elif "err" in box:
                results.append(None)
                errors.append(f"victim: {type(box['err']).__name__}: "
                              f"{box['err']}")
            else:
                results.append(box.get("out"))

            # phase 4: session-keyed recovery round on the survivors —
            # fresh digests first, then zero new compiles allowed
            scraper.scrape()
            survivors = [r for r in fleet.replicas if not r._dead]
            pre = {r.name: int(r.server.stats().get(
                "registry.compiles", 0)) for r in survivors}
            hits = 0
            for i, p in enumerate(sess_prompts):
                gi = vidx + 1 + i
                try:
                    out = fleet.submit_generate(
                        "lm", p, max_new_tokens=sess_new[i],
                        seed=seed + gi, session=f"sess{_rank(p)}")
                    results.append(out)
                    hits += int(out.get("prefix_hits", 0))
                except Exception as e:
                    results.append(None)
                    errors.append(f"session {i}: {type(e).__name__}: {e}")
            recover_hits = hits
            compile_delta = sum(
                int(r.server.stats().get("registry.compiles", 0))
                - pre[r.name] for r in survivors)
            failovers = int(fleet.router.stats()["failovers"])
            verdict["affinity"] = fleet.router.affinity.snapshot()
        finally:
            fleet.close()
    except Exception as e:
        errors.append(f"fleetprefix scenario: {type(e).__name__}: {e}")
    finally:
        for k, v in prior.items():
            mmlconfig.set(k, v)

    finished = [r is not None and r.get("finish_reason")
                in ("length", "stop") for r in results]
    identical = (len(results) == len(reference)
                 and all(r is not None and r["tokens"] == ref
                         for r, ref in zip(results, reference)))
    post_kill = route_log[kill_at:] if kill_at >= 0 else []
    verdict["schedule"] = {
        "killed_replica": killed_replica, "leaders": leaders,
        "victim_rank": 0, "kill_at": kill_at, "route_log": route_log,
        "warm_new": warm_new, "sess_new": sess_new,
        "failovers": failovers,
    }
    verdict["recover"] = {"prefix_hits": recover_hits,
                          "compile_delta": compile_delta}
    invariants = {
        "all_sequences_complete": bool(results) and all(finished),
        "tokens_bit_identical": identical,
        "victim_routed_to_leader": bool(killed_replica)
        and killed_replica in leaders,
        "failover_observed": failovers >= 1,
        "sessions_absorbed": bool(post_kill)
        and killed_replica not in post_kill,
        "hit_rate_recovers": recover_hits > 0,
        "steady_compiles_zero": compile_delta == 0,
        "no_unhandled_exceptions": not errors,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos fleetprefix verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.fleetprefix.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


# -- host scenario -----------------------------------------------------------

class _DeadHandle:
    """Fake worker handle that is already dead at birth: the crash-loop
    stimulus for the supervisor's breaker hysteresis (phase B of the host
    scenario). Satisfies the duck-typed handle protocol."""

    def __init__(self, pid: int):
        self.pid = pid
        self.addr = ""

    def poll(self) -> int:
        return 1

    def wait(self, timeout: Optional[float] = None) -> int:
        return 1

    def terminate(self) -> None:
        pass

    def kill(self) -> None:
        pass

    def close(self) -> None:
        pass


class _CrashSpawner:
    """Spawner whose every child dies instantly; counts spawns so the
    no-flapping invariant is a plain integer comparison."""

    def __init__(self) -> None:
        self.spawns = 0

    def spawn(self, name: str) -> _DeadHandle:
        self.spawns += 1
        return _DeadHandle(40_000 + self.spawns)


def run_host_scenario(seed: int, outdir: str, replicas: int = 2,
                      requests: int = 12) -> Dict[str, Any]:
    """SIGKILL a worker PROCESS under fire; the fleet rides it out warm.

    Unlike the ``fleet`` scenario (in-process replicas, simulated kill),
    every replica here is a real ``mmlspark-tpu serve`` OS process behind
    the :class:`~mmlspark_tpu.serve.supervisor.Supervisor` — the kill is
    a real ``SIGKILL`` (no drain, no goodbye, a torn final event-log
    line), and the restart is a real process cold-start that must come
    back WARM from the shared compile cache.

    **Phase A (real processes):** spawn ``replicas`` workers over a
    shared ``runtime.compile_cache_dir`` and a shared per-pid-sidecar
    events dir; drive a seeded request stream through the Router (client
    retries ride out the failover window); at the seeded ``kill_at`` the
    seeded victim is SIGKILLed; the supervisor backs off, respawns it,
    and re-registers it into rotation; the harness then scores directly
    on the restarted replica and scrapes its ``/metrics`` for
    ``compile_cache_hits``.

    **Phase B (crash-loop hysteresis, virtual clock):** a fake spawner
    whose children die at birth drives the SAME supervisor state machine
    under an injected clock: enough consecutive crashes trip the breaker
    OPEN, the cooldown admits exactly ONE half-open probe respawn, and
    the probe's crash re-opens — restart *flapping* is structurally
    impossible, and the whole phase is deterministic.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``zero_failed_requests``     — every streamed request scored
      despite the kill (failover + client retry absorbed the window);
    - ``warm_restart``             — the RESTARTED process reports
      ``compile_cache_hits > 0``: it loaded programs, didn't compile;
    - ``restart_observed``         — the victim really respawned (new
      pid, same replica name, back in rotation);
    - ``supervisor_events``        — the merged per-pid sidecars carry
      the supervisor's ``spawn``/``exit``/``backoff``/``restart``
      decisions;
    - ``merged_report_coherent``   — one ``build_report`` over all
      sidecars yields a supervisor section whose distinct worker pids
      cover the initial fleet AND the restart;
    - ``crash_loop_breaker_open``  — phase B ends breaker-open, the
      crash-looper held OUT of rotation;
    - ``no_restart_flapping``      — total phase-B spawns ==
      ``breaker_failures + 1`` (the closed-state attempts plus exactly
      one half-open probe) and the cooldown window spawned nothing.

    The ``schedule`` (kill point, victim) is a pure function of ``seed``.
    """
    import time as _time
    import urllib.request

    import numpy as np

    from mmlspark_tpu.observability.aggregate import (expand_event_paths,
                                                      merge_event_logs,
                                                      parse_prometheus_text)
    from mmlspark_tpu.observability.report import build_report
    from mmlspark_tpu.reliability.retry import RetryPolicy
    from mmlspark_tpu.serve.router import Router
    from mmlspark_tpu.serve.supervisor import ProcessSpawner, Supervisor
    from mmlspark_tpu.utils import config as mmlconfig

    os.makedirs(outdir, exist_ok=True)
    events_dir = os.path.join(outdir, "events")
    cache_dir = os.path.join(outdir, "compile-cache")
    os.makedirs(events_dir, exist_ok=True)
    os.makedirs(cache_dir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {"seed": seed, "scenario": "host",
                               "replicas": replicas, "requests": requests}

    rng = random.Random(seed ^ 0x4057)
    kill_at = rng.randint(max(1, requests // 3), max(1, (2 * requests) // 3))
    kill_idx = rng.randrange(replicas)
    kill_name = f"w{kill_idx}"
    verdict["schedule"] = {"kill_at": kill_at, "kill_replica": kill_name}

    model_spec = json.dumps({"input_dim": _DIM, "hidden": [16],
                             "num_classes": 3, "seed": seed & 0xFFFF})
    model_flag = f"chaos=mlp_tabular:{model_spec}"

    # the chaos/supervisor process writes its OWN per-pid sidecar next to
    # the workers' so supervisor.* decisions land in the merged view
    prior_events = mmlconfig.get("observability.events_path")
    mmlconfig.set("observability.events_path",
                  os.path.join(events_dir, f"events-{os.getpid()}.jsonl"))

    names = [f"w{i}" for i in range(replicas)]
    spawner = ProcessSpawner([model_flag], events_dir=events_dir,
                             compile_cache_dir=cache_dir,
                             extra_args=["--max-batch", "4",
                                         "--queue-depth", "32"])
    # tight supervision: a SIGKILLed worker respawns within ~50 ms of the
    # reap, and half a second of uptime confirms the incarnation healthy
    sup = Supervisor(spawner, names, min_uptime_s=0.5, base_delay_s=0.05,
                     max_delay_s=0.5, breaker_failures=3,
                     breaker_reset_s=30.0)
    client = RetryPolicy(max_attempts=6, base_delay=0.2, max_delay=2.0,
                         jitter=0.0, name="chaos.host.client", seed=seed)
    stream = loadgen.feature_rows(requests, 2, _DIM, seed)

    served = 0
    failed = 0
    killed_pid: Optional[int] = None
    cache_hits = -1.0
    restart_stats: Dict[str, Any] = {}
    router = None
    try:
        sup.start()
        down = [n for n, s in sup.stats()["replicas"].items()
                if not s["running"]]
        if down:
            raise ChaosError(f"workers failed to start: {down} "
                             f"(see {events_dir}/worker-*.log)")
        router = Router(sup.replicas, failover_attempts=replicas + 1)
        sup.attach_router(router)
        router.probe()
        sup.start_monitor(0.05)
        for i, x in enumerate(stream):
            if i == kill_at:
                killed_pid = sup.kill_replica(  # lint: allow-actuate
                    kill_name)
                if killed_pid is None:
                    errors.append("kill landed on a slot with no live "
                                  "process")
            try:
                y = np.asarray(client.call(router.submit, "chaos", x))
                if y.shape[0] == 2:
                    served += 1
                else:
                    failed += 1
                    errors.append(f"request {i}: wrong shape {y.shape}")
            except Exception as e:
                failed += 1
                errors.append(f"request {i}: {type(e).__name__}: {e}")
        # wait for the warm restart (respawn is ~50 ms after the reap; the
        # child's cold-start — imports + cache loads — dominates)
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            st = sup.stats()["replicas"][kill_name]
            # ready_spawns (not spawns) is the gate: the respawned pid is
            # alive long before it binds, and only _on_ready guarantees
            # the replica's addr points at the NEW incarnation
            if st["running"] and st["ready_spawns"] >= 2:
                restart_stats = dict(st)
                break
            _time.sleep(0.1)
        if not restart_stats:
            errors.append("killed replica never came back ready")
        else:
            # score directly on the RESTARTED process (forces its lazy
            # program build), then read its own /metrics: a warm restart
            # LOADED compiled programs from the shared cache
            rep = sup.replica(kill_name)
            y = np.asarray(rep.submit("chaos", stream[kill_at]))
            if y.shape[0] != 2:
                errors.append(f"restarted replica: wrong shape {y.shape}")
            with urllib.request.urlopen(f"{rep.addr}/metrics",
                                        timeout=10) as resp:
                parsed = parse_prometheus_text(resp.read().decode())
            cache_hits = float(
                parsed.get("compile_cache_hits", {}).get("value", 0.0))
    except Exception as e:
        errors.append(f"host scenario: {type(e).__name__}: {e}")
    finally:
        if router is not None:
            try:
                router.close()
            except Exception as e:
                _LOG.debug("router close failed: %s", e)
        sup.shutdown(reason="chaos host scenario complete")

    verdict["schedule"]["killed_pid"] = killed_pid
    verdict["host"] = {"served": served, "failed": failed,
                       "restart": restart_stats,
                       "compile_cache_hits": cache_hits,
                       "events_dir": events_dir}

    # merge every per-pid sidecar (workers + supervisor) into ONE view;
    # the SIGKILLed worker's torn final line must be skipped, not fatal
    paths = expand_event_paths(
        [], os.path.join(events_dir, "events-*.jsonl"))
    merged = merge_event_logs(paths)
    sup_event_names = {e.get("name") for e in merged
                       if e.get("type") == "supervisor"}
    report = build_report(paths) if paths else {}
    rep_sup = report.get("supervisor", {}) if isinstance(report, dict) \
        else {}
    worker_pids = rep_sup.get("worker_pids", [])
    coherent = (bool(rep_sup)
                and len(set(worker_pids)) >= replicas + 1
                and rep_sup.get("restarts", 0) >= 1)
    verdict["host"]["sidecars"] = len(paths)
    verdict["host"]["supervisor_event_names"] = sorted(
        n for n in sup_event_names if n)

    # phase B: crash-loop hysteresis on a virtual clock (deterministic)
    vt = {"t": 0.0}
    crash = _CrashSpawner()
    sup2 = Supervisor(crash, ["cl0"], min_uptime_s=5.0, base_delay_s=1.0,
                      max_delay_s=8.0, ready_timeout_s=1.0,
                      breaker_failures=3, breaker_reset_s=60.0,
                      clock=lambda: vt["t"],
                      sleep=lambda s: vt.__setitem__("t", vt["t"] + s))
    sup2.start()
    opened_at: Optional[float] = None
    spawns_at_open = 0
    spawn_trace: List[Any] = []
    for _ in range(200):
        sup2.poll_once()
        state = sup2.breaker_state("cl0")
        spawn_trace.append((vt["t"], crash.spawns, state))
        if opened_at is None and state == "open":
            opened_at = vt["t"]
            spawns_at_open = crash.spawns
        vt["t"] += 1.0
        if opened_at is not None and vt["t"] > opened_at + 75.0:
            break
    sup2.shutdown(reason="chaos host phase B complete")
    final_state = sup2.breaker_state("cl0")
    cooldown_spawns = [s for t, s, _ in spawn_trace
                       if opened_at is not None
                       and opened_at <= t < opened_at + 59.0]
    no_spawn_in_cooldown = bool(cooldown_spawns) \
        and max(cooldown_spawns) == spawns_at_open
    verdict["crash_loop"] = {
        "spawns": crash.spawns, "opened_at": opened_at,
        "spawns_at_open": spawns_at_open, "final_breaker": final_state,
    }

    invariants = {
        "zero_failed_requests": failed == 0 and served == requests,
        "warm_restart": cache_hits > 0,
        "restart_observed": bool(restart_stats),
        "supervisor_events": {"spawn", "exit", "backoff",
                              "restart"} <= sup_event_names,
        "merged_report_coherent": coherent,
        "crash_loop_breaker_open": final_state == "open",
        "no_restart_flapping": (crash.spawns == 3 + 1
                                and no_spawn_in_cooldown),
        "no_unhandled_exceptions": not errors,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    # restore the prior event sink AFTER the verdict facts are gathered
    mmlconfig.set("observability.events_path", prior_events)

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos host verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.host.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


# -- autopilot scenario ------------------------------------------------------

def _autopilot_drive(model, stream, arrivals, *, kill_round: int,
                     kill_idx: int, replicas: int, policy,
                     events_path: str = "",
                     deadline_s: float = 90.0) -> Dict[str, Any]:
    """One fleet pass through the seeded open-loop schedule — the shared
    driver behind both halves of the autopilot scenario (and the
    ``serving_autopilot`` bench lane). ``policy=None`` is the static
    fleet: same arrivals, same kill, no controller.

    OPEN loop: ``arrivals`` (per-round offered counts, normally
    ``loadgen.bucket_counts`` of a seeded trace) keeps offering no
    matter how wedged the fleet is, and every request's latency is
    measured from its ARRIVAL round — a retry after a kill does not
    restart its clock (the re-enqueue-time accounting this replaces was
    coordinated omission: both halves of the r08 spike read exactly
    90000.0 ms because the deadline clipped what the retries hid). The
    returned ``workload`` dict is the
    :class:`~mmlspark_tpu.observability.goodput.GoodputMeter` verdict:
    goodput under ``deadline_s``, offered/delivered QPS, and the
    un-clipped arrival-time percentiles.

    No executor threads: every replica is a ``start=False``
    :class:`~mmlspark_tpu.serve.server.Server` stepped with
    :meth:`~mmlspark_tpu.serve.server.Server.pump` (one coalesce+flush
    per replica per round), and the autopilot/SLO stack runs on a
    virtual clock advancing 30 s per round — the whole pass is a pure
    function of the schedule, which is what lets the verdict compare
    the two halves shed-for-shed."""
    import numpy as np

    from mmlspark_tpu.control.autopilot import Autopilot
    from mmlspark_tpu.observability.aggregate import FleetScraper
    from mmlspark_tpu.observability.goodput import GoodputMeter
    from mmlspark_tpu.observability.slo import SloEngine
    from mmlspark_tpu.serve.fleet import Fleet
    from mmlspark_tpu.serve.server import ServerClosed, ServerOverloaded

    fleet = Fleet({"chaos": model}, replicas=replicas, start=False,
                  server_kwargs={"max_batch": 4, "queue_depth": 8})
    vclock = {"t": 1000.0}
    scraper = FleetScraper(fleet, clock=lambda: vclock["t"])
    engine = SloEngine(clock=lambda: vclock["t"],
                       fast_window_s=300.0, slow_window_s=900.0)
    pilot = None
    if policy is not None:
        pilot = Autopilot(fleet, scraper=scraper, engine=engine,
                          policy=policy, clock=lambda: vclock["t"])

    prior_events = None
    if events_path:
        from mmlspark_tpu.utils import config as mmlconfig
        prior_events = mmlconfig.get("observability.events_path")
        mmlconfig.set("observability.events_path", events_path)

    scores: Dict[int, Any] = {}
    lat_rounds: Dict[int, int] = {}
    arrival_round: Dict[int, int] = {}   # intended arrival, NOT re-enqueue
    meter = GoodputMeter(deadline_s=deadline_s, bucket_s=30.0)
    shed = 0
    hard_failed = 0
    pending: List[tuple] = []   # (idx, replica, future, enqueue_round)
    retries: List[int] = []
    decisions: List[Dict[str, Any]] = []
    trace: List[Dict[str, Any]] = []
    next_req = 0

    def _tid(idx: int) -> str:
        return f"q{idx:06d}"

    def enqueue(idx: int, rnd: int) -> None:
        nonlocal shed
        weights = {name: h.get("weight", 0.0) for name, h in
                   fleet.router.stats()["replicas"].items()}
        cands = [r for r in fleet.replicas
                 if not r._dead and weights.get(r.name, 0.0) > 0.0]
        if not cands:
            shed += 1
            meter.shed(_tid(idx))
            return
        # deterministic spread: shortest queue wins, name breaks ties
        rep = min(cands, key=lambda r: (
            r.server.stats().get("queue_depth", 0), r.name))
        try:
            fut = rep.server.submit_async("chaos", stream[idx],
                                          trace_id=_tid(idx))
            pending.append((idx, rep, fut, rnd))
        except (ServerOverloaded, ServerClosed):
            shed += 1
            meter.shed(_tid(idx))

    def step_round(rnd: int, new_arrivals: int) -> None:
        nonlocal pending, hard_failed, retries
        if rnd == kill_round:
            fleet.kill(kill_idx)  # lint: allow-actuate
        this_round, retries = retries, []
        nonlocal next_req
        for idx in range(next_req, next_req + new_arrivals):
            arrival_round[idx] = rnd
            meter.offer(_tid(idx), vclock["t"])
            this_round.append(idx)
        next_req += new_arrivals
        for idx in this_round:
            enqueue(idx, rnd)
        for rep in list(fleet.replicas):
            if not rep._dead:
                try:
                    rep.server.pump(max_batches=1)
                except ServerClosed:  # pragma: no cover - kill race
                    pass
        still: List[tuple] = []
        for idx, rep, fut, enq in pending:
            if fut.done():
                exc = fut.exception()
                if exc is None:
                    scores[idx] = np.asarray(fut.result())
                    # arrival-time truth: the clock started when the
                    # request was OFFERED, not when a retry re-entered
                    lat_rounds[idx] = rnd - arrival_round[idx]
                    meter.complete(_tid(idx), vclock["t"])
                elif isinstance(exc, (ServerOverloaded, ServerClosed)):
                    retries.append(idx)   # the kill shed it; try again
                else:
                    hard_failed += 1
                    meter.expire(_tid(idx))
            elif rep._dead:
                retries.append(idx)       # future died with the replica
            else:
                still.append((idx, rep, fut, enq))
        pending = still
        if pilot is not None:
            decisions.extend(pilot.tick())
        else:
            engine.observe(scraper.slo_sample(scraper.scrape()))
        status = engine.status()
        trace.append({
            "round": rnd, "t": vclock["t"],
            "live": sum(1 for r in fleet.replicas
                        if not r._dead and r.health().get("ready")),
            "replicas": len(fleet.replicas),
            "burning": any(s["burning"] for s in status),
            "shed": shed})
        vclock["t"] += 30.0

    try:
        for rnd, n in enumerate(arrivals):
            step_round(rnd, n)
        # drain rounds: no new arrivals, same tick cadence, until every
        # admitted/retried request has resolved (bounded — base load is
        # far below capacity, so a handful of rounds always suffices)
        rnd = len(arrivals)
        while (pending or retries) and rnd < len(arrivals) + 12:
            step_round(rnd, 0)
            rnd += 1

        rstats = fleet.router.stats()["replicas"]
        final = {
            "live_ready": sum(1 for r in fleet.replicas
                              if not r._dead and r.health().get("ready")),
            "replicas": len(fleet.replicas),
            "ready_weights": {r.name: rstats[r.name]["weight"]
                              for r in fleet.replicas
                              if not r._dead and r.name in rstats},
            "dead_weights": {r.name: rstats[r.name]["weight"]
                             for r in fleet.replicas
                             if r._dead and r.name in rstats},
            "capacity_rows": int(fleet.router.fairness.capacity_rows),
            "baseline_rows": int(fleet.router.fairness.baseline_rows),
            "compiles": sum(
                int(s.get("registry.compiles", 0))
                for s in fleet.stats()["servers"].values()),
        }
        # workload verdict (goodput, offered/delivered QPS, un-clipped
        # arrival percentiles) — exported while the event log is still
        # ours so `report` can render the workload section for this run
        workload = meter.export(
            lane="autopilot" if policy is not None else "static")
    finally:
        if events_path:
            from mmlspark_tpu.utils import config as mmlconfig
            mmlconfig.set("observability.events_path", prior_events)
            from mmlspark_tpu.observability import events as _events
            _events.close()
        fleet.close()

    return {"scores": scores, "latency_rounds": lat_rounds,
            "arrival_rounds": arrival_round, "workload": workload,
            "shed": shed, "hard_failed": hard_failed,
            "unresolved": len(pending) + len(retries),
            "decisions": decisions, "trace": trace, "final": final}


def _no_flap(events_path: str, policy) -> Dict[str, Any]:
    """The no-flap check, from the ``autopilot.*`` event stream ALONE
    (not the in-memory decision list): no cooldown key may actuate two
    DIFFERENT actions within one cooldown window — A -> B -> A inside a
    window is the textbook control-loop flap the shared up/down cooldown
    key exists to prevent."""
    from mmlspark_tpu.control.autopilot import cooldown_key
    cooldowns = {"shift": policy.shift_cooldown_s,
                 "scale": policy.scale_cooldown_s,
                 "admission": policy.admission_cooldown_s}
    acted: List[Dict[str, Any]] = []
    suppressed = 0
    with open(events_path) as f:
        for line in f:
            e = json.loads(line)
            if e.get("type") != "autopilot":
                continue
            if e.get("suppressed"):
                suppressed += 1
            else:
                acted.append(e)
    flaps: List[Dict[str, Any]] = []
    last: Dict[str, tuple] = {}   # key -> (action, decision time)
    for e in acted:
        key = cooldown_key(e["lever"], e.get("target", ""))
        cd = cooldowns.get(e["lever"], 0.0)
        prev = last.get(key)
        if prev and prev[0] != e["name"] and e["t"] - prev[1] < cd:
            flaps.append({"key": key, "from": prev[0], "to": e["name"],
                          "dt": e["t"] - prev[1], "cooldown_s": cd})
        last[key] = (e["name"], e["t"])
    return {"actuated_events": len(acted), "suppressed_events": suppressed,
            "flaps": flaps}


def run_autopilot_scenario(seed: int, outdir: str, replicas: int = 3,
                           rounds: int = 40) -> Dict[str, Any]:
    """Close the loop under fire: the same seeded open-loop load spike +
    mid-spike replica kill hits a STATIC fleet and an AUTOPILOTED fleet,
    and the verdict compares them.

    The schedule (pure function of ``seed``): ~2 requests per 30 s
    virtual round of base load, a spike of 18/round for a seeded span,
    and one seeded replica killed without drain inside the spike.
    Capacity is 2 requests per replica per round (``max_batch=4`` rows,
    one pump each), so the spike overruns the static fleet by design.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``autopilot_sheds_fewer``  — the autopiloted half sheds STRICTLY
      fewer requests than the identically-seeded static half (the
      scale-up lever must actually buy capacity);
    - ``scaled_up_under_spike``  — at least one ``scale_up`` actuated;
    - ``replicas_recovered``     — after the spike the fleet is back to
      exactly ``min_replicas`` ready replicas (scale-down unwound the
      surge, the dead replica stayed dead);
    - ``weights_recovered``      — every ready replica ends at weight
      1.0 and the killed one at 0.0 (the shift lever ramped it out);
    - ``admission_restored``     — the fairness quota is back at its
      baseline (tighten was matched by relax);
    - ``no_flap``                — from the ``autopilot.*`` EVENT STREAM
      alone: no cooldown key actuates two different actions inside one
      cooldown window;
    - ``suppressed_decisions_visible`` — the event stream contains
      considered-but-held decisions (cooldown/window/bounds), proving
      suppression is observable, not silent;
    - ``scores_bit_identical``   — every served score equals the
      single-server reference, through the kill, the scale events and
      the weight shifts;
    - ``steady_compiles_zero``   — the autopiloted half (scale-ups
      included) triggered zero model compiles;
    - ``zero_hard_failures`` / ``all_requests_resolved`` — every request
      either served or shed; nothing lost, nothing wedged.
    """
    import numpy as np

    from mmlspark_tpu.control.autopilot import AutopilotPolicy
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.serve.server import Server

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {"seed": seed, "scenario": "autopilot",
                               "replicas": replicas, "rounds": rounds}

    rng = random.Random(seed ^ 0xA1707)
    spike_start = rng.randint(6, 9)
    spike_len = rng.randint(6, 9)
    kill_round = spike_start + rng.randint(1, 3)
    kill_idx = rng.randrange(replicas)
    base_rate, spike_rate = 2, 18
    # the open-loop schedule: a seeded Poisson flash-crowd trace from the
    # shared load vocabulary (testing/loadgen), bucketed into 30 s rounds
    # — same (seed, trace) replays the identical schedule, which the
    # fingerprint records
    trace_spec = loadgen.Trace(
        duration_s=rounds * 30.0, rate=base_rate / 30.0, shape="spike",
        spike_start_s=spike_start * 30.0, spike_len_s=spike_len * 30.0,
        spike_factor=spike_rate / base_rate)
    schedule = loadgen.generate(trace_spec, seed)
    arrivals = loadgen.bucket_counts(schedule, 30.0, rounds)
    total_requests = len(schedule)
    verdict["schedule"] = {
        "spike_start": spike_start, "spike_len": spike_len,
        "spike_rate": spike_rate, "base_rate": base_rate,
        "kill_round": kill_round, "kill_replica": f"r{kill_idx}",
        "trace": trace_spec.describe(),
        "fingerprint": loadgen.schedule_fingerprint(schedule),
        "total_requests": total_requests}

    model = JaxModel(inputCol="x", outputCol="y", miniBatchSize=8)
    model.set_model("mlp_tabular", input_dim=_DIM, hidden=[16],
                    num_classes=3, seed=seed & 0xFFFF)
    stream = loadgen.feature_rows(total_requests, 2, _DIM, seed)

    # every fleet server (founding AND autopilot-scaled) must load its
    # bucket programs from the shared on-disk cache the reference server
    # populates — that is what makes steady_compiles_zero assertable
    # through scale_up events
    from mmlspark_tpu.utils import config as mmlconfig
    prior_cache = mmlconfig.get("runtime.compile_cache_dir")
    mmlconfig.set("runtime.compile_cache_dir",
                  os.path.join(outdir, "compile_cache"))
    try:
        # ground truth: the full stream on one server, same model object
        ref_server = Server({"chaos": model}, max_batch=4, queue_depth=32)
        try:
            reference = [np.asarray(
                ref_server.submit("chaos", x, timeout=30))
                for x in stream]
        finally:
            ref_server.close()

        policy = AutopilotPolicy(
            tick_s=30.0, min_replicas=replicas,
            max_replicas=replicas + 3, scale_up_queue=3.0,
            scale_down_queue=0.0, scale_cooldown_s=45.0,
            shift_error_rate=0.5, shift_recover_rate=0.05,
            shift_step=0.5, shift_cooldown_s=30.0, admission_factor=0.5,
            admission_floor_frac=0.25, admission_relax_burn=1.0,
            admission_cooldown_s=45.0, window_s=300.0,
            max_actions_per_window=4)

        static = _autopilot_drive(model, stream, arrivals,
                                  kill_round=kill_round,
                                  kill_idx=kill_idx,
                                  replicas=replicas, policy=None)
        events_path = os.path.join(outdir, "autopilot_events.jsonl")
        if os.path.exists(events_path):
            os.remove(events_path)
        auto = _autopilot_drive(model, stream, arrivals,
                                kill_round=kill_round, kill_idx=kill_idx,
                                replicas=replicas, policy=policy,
                                events_path=events_path)
    finally:
        mmlconfig.set("runtime.compile_cache_dir", prior_cache)

    identical = all(
        np.array_equal(auto["scores"][i], reference[i])
        for i in auto["scores"])
    flap = _no_flap(events_path, policy)
    acted = [d for d in auto["decisions"] if not d.get("suppressed")]
    by_action: Dict[str, int] = {}
    for d in acted:
        by_action[d["action"]] = by_action.get(d["action"], 0) + 1
    fin = auto["final"]

    # time-to-recover: first post-spike round with the surge unwound
    spike_end = spike_start + spike_len
    recover_round = next(
        (e["round"] for e in auto["trace"]
         if e["round"] >= spike_end and e["live"] == replicas),
        rounds)
    verdict["static"] = {"shed": static["shed"],
                         "served": len(static["scores"]),
                         "hard_failed": static["hard_failed"],
                         "workload": static["workload"]}
    verdict["autopilot"] = {
        "shed": auto["shed"], "served": len(auto["scores"]),
        "hard_failed": auto["hard_failed"],
        "workload": auto["workload"],
        "decisions": len(auto["decisions"]),
        "actuated": len(acted), "by_action": by_action,
        "suppressed": flap["suppressed_events"],
        "events_path": events_path,
        "time_to_recover_s": (recover_round - spike_end) * 30.0,
        "final": fin}
    verdict["flaps"] = flap["flaps"]

    invariants = {
        "autopilot_sheds_fewer": auto["shed"] < static["shed"],
        "scaled_up_under_spike": by_action.get("scale_up", 0) >= 1,
        "replicas_recovered": fin["live_ready"] == replicas,
        "weights_recovered": (
            fin["ready_weights"]
            and all(w == 1.0 for w in fin["ready_weights"].values())
            and all(w == 0.0 for w in fin["dead_weights"].values())),
        "admission_restored":
            fin["capacity_rows"] == fin["baseline_rows"],
        "no_flap": not flap["flaps"],
        "suppressed_decisions_visible": flap["suppressed_events"] >= 1,
        "scores_bit_identical":
            identical and len(auto["scores"]) > 0,
        "steady_compiles_zero": fin["compiles"] == 0,
        "zero_hard_failures": (auto["hard_failed"] == 0
                               and static["hard_failed"] == 0),
        "all_requests_resolved": (
            auto["unresolved"] == 0 and static["unresolved"] == 0
            and len(auto["scores"]) + auto["shed"] == total_requests),
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos autopilot verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.autopilot.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


# -- elastic scenario --------------------------------------------------------

def run_elastic_scenario(seed: int, outdir: str, replicas: int = 2,
                         requests: int = 12) -> Dict[str, Any]:
    """SIGKILL a worker mid autopilot-driven scale-up; elasticity holds.

    The supervised-elasticity rung above ``host`` (real-process restart)
    and ``autopilot`` (in-process scale decisions): here the autopilot's
    ``scale_up`` actuates :meth:`~mmlspark_tpu.serve.supervisor.
    Supervisor.add_slot` — a REAL new ``mmlspark-tpu serve`` process —
    and the seeded kill lands while that spawn is still in flight.

    **Phase 1 (warm):** ``replicas`` supervised workers over a shared
    ``runtime.compile_cache_dir`` take a seeded stream through the
    Router, populating the disk cache every later incarnation loads
    from.

    **Phase 2 (elastic scale-up under fire):** an autopilot tick over
    :class:`~mmlspark_tpu.serve.fleet.ProcessFleet` decides ``scale_up``
    (``live < min_replicas``) and spawns ``w<replicas>``; the moment the
    new child has a pid, the seeded victim — the half-spawned slot
    itself, or an existing worker, a coin-flip of the seed — is
    SIGKILLed, with concurrent retrying traffic in flight the whole
    time. The ordinary supervision loop must reconcile desired == live
    with every slot ready (the half-spawned slot either completes
    registration or is reaped and respawned — never a zombie), and the
    scaled-up worker must come up WARM: ``compile_cache_hits > 0`` and
    ``compile_cache_misses == 0`` on its own ``/metrics``.

    **Phase 3 (elastic scale-down):** a second autopilot (its own event
    sidecar) decides ``scale_down`` on the idle fleet; the highest slot
    drains through :meth:`~mmlspark_tpu.serve.supervisor.Supervisor.
    retire_slot` and leaves the router rotation.

    **Phase 4 (replay fidelity):** both pilots' event sidecars are fed
    back through :mod:`mmlspark_tpu.control.replay` — replaying the
    recorded signals under the recorded policy must reproduce each
    recorded decision list byte for byte.

    Invariants (verdict JSON, ``outdir/chaos_verdict.json``):

    - ``zero_failed_requests``  — every streamed request scored despite
      the kill landing mid-scale-up;
    - ``scale_up_actuated``     — exactly one actuated ``scale_up``,
      no actuation error, new slot named ``w<replicas>``;
    - ``kill_landed``           — the seeded SIGKILL hit a live pid;
    - ``desired_equals_live``   — the fleet reconciled to
      ``replicas + 1`` workers, all ready, none mid-spawn;
    - ``killed_slot_respawned`` — the victim slot really respawned;
    - ``no_zombie_in_rotation`` — router rotation == supervised slots,
      every weight restored to 1.0;
    - ``warm_scale_up``         — the new worker loaded programs from
      the shared cache (``compile_cache_hits > 0``);
    - ``steady_compiles_zero``  — and compiled NOTHING
      (``compile_cache_misses == 0``);
    - ``scale_down_retired``    — one actuated ``scale_down`` retired
      the new slot; desired == live == ``replicas``; slot gone from
      rotation;
    - ``replay_fidelity``       — both recorded decision sequences
      replay byte-identical under their recorded policies;
    - ``no_unhandled_exceptions``.

    The ``schedule`` (kill mode + victim) is a pure function of ``seed``.
    """
    import threading
    import time as _time
    import urllib.request

    import numpy as np

    from mmlspark_tpu.control import replay as _replay
    from mmlspark_tpu.control.autopilot import Autopilot, AutopilotPolicy
    from mmlspark_tpu.observability.aggregate import parse_prometheus_text
    from mmlspark_tpu.reliability.retry import RetryPolicy
    from mmlspark_tpu.serve.fleet import ProcessFleet
    from mmlspark_tpu.serve.router import Router
    from mmlspark_tpu.serve.supervisor import ProcessSpawner, Supervisor
    from mmlspark_tpu.utils import config as mmlconfig

    os.makedirs(outdir, exist_ok=True)
    events_dir = os.path.join(outdir, "events")
    cache_dir = os.path.join(outdir, "compile-cache")
    os.makedirs(events_dir, exist_ok=True)
    os.makedirs(cache_dir, exist_ok=True)
    errors: List[str] = []
    verdict: Dict[str, Any] = {"seed": seed, "scenario": "elastic",
                               "replicas": replicas, "requests": requests}

    new_name = f"w{replicas}"
    rng = random.Random(seed ^ 0xE1A5)
    kill_new = rng.random() < 0.5
    kill_name = new_name if kill_new else f"w{rng.randrange(replicas)}"
    verdict["schedule"] = {
        "kill_replica": kill_name,
        "kill_mode": "half_spawned_slot" if kill_new
        else "existing_worker"}

    model_spec = json.dumps({"input_dim": _DIM, "hidden": [16],
                             "num_classes": 3, "seed": seed & 0xFFFF})
    model_flag = f"chaos=mlp_tabular:{model_spec}"

    # each autopilot phase records to its OWN sidecar so phase 4 can
    # fidelity-check one (policy, ticks, decisions) triple per log
    prior_events = mmlconfig.get("observability.events_path")
    up_log = os.path.join(events_dir, f"pilot-up-{os.getpid()}.jsonl")
    down_log = os.path.join(events_dir, f"pilot-down-{os.getpid()}.jsonl")
    mmlconfig.set("observability.events_path", up_log)

    names = [f"w{i}" for i in range(replicas)]
    spawner = ProcessSpawner([model_flag], events_dir=events_dir,
                             compile_cache_dir=cache_dir,
                             extra_args=["--max-batch", "4",
                                         "--queue-depth", "32"])
    sup = Supervisor(spawner, names, min_uptime_s=0.5, base_delay_s=0.05,
                     max_delay_s=0.5, breaker_failures=3,
                     breaker_reset_s=30.0)
    client = RetryPolicy(max_attempts=8, base_delay=0.2, max_delay=2.0,
                         jitter=0.0, name="chaos.elastic.client",
                         seed=seed)
    stream = loadgen.feature_rows(requests, 2, _DIM, seed)
    warm_n = max(2, requests // 3)

    served = 0
    failed = 0
    killed_pid: Optional[int] = None
    cache_hits = -1.0
    cache_misses = -1.0
    up_decisions: List[Dict[str, Any]] = []
    down_decisions: List[Dict[str, Any]] = []
    stats_up: Dict[str, Any] = {}
    stats_down: Dict[str, Any] = {}
    rotation_up: Dict[str, Any] = {}
    rotation_down: Dict[str, Any] = {}
    reconciled = False
    router = None
    try:
        sup.start()
        down = [n for n, s in sup.stats()["replicas"].items()
                if not s["running"]]
        if down:
            raise ChaosError(f"workers failed to start: {down} "
                             f"(see {events_dir}/worker-*.log)")
        router = Router(sup.replicas, failover_attempts=replicas + 2)
        sup.attach_router(router)
        router.probe()
        sup.start_monitor(0.05)

        # phase 1: warm the shared compile cache through the original
        # workers so the scaled-up incarnation can come up warm
        for i, x in enumerate(stream[:warm_n]):
            try:
                y = np.asarray(client.call(router.submit, "chaos", x))
                if y.shape[0] == 2:
                    served += 1
                else:
                    failed += 1
                    errors.append(f"request {i}: wrong shape {y.shape}")
            except Exception as e:
                failed += 1
                errors.append(f"request {i}: {type(e).__name__}: {e}")

        # phase 2: one autopilot tick decides scale_up (live < min) and
        # actuates add_slot; the seeded victim is SIGKILLed the moment
        # the new child has a pid, under concurrent retrying traffic
        policy_up = AutopilotPolicy(
            tick_s=1.0, min_replicas=replicas + 1,
            max_replicas=replicas + 2, scale_up_queue=1e6,
            scale_down_queue=0.0, scale_cooldown_s=0.0)
        pilot_up = Autopilot(ProcessFleet(sup, router), policy=policy_up)

        kill_box: Dict[str, Any] = {"pid": None}

        def _killer() -> None:
            deadline = _time.monotonic() + 60.0
            while _time.monotonic() < deadline:
                st = sup.stats()["replicas"].get(new_name)
                if st is not None and st["pid"] is not None:
                    pid = sup.kill_replica(  # lint: allow-actuate
                        kill_name)
                    if pid is not None:
                        kill_box["pid"] = pid
                        return
                _time.sleep(0.005)

        traffic_results: List[Optional[str]] = []

        def _traffic() -> None:
            for i, x in enumerate(stream[warm_n:], warm_n):
                try:
                    y = np.asarray(client.call(router.submit,
                                               "chaos", x))
                    traffic_results.append(
                        None if y.shape[0] == 2
                        else f"request {i}: wrong shape {y.shape}")
                except Exception as e:
                    traffic_results.append(
                        f"request {i}: {type(e).__name__}: {e}")

        killer = threading.Thread(target=_killer, daemon=True)
        traffic = threading.Thread(target=_traffic, daemon=True)
        killer.start()
        traffic.start()
        up_decisions = pilot_up.tick()   # blocks through add_slot
        killer.join(60.0)
        traffic.join(120.0)
        killed_pid = kill_box["pid"]
        if killed_pid is None:
            errors.append("seeded kill never landed on a live pid")
        if traffic.is_alive():
            errors.append("traffic thread wedged")
        for r in traffic_results:
            if r is None:
                served += 1
            else:
                failed += 1
                errors.append(r)

        # reconcile: the supervision loop must close the desired/live
        # gap — every slot ready, nothing mid-spawn, no zombie
        deadline = _time.monotonic() + 120.0
        while _time.monotonic() < deadline:
            st = sup.stats()
            if (st["desired_replicas"] == replicas + 1
                    and st["live_replicas"] == replicas + 1
                    and st["spawns_in_flight"] == 0
                    and all(r["ready_spawns"] == r["spawns"]
                            and r["ready_spawns"] >= 1
                            for r in st["replicas"].values())):
                reconciled = True
                stats_up = st
                break
            _time.sleep(0.05)
        if not reconciled:
            stats_up = sup.stats()
            errors.append(f"fleet never reconciled to {replicas + 1} "
                          f"ready workers: {stats_up['replicas']}")
        rotation_up = {n: dict(r) for n, r in
                       router.stats()["replicas"].items()}

        # warm check: score directly on the scaled-up worker (forces
        # its lazy program build), then read its own /metrics — a warm
        # scale-up LOADS programs from the shared cache, compiles none
        if reconciled:
            rep = sup.replica(new_name)
            y = np.asarray(rep.submit("chaos", stream[0]))
            if y.shape[0] != 2:
                errors.append(f"new slot: wrong shape {y.shape}")
            with urllib.request.urlopen(f"{rep.addr}/metrics",
                                        timeout=10) as resp:
                parsed = parse_prometheus_text(resp.read().decode())
            cache_hits = float(
                parsed.get("compile_cache_hits", {}).get("value", 0.0))
            cache_misses = float(
                parsed.get("compile_cache_misses", {}).get("value", 0.0))

            # phase 3: a second autopilot (fresh cooldowns, its own
            # sidecar) sees the idle fleet and retires the extra slot
            mmlconfig.set("observability.events_path", down_log)
            policy_down = AutopilotPolicy(
                tick_s=1.0, min_replicas=replicas,
                max_replicas=replicas + 2, scale_up_queue=1e6,
                scale_down_queue=0.0, scale_cooldown_s=0.0)
            pilot_down = Autopilot(ProcessFleet(sup, router),
                                   policy=policy_down)
            down_decisions = pilot_down.tick()  # blocks through retire
            stats_down = sup.stats()
            rotation_down = {n: dict(r) for n, r in
                             router.stats()["replicas"].items()}
    except Exception as e:
        errors.append(f"elastic scenario: {type(e).__name__}: {e}")
    finally:
        if router is not None:
            try:
                router.close()
            except Exception as e:
                _LOG.debug("router close failed: %s", e)
        sup.shutdown(reason="chaos elastic scenario complete")

    # phase 4: each pilot's sidecar must replay byte-identical under
    # its recorded policy — the counterfactual-replay contract, checked
    # against a REAL process-elasticity run rather than a synthetic log
    replay_fidelity: Dict[str, Any] = {}
    replay_ok = True
    for label, p in (("scale_up", up_log), ("scale_down", down_log)):
        try:
            log = _replay.load_log([p]) if os.path.exists(p) else \
                {"policy": None, "ticks": [], "decisions": []}
            if not log["ticks"] or log["policy"] is None:
                replay_fidelity[label] = {"identical": False,
                                          "error": "no recorded ticks"}
                replay_ok = False
                continue
            pol = _replay.policy_from_fields(log["policy"])
            fid = _replay.fidelity_check(
                log["decisions"],
                _replay.replay_decisions(log["ticks"], pol))
            replay_fidelity[label] = {"identical": fid["identical"],
                                      "decisions": fid["recorded"]}
            if not fid["identical"]:
                replay_ok = False
                replay_fidelity[label]["first_diff"] = fid["first_diff"]
        except Exception as e:
            replay_fidelity[label] = {
                "identical": False,
                "error": f"{type(e).__name__}: {e}"}
            replay_ok = False

    actuated_up = [d for d in up_decisions
                   if d["action"] == "scale_up" and not d["suppressed"]]
    actuated_down = [d for d in down_decisions
                     if d["action"] == "scale_down"
                     and not d["suppressed"]]
    verdict["schedule"]["killed_pid"] = killed_pid
    verdict["elastic"] = {
        "served": served, "failed": failed,
        "spawn_to_ready_ms": stats_up.get("spawn_to_ready_ms", {}),
        "compile_cache_hits": cache_hits,
        "compile_cache_misses": cache_misses,
        "supervisor_after_scale_up": stats_up.get("replicas", {}),
        "rotation_after_scale_up": sorted(rotation_up),
        "rotation_after_scale_down": sorted(rotation_down),
        "events_dir": events_dir}
    verdict["replay"] = replay_fidelity

    invariants = {
        "zero_failed_requests": failed == 0 and served == requests,
        "scale_up_actuated": (
            len(actuated_up) == 1
            and actuated_up[0].get("replica") == new_name
            and "error" not in actuated_up[0]),
        "kill_landed": killed_pid is not None,
        "desired_equals_live": reconciled,
        "killed_slot_respawned": (
            stats_up.get("replicas", {}).get(kill_name, {})
            .get("spawns", 0) >= 2),
        "no_zombie_in_rotation": (
            sorted(rotation_up) == sorted(stats_up.get("replicas", {}))
            and bool(rotation_up)
            and all(r.get("weight") == 1.0
                    for r in rotation_up.values())),
        "warm_scale_up": cache_hits > 0,
        "steady_compiles_zero": cache_misses == 0,
        "scale_down_retired": (
            len(actuated_down) == 1
            and actuated_down[0].get("target") == new_name
            and "error" not in actuated_down[0]
            and stats_down.get("desired_replicas") == replicas
            and stats_down.get("live_replicas") == replicas
            and new_name not in rotation_down),
        "replay_fidelity": replay_ok,
        "no_unhandled_exceptions": not errors,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    # restore the prior event sink AFTER the verdict facts are gathered
    mmlconfig.set("observability.events_path", prior_events)

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos elastic verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.elastic.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict


# -- the scenario ------------------------------------------------------------

def run_scenario(seed: int, outdir: str, total_steps: int = 8,
                 save_every: int = 2, requests: int = 12) -> Dict[str, Any]:
    """Train-kill-resume-then-serve under a seeded fault schedule; returns
    (and writes to ``outdir/chaos_verdict.json``) the verdict dict."""
    from mmlspark_tpu.utils import config as mmlconfig

    os.makedirs(outdir, exist_ok=True)
    errors: List[str] = []
    # flush interval deliberately COPRIME with save_every: the device
    # metrics ring's flush boundary lands mid-checkpoint-interval, so the
    # bit-identical-resume invariant proves the ring is pure telemetry —
    # where the kill falls relative to a flush must not change the stream
    flush_steps = max(3, save_every * 2 + 1)
    verdict: Dict[str, Any] = {"seed": seed, "total_steps": total_steps,
                               "save_every": save_every,
                               "metrics_flush_steps": flush_steps}

    batch_fn = _batch_fn(seed)
    prior_flush = mmlconfig.get("train.metrics_flush_steps")
    mmlconfig.set("train.metrics_flush_steps", flush_steps)
    chaos_dir = os.path.join(outdir, "chaos")
    plan = generate_train_plan(seed, total_steps)
    bit_identical = False
    final_loads = False
    restarts = 0
    try:
        ref_state, _ = _run_loop_to_completion(
            os.path.join(outdir, "ref"), batch_fn, total_steps, save_every,
            max_restarts=0)
        with plan:
            state, restarts = _run_loop_to_completion(
                chaos_dir, batch_fn, total_steps, save_every,
                max_restarts=len(plan.specs) + 2)
        bit_identical = _bit_identical(state, ref_state)
        final_loads = _final_checkpoint_loads(chaos_dir, state, total_steps)
    except Exception as e:
        errors.append(f"train phase: {type(e).__name__}: {e}")
    finally:
        mmlconfig.set("train.metrics_flush_steps", prior_flush)
    verdict["train"] = {"restarts": restarts, "faults": plan.triggered,
                        "quarantined": _quarantined(chaos_dir)}

    serve_facts: Dict[str, Any] = {}
    try:
        serve_facts = _serve_phase(seed, requests, errors)
    except Exception as e:
        errors.append(f"serve phase: {type(e).__name__}: {e}")
    verdict["serve"] = serve_facts

    invariants = {
        "params_bit_identical": bit_identical,
        "final_checkpoint_loads": final_loads,
        "server_stays_live": bool(serve_facts)
        and serve_facts.get("healthz_bad", 1) == 0
        and serve_facts.get("healthz_ok", 0) > 0,
        "no_unhandled_exceptions": not errors,
    }
    verdict["invariants"] = invariants
    verdict["errors"] = errors
    verdict["passed"] = all(invariants.values())

    path = os.path.join(outdir, VERDICT_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    _LOG.info("chaos verdict (%s): %s", path,
              "PASS" if verdict["passed"] else "FAIL")
    if not verdict["passed"]:
        # a red verdict ships its own forensics: the last-N telemetry
        # events land next to the verdict even with events_path unset
        from mmlspark_tpu.observability import flightrec
        dumped = flightrec.dump(
            reason=f"chaos.red.seed{seed}",
            path=os.path.join(outdir, "chaos_flightrec.jsonl"))
        if dumped:
            _LOG.error("chaos: flight recorder dumped to %s", dumped)
    return verdict
