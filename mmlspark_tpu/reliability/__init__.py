"""Reliability subsystem: retry/backoff, deterministic fault injection,
crash-safe training (ISSUE 1), and the liveness layer — watchdog,
preemption-aware shutdown, circuit breakers, seeded chaos (ISSUE 5).

- :mod:`mmlspark_tpu.reliability.retry` — :class:`RetryPolicy`, the shared
  exponential-backoff primitive (deterministic jitter, deadline, retryable
  predicate, ``Retry-After`` honor);
- :mod:`mmlspark_tpu.reliability.faults` — :func:`fault_site` hooks +
  :class:`FaultPlan`, bit-for-bit reproducible failure injection;
- :mod:`mmlspark_tpu.reliability.resilient` — :class:`ResilientTrainLoop`,
  the crash-safe trainer/checkpointer driver with corrupt-checkpoint
  fallback and preemption-drain exit;
- :mod:`mmlspark_tpu.reliability.watchdog` — heartbeat registry +
  :class:`Watchdog` stall detector with all-thread stack dumps;
- :mod:`mmlspark_tpu.reliability.preemption` — SIGTERM/SIGINT ->
  process-wide :class:`PreemptionSignal`, polled by train/serve loops;
- :mod:`mmlspark_tpu.reliability.breaker` — :class:`CircuitBreaker`
  (closed/open/half-open) above the retry layer;
- :mod:`mmlspark_tpu.reliability.chaos` — seeded randomized fault
  schedules + the ``mmlspark-tpu chaos`` train-kill-resume-serve scenario;
- :mod:`mmlspark_tpu.reliability.lint` — the static gate behind
  ``mmlspark-tpu check`` (urlopen timeouts, swallowed excepts, print,
  thread daemon, queue bounds, signal-handler centralization).
"""
from mmlspark_tpu.reliability.breaker import (
    CircuitBreaker, CircuitOpen, breaker_for, reset_breakers,
)
from mmlspark_tpu.reliability.chaos import (
    ChaosError, generate_serve_plan, generate_train_plan, run_scenario,
)
from mmlspark_tpu.reliability.faults import (
    FaultPlan, FaultSpec, InjectedFault, active_plan, fault_site,
)
from mmlspark_tpu.reliability.preemption import (
    PreemptionSignal, install_handlers, preempted, preemption_reason,
    request_preemption,
)
from mmlspark_tpu.reliability.resilient import ResilientTrainLoop
from mmlspark_tpu.reliability.retry import (
    Attempt, RetryPolicy, default_retryable,
)
from mmlspark_tpu.reliability.watchdog import Heartbeat, Stall, Watchdog
from mmlspark_tpu.reliability.watchdog import register as register_heartbeat

__all__ = [
    "Attempt", "ChaosError", "CircuitBreaker", "CircuitOpen", "FaultPlan",
    "FaultSpec", "Heartbeat", "InjectedFault", "PreemptionSignal",
    "ResilientTrainLoop", "RetryPolicy", "Stall", "Watchdog", "active_plan",
    "breaker_for", "default_retryable", "fault_site",
    "generate_serve_plan", "generate_train_plan", "install_handlers",
    "preempted", "preemption_reason", "register_heartbeat",
    "request_preemption", "reset_breakers", "run_scenario",
]
