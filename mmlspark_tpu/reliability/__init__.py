"""Reliability subsystem: retry/backoff, deterministic fault injection, and
crash-safe training (ISSUE 1).

- :mod:`mmlspark_tpu.reliability.retry` — :class:`RetryPolicy`, the shared
  exponential-backoff primitive (deterministic jitter, deadline, retryable
  predicate);
- :mod:`mmlspark_tpu.reliability.faults` — :func:`fault_site` hooks +
  :class:`FaultPlan`, bit-for-bit reproducible failure injection;
- :mod:`mmlspark_tpu.reliability.resilient` — :class:`ResilientTrainLoop`,
  the crash-safe trainer/checkpointer driver with corrupt-checkpoint
  fallback;
- :mod:`mmlspark_tpu.reliability.lint` — the static ``urlopen``-timeout /
  swallowed-except gate behind ``mmlspark-tpu check``.
"""
from mmlspark_tpu.reliability.faults import (
    FaultPlan, FaultSpec, InjectedFault, active_plan, fault_site,
)
from mmlspark_tpu.reliability.resilient import ResilientTrainLoop
from mmlspark_tpu.reliability.retry import (
    Attempt, RetryPolicy, default_retryable,
)

__all__ = [
    "Attempt", "FaultPlan", "FaultSpec", "InjectedFault", "RetryPolicy",
    "ResilientTrainLoop", "active_plan", "default_retryable", "fault_site",
]
