"""Static reliability lint: the two bug classes this subsystem exists for.

Rule 1 — ``urlopen(...)`` without an explicit ``timeout=``: a stalled
connection hangs the caller forever (the pre-reliability downloader did
exactly this on MANIFEST and model fetches).

Rule 2 — ``except:`` (bare) or ``except Exception: pass`` / ``except
BaseException: pass``: a swallowed error turns a crash into silent
corruption — the failure mode the fault-injection harness exists to make
reproducible, and the one a reliability subsystem must not ship.

Rule 3 — ``print(...)`` in library code: stdout bypasses the framework
logger tree AND the telemetry layer (observability/), so the output is
invisible to log levels, event logs, and run reports. Route through
``get_logger`` or ``observability.events.emit``. CLI entry points whose
CONTRACT is stdout (e.g. ``mmlspark-tpu info`` printing JSON) mark the
line with ``# lint: allow-print``.

Rule 4 — ``threading.Thread(...)`` without an explicit ``daemon=``: the
default (inherit the creator's daemon flag) decides whether interpreter
shutdown BLOCKS on the thread, and an implicit choice is how a serving
executor or prefetch worker quietly turns Ctrl-C into a hang. Every
library-code thread states its shutdown contract at the constructor.

Rule 5 — ``queue.Queue(...)`` without an explicit ``maxsize=``: the
default is unbounded, which silently removes backpressure — a stalled
consumer (a wedged device, a slow decode stage) lets the producer buffer
the whole stream in host memory instead of blocking. Every library-code
queue states its bound; a deliberate unbounded queue writes ``maxsize=0``
so the choice is greppable.

Rule 6 — ``signal.signal(...)`` outside ``reliability/preemption.py``:
signal handlers are PROCESS-GLOBAL and last-installer-wins, so a handler
registered in some corner of the library silently clobbers the
preemption layer's SIGTERM->clean-checkpoint path. All handler
installation goes through ``reliability.preemption``; intentional
exceptions mark the line ``# lint: allow-signal``.

Rule 7 — raw ``jax.device_get(...)`` / ``block_until_ready(...)`` outside
``observability/syncs.py``: every one is a host<->device round trip the
sync accounter cannot see, which silently falsifies the ROADMAP item-4
"syncs per step" scoreboard. Route through ``syncs.device_get`` /
``syncs.block_until_ready`` (calls whose receiver mentions ``sync`` are
recognized as the wrappers); deliberate raw syncs mark the line
``# lint: allow-sync``.

Rule 8 — direct replica calls (``<x>replica.submit/submit_async/
submit_many/score(...)``) in ``serve/`` outside ``serve/router.py``: a
cross-replica call that bypasses the router bypasses its circuit
breaker, failover retry, and fairness accounting — the exact wrappers
the fleet layer exists to enforce — so one unrouted call site quietly
loses a request when its replica dies. All cross-replica traffic goes
through the Router; deliberate direct calls (a rollout warming a
drained replica) mark the line ``# lint: allow-direct-replica``.

Rule 9 — compile sites (``<x>.lower(...).compile()`` or ``jax.jit(...)``)
in ``serve/`` outside ``compile_cache.py``: an unsanctioned compile in
the serving layer bypasses the persistent AOT program cache, so every
replica cold-start and rollout warm pays the full XLA compile the cache
exists to kill — and the ``compile_cache.*`` hit/miss counters stop
telling the truth. All serve-side compilation goes through
``compile_cache.load_or_compile``; deliberate exceptions mark the line
``# lint: allow-compile``.

Rule 10 — device allocations (``jnp.zeros/ones/full/empty`` and their
``_like`` forms, ``device_put``) in ``serve/`` outside
``serve/kvcache.py``: serving-side HBM is a budgeted arena — params under
the registry's ``runtime.device_cache_mb`` LRU, decode KV pages under the
``KVCacheManager`` free list — and an ad-hoc allocation is invisible to
both accountants, so occupancy gauges and eviction decisions quietly lie
until the real device OOMs. All serve-side device memory goes through
``KVCacheManager`` or ``ModelRegistry``; deliberate exceptions mark the
line ``# lint: allow-alloc``.

Rule 11 — device-byte arithmetic (``.nbytes`` / ``.itemsize``) in
``serve/`` outside ``observability/memory.py``: HBM accounting lives in
one ledger so totals stay mutually consistent — a private size formula
in a serve/ module drifts from the ledger's (padding, dtype, layout) and
the occupancy gauges stop summing. Size arithmetic goes through
``memory.nbytes_of`` / ``memory.param_bytes``; deliberate exceptions
mark the line ``# lint: allow-bytes``.

Rule 12 — process management (``subprocess.Popen(...)``, ``os.kill(...)``,
``os.waitpid(...)``) outside ``serve/supervisor.py`` /
``serve/launcher.py``: child processes need exactly one owner per layer
— a worker spawned (or signalled) from some corner of the library is
invisible to the supervisor's restart/backoff/breaker machinery and its
drain path (and a per-host fleet started outside the launcher is
invisible to its stop/drain fan-in), so it leaks on shutdown and
double-restarts under chaos. All process lifecycle goes through the
supervisor (workers) or the host launcher (per-host fleets); deliberate
exceptions mark the line ``# lint: allow-process``.

Rule 13 — quantization arithmetic (``.astype(np.int8)`` /
``127``-range scale math) in ``serve/`` outside ``serve/kvcache.py``:
the int8 KV arena keeps ONE quantization scheme (symmetric per-row
absmax, ``quantize_rows``/``dequantize_rows``) so stored blocks and
every program that reads them agree bit-for-bit — an open-coded cast or
scale formula in a program builder silently diverges from the arena's
(rounding mode, clip range, scale epsilon) and decodes garbage KV.
Quant math goes through the ``kvcache`` helpers; deliberate exceptions
mark the line ``# lint: allow-quant``.

Rule 14 — ``PartitionSpec`` / ``NamedSharding`` construction (including
the ``P(...)`` alias) outside ``parallel/sharding.py`` /
``parallel/mesh.py``: placement decisions live in ONE home so the 2-D
``(data, model)`` mesh mode can change topology without auditing every
module — an open-coded spec in a trainer or the serving lane silently
disagrees with the param-sharding rules (axis names, divisibility
clamps) and either crashes at dispatch or replicates a tensor the mesh
was supposed to split. Route through the sharding helpers
(``param_shardings``, ``replicated``, ``kv_arena_sharding``,
``epoch_cache_sharding``, ...); genuinely local spec construction (e.g.
``shard_map`` in/out specs naming module-private axes) marks the line
``# lint: allow-spec``.

Rule 15 — fleet actuator calls (``set_weight`` / ``kill_replica`` /
``scale_up`` / ``scale_down`` / ``add_replica`` / ``remove_replica`` /
``set_capacity`` / ``reset_breaker`` / ``add_slot`` / ``retire_slot`` /
``launch_host`` / ``stop_host`` / ``reshard`` / ``reshard_to``, plus
``.kill()`` on a replica/fleet receiver) outside ``control/`` and the
existing
rollout/supervisor/launcher homes: every control action must stay
attributable —
an actuation from a random module is invisible to the autopilot's
decision telemetry (``autopilot.*`` events), so a post-mortem can no
longer explain why a weight moved or a replica died. Route actions
through ``control.autopilot`` (or the fleet/supervisor machinery that
owns them); deliberate out-of-band actuations (a chaos scenario's kill,
an operator script) mark the line ``# lint: allow-actuate``.

Rule 16 — hand-rolled load construction in ``reliability/chaos.py``:
a private ``default_rng(...)`` generator, or ``randrange``/``randint``
draws inside a comprehension, is how a scenario builds its own request
stream — payloads and prompts that exist outside the shared, seeded
workload vocabulary (``testing/loadgen``: ``generate`` schedules,
``feature_rows``, ``token_prompts``, ``PromptPopulation``) and
therefore outside the byte-identical replay contract the open-loop
rework established. Scenarios draw load ONLY from loadgen; a
deliberate hand-rolled stream marks the line
``# lint: allow-handload``.

Rule 17 — embedding gather/scatter arithmetic (``segment_sum`` /
``scatter_add`` calls) or id-bucketing math (``ids // rows_per_shard``,
``id % num_shards`` — floor-div/mod pairing an id operand with a shard
operand) outside ``embed/tables.py``: the fused all-to-all lookup and
the sparse scatter-add gradient are bit-identical to the unsharded
reference ONLY because every step (bucket capacity, stable sort,
segment order) lives in one audited home — a private re-implementation
in a model or serving module silently diverges in association order
and breaks the recommender's cross-topology bit-identity contract.
Route through ``embed.tables`` (``make_bag_lookup``,
``bag_lookup_reference``, ``sparse_table_grads``); deliberate
exceptions mark the line ``# lint: allow-embed``.

Rule 18 — consistent-hash / digest-scoring arithmetic outside
``serve/affinity.py``: a ring point minted from a truncated
cryptographic digest (``int(sha256(...).hexdigest()[:16], 16)``) or
vnode/ring modular bucketing math is placement policy the WHOLE fleet
must agree on — a private ring in a scenario, bench, or second serving
module assigns the same session key to a different replica than the
router does, and the "N replicas, one KV cache" contract silently
splits. The scoring/ring home is ``serve.affinity``
(``ConsistentHashRing``, ``score_digest``); deliberate exceptions mark
the line ``# lint: allow-affinity``.

Shared core for ``tools/check_reliability.py`` (standalone CLI),
``mmlspark-tpu check`` (installed CLI), and the in-pytest gate
(tests/test_reliability_lint.py) — same single source of truth pattern as
``tools/namecheck.py``.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Sequence, Union

# The canonical scope: production code only. tests/ legitimately use broad
# excepts in fixtures; examples/ and tools/ are not on the serving path.
DEFAULT_ROOTS = ["mmlspark_tpu"]


def _is_urlopen(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Name) and f.id == "urlopen") or \
        (isinstance(f, ast.Attribute) and f.attr == "urlopen")


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Name) and f.id == "Thread") or \
        (isinstance(f, ast.Attribute) and f.attr == "Thread")


def _is_queue_ctor(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Name) and f.id == "Queue") or \
        (isinstance(f, ast.Attribute) and f.attr == "Queue")


def _catches_everything(node: ast.expr) -> bool:
    """Does this except clause name Exception/BaseException (alone or in a
    tuple)?"""
    names = node.elts if isinstance(node, ast.Tuple) else [node]
    return any(isinstance(n, ast.Name)
               and n.id in ("Exception", "BaseException") for n in names)


_ALLOW_PRINT = "# lint: allow-print"
_ALLOW_SIGNAL = "# lint: allow-signal"
_ALLOW_SYNC = "# lint: allow-sync"
# the ONE module allowed to install process-global signal handlers
_SIGNAL_HOME = "reliability/preemption.py"
# the ONE module allowed to call the raw blocking primitives
_SYNC_HOME = "observability/syncs.py"
_SYNC_CALLS = ("device_get", "block_until_ready")
_ALLOW_REPLICA = "# lint: allow-direct-replica"
# the ONE serve/ module allowed to call replicas directly (it IS the
# breaker/retry wrapper layer)
_REPLICA_HOME = "serve/router.py"
_REPLICA_CALLS = ("submit", "submit_async", "submit_many", "score")
_ALLOW_COMPILE = "# lint: allow-compile"
# the ONE module allowed to compile serve-side programs (it IS the
# persistent AOT cache seam)
_COMPILE_HOME = "compile_cache.py"
_ALLOW_ALLOC = "# lint: allow-alloc"
# the ONE serve/ module allowed to allocate device memory directly (it IS
# the KV arena accountant; params are the registry's job)
_ALLOC_HOME = "serve/kvcache.py"
_ALLOC_CALLS = ("zeros", "ones", "full", "empty", "zeros_like",
                "ones_like", "full_like", "empty_like")
_ALLOW_BYTES = "# lint: allow-bytes"
# the ONE module allowed to do device-byte arithmetic (it IS the ledger)
_BYTES_HOME = "observability/memory.py"
_BYTES_ATTRS = ("nbytes", "itemsize")
_ALLOW_PROCESS = "# lint: allow-process"
# the modules allowed to manage OS processes: the supervisor (worker
# lifecycle on one host) and the host launcher (fleet-per-host fan-out)
_PROCESS_HOMES = ("serve/supervisor.py", "serve/launcher.py")
_PROCESS_OS_CALLS = ("kill", "waitpid")
_ALLOW_QUANT = "# lint: allow-quant"
# the ONE serve/ module allowed to open-code KV quantization arithmetic
# (it owns quantize_rows/dequantize_rows — the single scheme every
# arena reader and writer must share)
_QUANT_HOME = "serve/kvcache.py"
_ALLOW_SPEC = "# lint: allow-spec"
# the modules allowed to construct placement specs directly (they ARE the
# sharding policy: the rule table, the topology resolver)
_SPEC_HOMES = ("parallel/sharding.py", "parallel/mesh.py")
_SPEC_CTORS = ("PartitionSpec", "NamedSharding")
_ALLOW_ACTUATE = "# lint: allow-actuate"
# the modules allowed to move fleet levers: the decision loop itself,
# and the serve/ machinery that OWNS each lever (router weights, fleet
# scale/rollout, supervisor restart + slot elasticity, host launcher)
_ACTUATE_HOMES = ("control/autopilot.py", "serve/router.py",
                  "serve/fleet.py", "serve/supervisor.py",
                  "serve/launcher.py")
_ACTUATE_CALLS = ("set_weight", "kill_replica", "scale_up", "scale_down",
                  "add_replica", "remove_replica", "set_capacity",
                  "reset_breaker", "add_slot", "retire_slot",
                  "launch_host", "stop_host", "reshard", "reshard_to")
_ALLOW_HANDLOAD = "# lint: allow-handload"
# the ONE module chaos scenarios may construct load through (schedules,
# feature streams, token prompts, prefix populations — all seeded,
# all replayable)
_HANDLOAD_HOME = "testing/loadgen.py"
# Rule 16 scope: the chaos scenario harness only
_HANDLOAD_SCOPE = "reliability/chaos.py"
_HANDLOAD_DRAWS = ("randrange", "randint")
_ALLOW_EMBED = "# lint: allow-embed"
# the ONE module allowed to open-code embedding gather/scatter and
# id-bucketing arithmetic (it IS the fused lookup / sparse-grad home
# whose association order defines the bit-identity contract)
_EMBED_HOME = "embed/tables.py"
_EMBED_CALLS = ("segment_sum", "scatter_add")
_ALLOW_AFFINITY = "# lint: allow-affinity"
# the ONE module allowed to mint ring points from digests and open-code
# vnode/ring bucketing (it IS the placement policy every router, bench,
# and scenario must agree with)
_AFFINITY_HOME = "serve/affinity.py"


def _is_raw_sync(call: ast.Call) -> bool:
    """``jax.device_get(...)``, ``arr.block_until_ready()``, or a bare
    ``device_get(...)`` name call — any spelling of the raw blocking
    primitives. Calls routed through the accounting wrappers are exempt:
    an attribute call whose receiver NAME mentions ``sync``
    (``syncs.device_get``, ``obssyncs.block_until_ready``) is the wrapper,
    not the primitive."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _SYNC_CALLS
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_CALLS:
        if isinstance(f.value, ast.Name) and "sync" in f.value.id:
            return False
        return True
    return False


def _is_direct_replica_call(call: ast.Call) -> bool:
    """``<recv>.submit/submit_async/submit_many/score(...)`` where the
    receiver's terminal name mentions ``replica`` (``replica.submit``,
    ``h.replica.submit``, ``self.replica.score``) — a raw cross-replica
    call. Router-mediated traffic never spells the replica receiver at
    the call site, so the name is the signal."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in _REPLICA_CALLS):
        return False
    v = f.value
    if isinstance(v, ast.Name):
        name = v.id
    elif isinstance(v, ast.Attribute):
        name = v.attr
    else:
        return False
    return "replica" in name.lower()


def _is_compile_site(call: ast.Call) -> bool:
    """A serve-side compilation entry point: ``<x>.lower(...).compile()``
    (or ``.compile()`` on a name mentioning ``lower``, the two-statement
    spelling), ``jax.jit(...)``, or a bare ``jit(...)`` call. The
    receiver-mentions-``lower`` requirement keeps ``re.compile(...)`` and
    other unrelated ``.compile()`` methods out of scope."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "compile":
        v = f.value
        # jitted.lower(args).compile() — chained form
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr == "lower"):
            return True
        # lowered.compile() — the receiver name carries the evidence
        name = v.id if isinstance(v, ast.Name) else (
            v.attr if isinstance(v, ast.Attribute) else "")
        return "lower" in name.lower()
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    return isinstance(f, ast.Name) and f.id == "jit"


def _is_device_alloc(call: ast.Call) -> bool:
    """A device-memory allocation site: ``jnp.zeros(...)`` (or any of the
    array factories in :data:`_ALLOC_CALLS` called on a receiver named
    ``jnp`` or spelled ``jax.numpy``), plus ``device_put`` in any
    spelling. Host-side ``np.zeros`` is NOT flagged — numpy arrays cost
    host RAM, not the budgeted HBM the serve-side accountants track."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _ALLOC_CALLS:
        v = f.value
        if isinstance(v, ast.Name):
            return v.id == "jnp"
        # jax.numpy.zeros(...) — the unaliased spelling
        return isinstance(v, ast.Attribute) and v.attr == "numpy"
    if isinstance(f, ast.Attribute) and f.attr == "device_put":
        return True
    return isinstance(f, ast.Name) and f.id == "device_put"


def _is_process_call(call: ast.Call) -> bool:
    """``subprocess.Popen(...)`` (any receiver, or a bare ``Popen(...)``
    name call) plus ``os.kill(...)`` / ``os.waitpid(...)`` — process
    lifecycle management. The ``os.``-receiver restriction mirrors
    :func:`_is_signal_signal`: ``proc.kill()`` / ``replica.kill()`` are
    object methods with their own contracts, not the raw syscall."""
    f = call.func
    if isinstance(f, ast.Name) and f.id == "Popen":
        return True
    if isinstance(f, ast.Attribute) and f.attr == "Popen":
        return True
    return (isinstance(f, ast.Attribute) and f.attr in _PROCESS_OS_CALLS
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def _mentions_int8(node: ast.expr) -> bool:
    """``np.int8`` / ``jnp.int8`` / bare ``int8`` / the string
    ``"int8"`` — any spelling of the quantized storage dtype."""
    if isinstance(node, ast.Attribute):
        return node.attr == "int8"
    if isinstance(node, ast.Name):
        return node.id == "int8"
    return isinstance(node, ast.Constant) and node.value == "int8"


def _is_quant_cast(call: ast.Call) -> bool:
    """``<x>.astype(np.int8)`` (any int8 spelling) — the narrowing cast
    at the heart of open-coded KV quantization. Widening casts and
    casts to other dtypes are not quantization and stay out of scope."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "astype"
            and any(_mentions_int8(a) for a in call.args))


def _is_quant_scale_math(node: ast.BinOp) -> bool:
    """Arithmetic against the ``127``/``127.0`` quantization range
    constant on either side — scale-factor math (``amax / 127.0``,
    ``q * scale`` spelled with the range). The magic number IS the
    signal: no other serve-side arithmetic has a reason to touch it."""
    def _is_range(n: ast.expr) -> bool:
        return isinstance(n, ast.Constant) and n.value in (127, 127.0)
    return _is_range(node.left) or _is_range(node.right)


def _is_spec_ctor(call: ast.Call) -> bool:
    """``PartitionSpec(...)`` / ``NamedSharding(...)`` in any spelling
    (bare name, ``jax.sharding.``-qualified, or the conventional
    ``P(...)`` alias) — a placement decision being made at the call
    site. A bare ``P`` name call is only ever the PartitionSpec alias
    in this codebase; Rule 14's scope is library code, where that
    convention holds."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _SPEC_CTORS or f.id == "P"
    return isinstance(f, ast.Attribute) and f.attr in _SPEC_CTORS


def _is_actuator_call(call: ast.Call) -> bool:
    """A fleet-lever actuation: any attribute call named in
    :data:`_ACTUATE_CALLS` (the lever methods are distinctive enough
    that the name alone is the signal), plus ``.kill(...)`` where the
    receiver's terminal name mentions ``replica`` or ``fleet`` (the
    chaos kill lever; ``proc.kill()``/``handle.kill()`` keep their own
    Rule 12 contract)."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr in _ACTUATE_CALLS:
        return True
    if f.attr != "kill":
        return False
    v = f.value
    if isinstance(v, ast.Name):
        name = v.id
    elif isinstance(v, ast.Attribute):
        name = v.attr
    else:
        return False
    return "replica" in name.lower() or "fleet" in name.lower()


def _is_handload_rng(call: ast.Call) -> bool:
    """``default_rng(...)`` in any spelling (``np.random.default_rng``,
    an aliased import, a bare name) — a private numpy Generator is the
    signature of a scenario hand-rolling its own feature stream instead
    of drawing from :data:`_HANDLOAD_HOME`."""
    f = call.func
    return (isinstance(f, ast.Name) and f.id == "default_rng") or \
        (isinstance(f, ast.Attribute) and f.attr == "default_rng")


def _is_signal_signal(call: ast.Call) -> bool:
    """``signal.signal(...)`` (or any ``<x>.signal(...)`` attribute call on
    a name ending in ``signal``) — the handler-installation form. A bare
    ``signal(...)`` name call is NOT flagged: that's someone's local
    function, not the stdlib installer."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "signal"
            and isinstance(f.value, ast.Name) and f.value.id == "signal")


def _is_embed_call(call: ast.Call) -> bool:
    """``segment_sum(...)`` / ``scatter_add(...)`` under any spelling
    (bare name, ``jax.ops.segment_sum``, ``lax.scatter_add``)."""
    f = call.func
    return (isinstance(f, ast.Name) and f.id in _EMBED_CALLS) or \
        (isinstance(f, ast.Attribute) and f.attr in _EMBED_CALLS)


def _mentions_token(node: ast.expr, tokens) -> bool:
    """Does any identifier in the expression carry one of ``tokens`` as
    an underscore-separated word (``ids``, ``flat_ids``, ``num_shards``,
    ``rows_per_shard``)? Word-level matching so ``width``/``grid`` never
    false-positive on the substring ``id``."""
    for sub in ast.walk(node):
        name = sub.id if isinstance(sub, ast.Name) else (
            sub.attr if isinstance(sub, ast.Attribute) else None)
        if name and any(t in name.lower().split("_") for t in tokens):
            return True
    return False


def _is_id_bucketing(binop: ast.BinOp) -> bool:
    """``ids // rows_per_shard`` / ``id % num_shards``: floor-div or mod
    pairing an id-named operand with a shard-named one — the owner
    computation at the heart of the bucketized lookup."""
    if not isinstance(binop.op, (ast.FloorDiv, ast.Mod)):
        return False
    return _mentions_token(binop.left, ("id", "ids")) \
        and _mentions_token(binop.right, ("shard", "shards"))


def _is_ring_point(call: ast.Call) -> bool:
    """``int(<...>.hexdigest()<...>, 16)`` — a cryptographic digest
    truncated into a base-16 integer, the signature of a ring point (or
    any other hash-derived placement key) being minted inline."""
    f = call.func
    if not (isinstance(f, ast.Name) and f.id == "int"):
        return False
    if len(call.args) != 2:
        return False
    base = call.args[1]
    if not (isinstance(base, ast.Constant) and base.value == 16):
        return False
    return any(isinstance(sub, ast.Attribute) and sub.attr == "hexdigest"
               for sub in ast.walk(call.args[0]))


def _is_ring_bucketing(binop: ast.BinOp) -> bool:
    """``point % num_vnodes`` / ``h // ring_size``: mod or floor-div
    arithmetic with a vnode/ring-named operand — ring ownership math
    deciding which replica a key lands on."""
    if not isinstance(binop.op, (ast.FloorDiv, ast.Mod)):
        return False
    toks = ("vnode", "vnodes", "ring")
    return _mentions_token(binop.left, toks) \
        or _mentions_token(binop.right, toks)


def check_source(src: str, filename: str = "<src>") -> List[str]:
    """Return ``"file:line: message"`` problems for one module's source."""
    problems: List[str] = []
    tree = ast.parse(src, filename=filename)
    lines = src.splitlines()
    norm = str(filename).replace("\\", "/")
    signal_home = norm.endswith(_SIGNAL_HOME)
    sync_home = norm.endswith(_SYNC_HOME)
    # Rule 8 scope: serve/ modules only (the fleet layer), router exempt
    replica_scoped = "serve/" in norm and not norm.endswith(_REPLICA_HOME)
    # Rule 9 scope: serve/ modules only, the compile-cache seam exempt
    compile_scoped = "serve/" in norm and not norm.endswith(_COMPILE_HOME)
    # Rule 10 scope: serve/ modules only, the KV-arena accountant exempt
    alloc_scoped = "serve/" in norm and not norm.endswith(_ALLOC_HOME)
    # Rule 11 scope: serve/ modules only (the ledger home is outside it)
    bytes_scoped = "serve/" in norm and not norm.endswith(_BYTES_HOME)
    # Rule 12 scope: everywhere, the process-management homes exempt
    # (supervisor + host launcher ARE the owners)
    process_home = any(norm.endswith(h) for h in _PROCESS_HOMES)
    # Rule 13 scope: serve/ modules only, the quant-scheme home exempt
    quant_scoped = "serve/" in norm and not norm.endswith(_QUANT_HOME)
    # Rule 14 scope: everywhere, the sharding-policy homes exempt
    spec_scoped = not any(norm.endswith(h) for h in _SPEC_HOMES)
    # Rule 15 scope: everywhere, the decision loop + lever owners exempt
    actuate_scoped = not any(norm.endswith(h) for h in _ACTUATE_HOMES)
    # Rule 16 scope: the chaos scenario harness only
    handload_scoped = norm.endswith(_HANDLOAD_SCOPE)
    # Rule 17 scope: everywhere, the fused lookup/sparse-grad home exempt
    embed_scoped = not norm.endswith(_EMBED_HOME)
    # Rule 18 scope: everywhere, the ring/digest-scoring home exempt
    affinity_scoped = not norm.endswith(_AFFINITY_HOME)

    def _allowed(lineno: int) -> bool:
        # marker anywhere on the offending line opts that line out
        return (0 < lineno <= len(lines)
                and _ALLOW_PRINT in lines[lineno - 1])

    def _signal_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_SIGNAL in lines[lineno - 1])

    def _sync_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_SYNC in lines[lineno - 1])

    def _replica_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_REPLICA in lines[lineno - 1])

    def _compile_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_COMPILE in lines[lineno - 1])

    def _alloc_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_ALLOC in lines[lineno - 1])

    def _bytes_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_BYTES in lines[lineno - 1])

    def _process_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_PROCESS in lines[lineno - 1])

    def _quant_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_QUANT in lines[lineno - 1])

    def _spec_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_SPEC in lines[lineno - 1])

    def _actuate_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_ACTUATE in lines[lineno - 1])

    def _handload_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_HANDLOAD in lines[lineno - 1])

    def _embed_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_EMBED in lines[lineno - 1])

    def _affinity_allowed(lineno: int) -> bool:
        return (0 < lineno <= len(lines)
                and _ALLOW_AFFINITY in lines[lineno - 1])

    if handload_scoped:
        # Rule 16, comprehension form: randrange/randint draws inside a
        # list/generator comprehension are a prompt/payload stream being
        # built inline — needs its own pass because the draw's context
        # (the comprehension) is what makes it load construction
        for comp in ast.walk(tree):
            if not isinstance(comp, (ast.ListComp, ast.GeneratorExp,
                                     ast.SetComp)):
                continue
            for sub in ast.walk(comp):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _HANDLOAD_DRAWS
                        and not _handload_allowed(sub.lineno)):
                    problems.append(
                        f"{filename}:{sub.lineno}: hand-rolled load "
                        f"construction ({sub.func.attr} in a "
                        f"comprehension) in chaos (request streams come "
                        f"from {_HANDLOAD_HOME} — feature_rows/"
                        "token_prompts/PromptPopulation — so they stay "
                        "seeded and replayable; mark deliberate "
                        f"exceptions `{_ALLOW_HANDLOAD}`)")

    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
                and not _allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: print() in library code "
                "(route through get_logger or the event log; stdout CLI "
                f"contracts mark the line `{_ALLOW_PRINT}`)")
        elif isinstance(node, ast.Call) and _is_thread_ctor(node):
            has_daemon = any(kw.arg == "daemon" for kw in node.keywords)
            has_star_kwargs = any(kw.arg is None for kw in node.keywords)
            if not (has_daemon or has_star_kwargs):
                problems.append(
                    f"{filename}:{node.lineno}: Thread() without explicit "
                    "daemon= (state the shutdown contract; an inherited "
                    "flag hangs or kills by accident)")
        elif isinstance(node, ast.Call) and _is_queue_ctor(node):
            has_maxsize = any(kw.arg == "maxsize" for kw in node.keywords)
            has_star_kwargs = any(kw.arg is None for kw in node.keywords)
            # positional signature is Queue(maxsize=0): a first positional
            # arg IS the maxsize
            has_positional = len(node.args) >= 1
            if not (has_maxsize or has_star_kwargs or has_positional):
                problems.append(
                    f"{filename}:{node.lineno}: Queue() without explicit "
                    "maxsize= (unbounded queues hide backpressure; state "
                    "the bound, or maxsize=0 to make unbounded deliberate)")
        elif isinstance(node, ast.Call) and _is_urlopen(node):
            has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
            has_star_kwargs = any(kw.arg is None for kw in node.keywords)
            # positional signature is urlopen(url, data, timeout, ...):
            # a third positional arg IS the timeout
            has_positional = len(node.args) >= 3
            if not (has_timeout or has_star_kwargs or has_positional):
                problems.append(
                    f"{filename}:{node.lineno}: urlopen() without timeout= "
                    "(a stalled connection hangs forever)")
        elif (isinstance(node, ast.Call) and _is_signal_signal(node)
                and not signal_home
                and not _signal_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: signal.signal() outside "
                f"{_SIGNAL_HOME} (handlers are process-global and "
                "last-installer-wins; route through "
                "reliability.preemption, or mark the line "
                f"`{_ALLOW_SIGNAL}`)")
        elif (isinstance(node, ast.Call) and replica_scoped
                and _is_direct_replica_call(node)
                and not _replica_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: direct replica call in serve/ "
                f"outside {_REPLICA_HOME} (bypasses the router's breaker/"
                "failover/fairness wrappers; route through Router.submit, "
                f"or mark the line `{_ALLOW_REPLICA}`)")
        elif (isinstance(node, ast.Call) and compile_scoped
                and _is_compile_site(node)
                and not _compile_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: compile site in serve/ "
                f"outside {_COMPILE_HOME} (bypasses the persistent AOT "
                "program cache and its hit/miss accounting; route "
                "through compile_cache.load_or_compile, or mark the "
                f"line `{_ALLOW_COMPILE}`)")
        elif (isinstance(node, ast.Call) and alloc_scoped
                and _is_device_alloc(node)
                and not _alloc_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: device allocation in serve/ "
                f"outside {_ALLOC_HOME} (HBM the registry LRU and KV "
                "arena accountants cannot see; route through "
                "KVCacheManager/ModelRegistry, or mark the line "
                f"`{_ALLOW_ALLOC}`)")
        elif (isinstance(node, ast.Attribute) and bytes_scoped
                and node.attr in _BYTES_ATTRS
                and not _bytes_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: device-byte arithmetic "
                f"(.{node.attr}) in serve/ outside {_BYTES_HOME} (private "
                "size formulas drift from the HBM ledger's; route through "
                "memory.nbytes_of/memory.param_bytes, or mark the line "
                f"`{_ALLOW_BYTES}`)")
        elif (isinstance(node, ast.Call) and _is_process_call(node)
                and not process_home
                and not _process_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: process management "
                "(Popen/os.kill/os.waitpid) outside "
                f"{'/'.join(_PROCESS_HOMES)} (workers need ONE owner — "
                "the supervisor's restart/drain machinery, per-host "
                "fleets the launcher's; route through serve.supervisor "
                f"or serve.launcher, or mark the line "
                f"`{_ALLOW_PROCESS}`)")
        elif (isinstance(node, ast.Call) and quant_scoped
                and _is_quant_cast(node)
                and not _quant_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: int8 quantization cast in "
                f"serve/ outside {_QUANT_HOME} (a private quant scheme "
                "diverges from the arena's rounding/clip/scale rules; "
                "route through kvcache.quantize_rows/dequantize_rows, "
                f"or mark the line `{_ALLOW_QUANT}`)")
        elif (isinstance(node, ast.BinOp) and quant_scoped
                and _is_quant_scale_math(node)
                and not _quant_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: quantization scale math "
                f"(127-range constant) in serve/ outside {_QUANT_HOME} "
                "(the scheme lives in ONE place so blocks and readers "
                "agree bit-for-bit; route through kvcache."
                "quantize_rows/dequantize_rows, or mark the line "
                f"`{_ALLOW_QUANT}`)")
        elif (isinstance(node, ast.Call) and spec_scoped
                and _is_spec_ctor(node)
                and not _spec_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: PartitionSpec/NamedSharding "
                f"construction outside {'/'.join(_SPEC_HOMES)} (placement "
                "policy lives in ONE home so mesh topology can change "
                "without auditing every module; route through the "
                "sharding helpers, or mark the line "
                f"`{_ALLOW_SPEC}`)")
        elif (isinstance(node, ast.Call) and actuate_scoped
                and _is_actuator_call(node)
                and not _actuate_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: fleet actuator call outside "
                f"control/ and {'/'.join(_ACTUATE_HOMES[1:])} (control "
                "actions must stay attributable in the autopilot's "
                "decision telemetry; route through control.autopilot, "
                f"or mark the line `{_ALLOW_ACTUATE}`)")
        elif (isinstance(node, ast.Call) and embed_scoped
                and _is_embed_call(node)
                and not _embed_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: embedding gather/scatter "
                f"({node.func.attr if isinstance(node.func, ast.Attribute) else node.func.id}) "  # noqa: E501
                f"outside {_EMBED_HOME} (bag association order defines "
                "the sharded-vs-reference bit-identity contract; route "
                "through embed.tables make_bag_lookup/"
                "bag_lookup_reference/sparse_table_grads, or mark the "
                f"line `{_ALLOW_EMBED}`)")
        elif (isinstance(node, ast.BinOp) and embed_scoped
                and _is_id_bucketing(node)
                and not _embed_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: id-bucketing arithmetic "
                f"(id //|% shard) outside {_EMBED_HOME} (shard ownership "
                "math lives in ONE home so every path agrees which chip "
                "owns a row; route through embed.tables, or mark the "
                f"line `{_ALLOW_EMBED}`)")
        elif (isinstance(node, ast.Call) and affinity_scoped
                and _is_ring_point(node)
                and not _affinity_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: hash-ring point minted "
                f"inline (int(hexdigest, 16)) outside {_AFFINITY_HOME} "
                "(placement keys the whole fleet must agree on; route "
                "through affinity.ConsistentHashRing/score_digest, or "
                f"mark the line `{_ALLOW_AFFINITY}`)")
        elif (isinstance(node, ast.BinOp) and affinity_scoped
                and _is_ring_bucketing(node)
                and not _affinity_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: vnode/ring bucketing "
                f"arithmetic outside {_AFFINITY_HOME} (a private ring "
                "assigns sessions differently than the router's; route "
                "through affinity.ConsistentHashRing, or mark the line "
                f"`{_ALLOW_AFFINITY}`)")
        elif (isinstance(node, ast.Call) and handload_scoped
                and _is_handload_rng(node)
                and not _handload_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: hand-rolled load "
                "construction (private default_rng generator) in chaos "
                f"(request streams come from {_HANDLOAD_HOME} — "
                "feature_rows/token_prompts/PromptPopulation — so they "
                "stay seeded and replayable; mark deliberate "
                f"exceptions `{_ALLOW_HANDLOAD}`)")
        elif (isinstance(node, ast.Call) and _is_raw_sync(node)
                and not sync_home
                and not _sync_allowed(node.lineno)):
            problems.append(
                f"{filename}:{node.lineno}: raw device_get/"
                "block_until_ready outside "
                f"{_SYNC_HOME} (uncounted host sync; route through "
                "syncs.device_get/syncs.block_until_ready, or mark the "
                f"line `{_ALLOW_SYNC}`)")
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None:
                problems.append(
                    f"{filename}:{node.lineno}: bare `except:` (swallows "
                    "SystemExit/KeyboardInterrupt; name the exceptions)")
            elif _catches_everything(node.type) \
                    and len(node.body) == 1 \
                    and isinstance(node.body[0], ast.Pass):
                problems.append(
                    f"{filename}:{node.lineno}: `except Exception: pass` "
                    "(silently swallowed error; narrow it or handle it)")
    return problems


def check_file(path: Union[str, Path]) -> List[str]:
    path = Path(path)
    try:
        src = path.read_text(encoding="utf-8")
    except (OSError, UnicodeDecodeError) as e:
        return [f"{path}: unreadable ({e})"]
    try:
        return check_source(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error ({e.msg})"]


def check_roots(roots: Sequence[Union[str, Path]],
                base: Union[str, Path, None] = None) -> List[str]:
    """Lint every ``.py`` under each root (a file or a directory).

    A missing root is itself a problem — a bad invocation must fail loudly,
    not silently shrink coverage (the namecheck.py convention).
    """
    problems: List[str] = []
    base = Path(base) if base is not None else Path.cwd()
    for root in roots:
        p = Path(root)
        if not p.is_absolute():
            p = base / p
        if not p.exists():
            problems.append(f"{root}: root not found")
            continue
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            problems.extend(check_file(f))
    return problems


def main(argv: Sequence[str] = ()) -> int:
    roots = list(argv) or DEFAULT_ROOTS
    problems = check_roots(roots)
    for p in problems:
        print(p)  # lint: allow-print
    if problems:
        print(f"check_reliability: {len(problems)} problem(s)")  # lint: allow-print
        return 1
    print(f"check_reliability: clean ({', '.join(map(str, roots))})")  # lint: allow-print
    return 0
