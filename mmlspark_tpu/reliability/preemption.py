"""Preemption signal: SIGTERM/SIGINT -> graceful checkpoint-and-drain.

Preemptible TPU capacity gives ~30s of notice as a SIGTERM. The default
Python behavior (KeyboardInterrupt mid-`urlopen`, or instant death) turns
that notice into a corrupt half-written step; this module turns it into a
process-wide flag that the long-running loops POLL at their own safe
points:

- :class:`ResilientTrainLoop` checks :func:`preempted` every step and, on
  preemption, writes a final checkpoint + data-state sidecar and returns
  cleanly — the next run resumes bit-identically.
- ``serve.Server`` / ``mmlspark-tpu serve`` drain: stop admission (503 +
  ``Retry-After``), finish in-flight batches, then close.

Design rules:

- The handler does NOTHING but set an event and emit telemetry — no
  checkpointing, no locks, no allocation-heavy work in signal context.
- Handlers install only on the main thread (CPython requirement) and are
  a no-op with a warning elsewhere, so library code may call
  :func:`install_handlers` unconditionally.
- :func:`request_preemption` flips the same flag programmatically — the
  watchdog's checkpoint-and-abort action and tests use it, so every
  consumer has exactly one condition to poll.

This module is the ONLY place ``signal.signal(`` is permitted
(reliability lint Rule 6): scattering handlers across modules makes the
last installer win silently, which is precisely the bug class this
central flag exists to kill.
"""
from __future__ import annotations

import signal
import threading
from typing import Dict, Optional

from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.preemption")


class PreemptionSignal:
    """Process-wide latch: set once by a signal/request, polled by loops.

    Thread-safe; ``reason`` records what tripped it (``"SIGTERM"``,
    ``"SIGINT"``, or a caller-supplied string) for the event log and the
    final run report.
    """

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._reason: Optional[str] = None

    def set(self, reason: str) -> None:
        with self._lock:
            if self._reason is None:
                self._reason = reason
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        with self._lock:
            return self._reason

    def clear(self) -> None:
        with self._lock:
            self._reason = None
        self._event.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


_SIGNAL = PreemptionSignal()
_installed: Dict[int, object] = {}   # signum -> previous handler


def get_signal() -> PreemptionSignal:
    """The process-wide preemption latch (one per process, like the
    active fault plan in :mod:`~mmlspark_tpu.reliability.faults`)."""
    return _SIGNAL


def preempted() -> bool:
    """Cheap poll for loop bodies: has a preemption been requested?"""
    return _SIGNAL.is_set()


def preemption_reason() -> Optional[str]:
    return _SIGNAL.reason


def request_preemption(reason: str = "requested") -> None:
    """Flip the latch programmatically (watchdog abort action, tests,
    orchestrators that learn of preemption out-of-band)."""
    first = not _SIGNAL.is_set()
    _SIGNAL.set(reason)
    if first:
        _LOG.warning("preemption requested (%s): draining to a clean stop",
                     reason)
        _emit(reason)


def reset() -> None:
    """Clear the latch (tests, or a supervisor re-arming after a drain)."""
    _SIGNAL.clear()


def _emit(reason: str) -> None:
    from mmlspark_tpu.observability import events, metrics
    metrics.counter("reliability.preemptions").inc()
    if events.events_enabled():
        events.emit("event", "preemption.signal", reason=reason)


def _handler(signum, frame) -> None:
    # Signal context: set the flag, nothing else. emit() appends one
    # JSONL line which is safe enough here and invaluable forensically.
    name = signal.Signals(signum).name
    first = not _SIGNAL.is_set()
    _SIGNAL.set(name)
    if first:
        _LOG.warning("received %s: draining to a clean stop", name)
        _emit(name)


def install_handlers(signums=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """Install the preemption handler for ``signums`` on the main thread.

    Returns True if installed; False (with a warning) when called off the
    main thread, where CPython forbids ``signal.signal``. Idempotent —
    re-installing over ourselves does not clobber the saved previous
    handlers.
    """
    if threading.current_thread() is not threading.main_thread():
        _LOG.warning("install_handlers() called off the main thread; "
                     "preemption handlers NOT installed")
        return False
    for signum in signums:
        prev = signal.signal(signum, _handler)
        if signum not in _installed:
            _installed[signum] = prev
    return True


def uninstall_handlers() -> None:
    """Restore the pre-install handlers (tests / embedding hosts)."""
    if threading.current_thread() is not threading.main_thread():
        return
    while _installed:
        signum, prev = _installed.popitem()
        signal.signal(signum, prev)
