"""Circuit breakers: stop hammering a dependency that is actively failing.

Retries (``retry.py``) make ONE call robust; they also make a DOWN
dependency worse — every request burns its full backoff schedule against
an endpoint that cannot answer, and under serving load those stacked
deadlines become the outage. The circuit breaker (Clipper/Hystrix-style)
sits ABOVE the retry layer and converts repeated failure into fast
rejection:

- **closed** (healthy): calls pass through; consecutive failures are
  counted, any success resets the count.
- **open** (tripped): after ``failure_threshold`` consecutive failures,
  calls fail immediately with :class:`CircuitOpen` — no network, no
  backoff — for ``reset_timeout_s``.
- **half-open** (probing): after the cooldown, exactly ONE caller is let
  through. Success closes the breaker; failure re-opens it and restarts
  the cooldown.

``CircuitOpen.retryable`` is True, so a breaker wrapped INSIDE a
``RetryPolicy`` composes correctly: the retry layer backs off (rather
than aborting) while the breaker holds the line, and a later attempt
lands after the probe window opens. State changes emit
``breaker.open|half_open|close`` events, trip counters, and a per-key
state gauge. Clock is injectable per-instance; :func:`breaker_for` keeps
one breaker per key (one per model, one per repo host) in a process
registry, mirroring ``faults._ACTIVE`` / the metrics registry.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 0.5}


class CircuitOpen(RuntimeError):
    """Raised instead of calling through while the breaker is open.

    ``retryable = True``: under a ``RetryPolicy`` this backs off and
    retries — by design, so retry-wrapped callers ride out a trip and
    recover through the half-open probe without special-casing.
    """

    retryable = True

    def __init__(self, key: str, retry_in_s: float):
        super().__init__(
            f"circuit {key!r} open; retry in {max(retry_in_s, 0.0):.1f}s")
        self.key = key
        self.retry_in_s = max(retry_in_s, 0.0)


class CircuitBreaker:
    """closed/open/half-open state machine around a failure-prone call.

    Use either form::

        breaker.call(fetch, url)            # wraps + classifies for you

        if breaker.allow():                 # explicit form for call sites
            try: ...                        # that need custom accounting
            except ...: breaker.record_failure()
            else: breaker.record_success()

    ``allow()`` returning True in half-open CLAIMS the single probe slot;
    a caller that then neither records success nor failure would wedge
    the breaker, so ``call()`` is the safer default.
    """

    def __init__(self, key: str, failure_threshold: Optional[int] = None,
                 reset_timeout_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.key = key
        self.failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else mmlconfig.get("reliability.breaker_failures"))
        if self.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.reset_timeout_s = float(
            reset_timeout_s if reset_timeout_s is not None
            else mmlconfig.get("reliability.breaker_reset_s"))
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, while closed
        self._opened_at = 0.0
        self._probing = False       # half-open probe slot claimed

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May this call proceed? In half-open, True claims the probe."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == HALF_OPEN:
                # failed probe: straight back to open, fresh cooldown
                self._opened_at = self.clock()
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._transition(OPEN)

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` through the breaker; any exception counts as a
        failure and propagates."""
        if not self.allow():
            with self._lock:
                retry_in = self._opened_at + self.reset_timeout_s \
                    - self.clock()
            raise CircuitOpen(self.key, retry_in)
        try:
            result = fn(*args, **kwargs)
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result

    def reset(self) -> None:
        """Force-close (tests / operator intervention)."""
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    # -- internals (callers hold self._lock) -------------------------------
    def _maybe_half_open(self) -> None:
        if self._state == OPEN and \
                self.clock() - self._opened_at >= self.reset_timeout_s:
            self._transition(HALF_OPEN)

    def _transition(self, state: str) -> None:
        prev, self._state = self._state, state
        _LOG.warning("circuit %r: %s -> %s", self.key, prev, state)
        from mmlspark_tpu.observability import events, metrics
        metrics.gauge(f"reliability.breaker_state.{self.key}").set(
            _STATE_GAUGE[state])
        if state == OPEN:
            metrics.counter("reliability.breaker_trips").inc()
        if events.events_enabled():
            # event names use the transition VERB (breaker.close), not the
            # state adjective — the docs/RELIABILITY.md contract
            verb = "close" if state == CLOSED else state
            events.emit("event", f"breaker.{verb}", key=self.key,
                        prev=prev, failures=self._failures)


_REG_LOCK = threading.Lock()
_BREAKERS: Dict[str, CircuitBreaker] = {}


def breaker_for(key: str, **kwargs) -> CircuitBreaker:
    """One process-wide breaker per key (e.g. ``serve.<model>``,
    ``downloader.<host>``); kwargs apply only on first creation."""
    with _REG_LOCK:
        br = _BREAKERS.get(key)
        if br is None:
            br = _BREAKERS[key] = CircuitBreaker(key, **kwargs)
        return br


def reset_breakers() -> None:
    """Drop all registered breakers (tests)."""
    with _REG_LOCK:
        _BREAKERS.clear()
