"""Watchdog: heartbeat registry + stall detection with all-thread dumps.

The PROACTIVE half of the reliability story (ISSUE 5). Retries and
crash-resume react to failures that announce themselves; a hang does not —
a wedged device program, a deadlocked queue, or a stuck remote fetch just
burns the deadline silently. Pathways-style schedulers (Barham et al.,
2022) close this gap with liveness tracking; here the same idea is two
pieces:

- **Heartbeats**: long-running loops register a :class:`Heartbeat` handle
  and call ``beat()`` on every unit of progress (a train step, one serve
  executor pass, a decoded record, a prefetched batch). A beat is ONE
  attribute write — cheap enough for any hot path, always on. The handle
  deregisters on ``close()`` so a finished loop can never look stalled.
- **The monitor**: a :class:`Watchdog` thread wakes every ``poll_s`` and
  flags any registered heartbeat whose last beat is older than its stall
  timeout (``reliability.stall_timeout_s`` by default, per-handle
  override). A stall dumps EVERY thread's stack to the event log
  (``watchdog.stall`` + the ``reliability.watchdog_stalls`` counter) —
  the forensic snapshot a post-mortem needs and a dead process can never
  give — then invokes the configured action:

  - ``"warn"`` (default): log + telemetry only;
  - ``"abort"``: additionally request a graceful preemption
    (:func:`mmlspark_tpu.reliability.preemption.request_preemption`), so
    ``ResilientTrainLoop`` checkpoints and exits cleanly and
    ``serve.Server`` drains — checkpoint-and-abort, not kill -9;
  - any callable ``action(stall: Stall)`` for custom escalation.

A stall fires ONCE per heartbeat until that heartbeat beats again
(re-arm on progress), so a long hang does not flood the log. The module
clock is injectable (:func:`set_clock`) and the check loop is callable
directly (:meth:`Watchdog.check`), so tests drive detection with zero
sleeps and zero real threads.
"""
from __future__ import annotations

import sys
import threading
import traceback
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Union

from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.watchdog")

_LOCK = threading.Lock()
_REGISTRY: Dict[int, "Heartbeat"] = {}
_clock: Callable[[], float] = time.monotonic


def set_clock(fn: Optional[Callable[[], float]]) -> None:
    """Inject a fake monotonic clock (tests); ``None`` restores the real
    one. Heartbeat timestamps and watchdog checks share this clock, so an
    injected test clock advances both consistently."""
    global _clock
    _clock = fn if fn is not None else time.monotonic


class Heartbeat:
    """One monitored loop's liveness handle.

    ``beat()`` is a single attribute write (no lock: CPython attribute
    stores are atomic, and the monitor only ever reads a slightly-stale
    value — off by at most one beat, which stall detection tolerates by
    construction). ``close()`` deregisters; a closed handle's ``beat()``
    is a harmless no-op so shutdown ordering never matters.
    """

    __slots__ = ("name", "timeout_s", "last", "beats", "_stalled")

    def __init__(self, name: str, timeout_s: Optional[float] = None):
        self.name = name
        self.timeout_s = timeout_s          # None = config default at check
        self.last = _clock()
        self.beats = 0
        self._stalled = False               # re-arm latch (one event/hang)

    def beat(self) -> None:
        self.last = _clock()
        self.beats += 1
        self._stalled = False

    def close(self) -> None:
        with _LOCK:
            _REGISTRY.pop(id(self), None)

    def __enter__(self) -> "Heartbeat":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False


def register(name: str, timeout_s: Optional[float] = None) -> Heartbeat:
    """Register a heartbeat for one loop instance. Always cheap and always
    on — whether anything WATCHES is the :class:`Watchdog` owner's call,
    so instrumented code never needs to know if a monitor exists."""
    hb = Heartbeat(name, timeout_s)
    with _LOCK:
        _REGISTRY[id(hb)] = hb
    return hb


def registered() -> List[Heartbeat]:
    with _LOCK:
        return list(_REGISTRY.values())


@dataclass
class Stall:
    """One detected stall: the silent heartbeat plus the evidence."""

    name: str
    stalled_s: float
    timeout_s: float
    beats: int
    stacks: str


def dump_all_stacks() -> str:
    """Every live thread's current stack, formatted — the post-mortem
    snapshot a hung process can still produce (a crashed one cannot)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(l.rstrip("\n")
                   for l in traceback.format_stack(frame))
    return "\n".join(out)


class Watchdog:
    """Monitor thread over the process heartbeat registry.

    ``action`` is ``"warn"``, ``"abort"`` (graceful preemption via the
    :mod:`~mmlspark_tpu.reliability.preemption` signal), or a callable
    taking the :class:`Stall`. ``stall_timeout_s`` defaults from
    ``reliability.stall_timeout_s`` (0 disables detection entirely);
    ``poll_s`` from ``reliability.watchdog_poll_s``. ``start=False``
    leaves the thread unstarted — tests call :meth:`check` directly
    under an injected clock.
    """

    def __init__(self, stall_timeout_s: Optional[float] = None,
                 action: Union[str, Callable[[Stall], None]] = "warn",
                 poll_s: Optional[float] = None, start: bool = True):
        self.stall_timeout_s = float(
            stall_timeout_s if stall_timeout_s is not None
            else mmlconfig.get("reliability.stall_timeout_s"))
        self.poll_s = float(poll_s if poll_s is not None
                            else mmlconfig.get("reliability.watchdog_poll_s"))
        if isinstance(action, str) and action not in ("warn", "abort"):
            raise ValueError(
                f"action must be 'warn', 'abort', or callable, got {action!r}")
        self.action = action
        self.stalls: List[Stall] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="mmlspark-tpu-watchdog", daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Stop the monitor thread. Idempotent."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    # -- detection ---------------------------------------------------------
    def check(self, now: Optional[float] = None) -> List[Stall]:
        """One detection pass; returns the stalls flagged THIS pass (each
        heartbeat fires at most once until it beats again)."""
        if self.stall_timeout_s <= 0:
            return []
        if now is None:
            now = _clock()
        fired: List[Stall] = []
        for hb in registered():
            timeout = (hb.timeout_s if hb.timeout_s is not None
                       else self.stall_timeout_s)
            if timeout <= 0 or hb._stalled:
                continue
            stalled_s = now - hb.last
            if stalled_s <= timeout:
                continue
            hb._stalled = True
            stall = Stall(name=hb.name, stalled_s=stalled_s,
                          timeout_s=timeout, beats=hb.beats,
                          stacks=dump_all_stacks())
            fired.append(stall)
            self.stalls.append(stall)
            self._report(stall)
        return fired

    def _report(self, stall: Stall) -> None:
        _LOG.error(
            "watchdog: %r silent for %.1fs (timeout %.1fs, %d beats); "
            "all-thread stacks:\n%s", stall.name, stall.stalled_s,
            stall.timeout_s, stall.beats, stall.stacks)
        # a stall is rare and already catastrophic-adjacent: count and
        # emit unconditionally-cheap telemetry, never swallow its cost
        from mmlspark_tpu.observability import events, flightrec, metrics
        metrics.counter("reliability.watchdog_stalls").inc()
        if events.recording_enabled():
            events.emit("event", "watchdog.stall", heartbeat=stall.name,
                        stalled_s=round(stall.stalled_s, 3),
                        timeout_s=stall.timeout_s, beats=stall.beats,
                        stacks=stall.stacks)
        # persist the in-memory ring NOW: a stall often precedes a SIGKILL
        # (driver timeout), after which there is nothing left to dump —
        # this works with events_path unset, which is the whole point
        dumped = flightrec.dump(reason=f"watchdog.stall.{stall.name}")
        if dumped:
            _LOG.error("watchdog: flight recorder dumped to %s", dumped)
        try:
            if callable(self.action):
                self.action(stall)
            elif self.action == "abort":
                from mmlspark_tpu.reliability import preemption
                preemption.request_preemption(
                    f"watchdog stall: {stall.name} silent "
                    f"{stall.stalled_s:.1f}s")
        except Exception as e:
            # the monitor must survive a broken action — it may be the
            # only thread still reporting anything
            _LOG.error("watchdog action failed (%s: %s)",
                       type(e).__name__, e)
