"""ResilientTrainLoop: preemption-tolerant training driver.

Composes ``DistributedTrainer`` + ``TrainCheckpointer`` into a loop whose
contract is the ROADMAP's north star for elastic pods: kill the process at
ANY point — mid-step, mid-checkpoint-write — rerun the same program, and the
resumed run's final parameters are bit-identical to an uninterrupted run.

What makes that hold:

- batches come from a DETERMINISTIC ``batch_fn(step)`` (step -> host batch),
  so a restart replays the exact data order;
- the train step folds its rng with ``state["step"]`` (trainer.py), so
  randomness is a function of the step, not of wall history;
- checkpoint saves commit atomically (orbax writes to a tmp dir and
  renames), so a crash mid-write leaves either the previous steps or the
  new one — never a half-step the resume could silently load;
- restore VALIDATES: if the newest checkpoint fails to load (corrupt or
  partial on-disk state), it is quarantined — renamed aside, preserved for
  forensics, invisible to orbax — and restore falls back to the next-newest
  step in ``all_steps()``, down to a fresh init when none survive.

This extends ``restore_or_init``'s resume-equality guarantee (checkpoint.py)
from the clean-exit path to the crash path, and is the driver later scaling
PRs (elastic pods, serving warm-restarts) build on.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

from mmlspark_tpu.reliability import preemption
from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.resilient")


class ResilientTrainLoop:
    """Crash-safe driver over a trainer + checkpointer pair.

    ``save_every`` is the checkpoint cadence in steps (the crash-loss
    window); the final step always commits with ``wait=True`` so a clean
    exit never loses the tail.

    ``trainer_factory(mesh) -> trainer`` enables the ELASTIC MESH lever:
    :meth:`reshard_to` requests a new ``(data, tensor[, pipe])`` shape
    and the loop honors it at the next step boundary — drain to a
    consistent checkpoint (+ input-pipeline sidecar when streaming),
    rebuild the trainer on the new mesh, restore the SAME state across
    mesh shapes, and continue with the SAME live iterator, so the batch
    stream is bit-identical to an un-resharded run. A factory (not a
    mutated trainer) because ``DistributedTrainer`` fixes its mesh and
    compiled steps at construction.
    """

    def __init__(self, trainer, checkpointer,
                 init_params_fn: Callable[[], Any], save_every: int = 1,
                 trainer_factory: Optional[Callable[[Any], Any]] = None):
        self.trainer = trainer
        self.ckpt = checkpointer
        self.init_params_fn = init_params_fn
        self.save_every = save_every
        self.trainer_factory = trainer_factory
        self._reshard_lock = threading.Lock()
        self._pending_reshard: Optional[str] = None

    # -- elastic mesh (lint Rule 15: a fenced actuator) ---------------------
    def reshard_to(self, mesh_shape: str) -> None:
        """Request a mid-run mesh change (``'4x2'``, ``'2x2x2'``, ...).

        Thread-safe and asynchronous: the request is honored at the next
        STEP BOUNDARY (a rendezvous — never mid-step), where the loop
        drains to a consistent checkpoint + data-state sidecar, rebuilds
        the trainer via ``trainer_factory`` on the new mesh, restores the
        state across mesh shapes (the PR 13 checkpoint contract), and
        resumes the SAME batch stream. Killed mid-reshard, the next run
        restores the drained checkpoint on whatever mesh ITS trainer was
        built with — position is never lost. Requires ``trainer_factory``
        (raises immediately otherwise: a request that could never be
        honored must not be accepted silently)."""
        if self.trainer_factory is None:
            raise RuntimeError(
                "reshard_to needs a trainer_factory(mesh) -> trainer; "
                "construct ResilientTrainLoop with one")
        # parse eagerly so a bad shape surfaces at the call site, not
        # inside the training loop
        from mmlspark_tpu.parallel.mesh import parse_mesh_shape
        parse_mesh_shape(mesh_shape)
        with self._reshard_lock:
            self._pending_reshard = mesh_shape

    def _take_pending_reshard(self) -> Optional[str]:
        with self._reshard_lock:
            shape, self._pending_reshard = self._pending_reshard, None
            return shape

    def _maybe_reshard(self, state: Any, step: int,
                       it: Any = None) -> Any:
        """The step-boundary rendezvous: when a reshard is pending, drain
        to a consistent checkpoint (sidecar first — an orphan snapshot is
        harmless, a committed step without one would restart the stream),
        swap the trainer onto the new mesh, and restore the state into
        its placement. Returns the (possibly resharded) state."""
        if step <= 0:
            return state   # nothing checkpointable yet; stays pending
        shape = self._take_pending_reshard()
        if shape is None:
            return state
        from mmlspark_tpu.parallel.mesh import make_mesh, parse_mesh_shape
        _LOG.warning("resharding at step %d to mesh %s", step, shape)
        self.ckpt.wait()
        if self.ckpt.latest_step() != step:
            if it is not None:
                self.ckpt.put_data_state(step, it.state_dict())
            self.ckpt.save(state, step=step, wait=True)
        mesh = make_mesh(parse_mesh_shape(shape))
        self.trainer = self.trainer_factory(mesh)
        state = self.ckpt.restore(self.trainer, self.init_params_fn,
                                  step=step)
        from mmlspark_tpu.observability import events, metrics
        metrics.counter("reliability.reshards").inc()
        if events.events_enabled():
            events.emit("event", "train.reshard", step=step,
                        mesh_shape=shape)
        return state

    def restore_or_init(self) -> Tuple[Any, int]:
        """(state, start_step): newest VALID checkpoint, else fresh init.

        A checkpoint that fails to restore is quarantined (preserved under a
        ``corrupt-<step>`` name, invisible to the manager) and the next-
        newest step is tried — restore-time validation, so a torn write or
        bitrot in the latest step costs ``save_every`` steps of progress
        instead of the whole run.
        """
        while True:
            step = self.ckpt.latest_step()
            if step is None:
                return self.trainer.init(self.init_params_fn), 0
            try:
                state = self.ckpt.restore(self.trainer, self.init_params_fn,
                                          step=step)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                # any load failure means this step is unusable on THIS disk;
                # quarantine and fall back rather than crash the whole run
                quarantined = self.ckpt.quarantine_step(step)
                _LOG.warning(
                    "checkpoint step %d failed to restore (%s: %s); "
                    "quarantined to %s, falling back to %s", step,
                    type(e).__name__, e, quarantined,
                    self.ckpt.latest_step())
                from mmlspark_tpu.observability import events
                if events.events_enabled():
                    events.emit("event", "restore.fallback", step=step,
                                error=f"{type(e).__name__}: {e}",
                                fallback=self.ckpt.latest_step())
                continue
            return state, step

    def run(self, batch_fn: Callable[[int], Dict], total_steps: int,
            rng: Optional[Any] = None) -> Any:
        """Train to ``total_steps`` (1-based), resuming from the newest
        valid checkpoint. ``batch_fn(step)`` must be deterministic in
        ``step`` — that is what makes a resumed run replay the interrupted
        one bit-for-bit.
        """
        import jax
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        state, start = self.restore_or_init()
        if start > 0:
            _LOG.info("resuming from checkpoint step %d", start)
        if start >= total_steps:
            return state
        for step in range(start + 1, total_steps + 1):
            if preemption.preempted():
                return self._drain(state, step - 1)
            state = self._maybe_reshard(state, step - 1)
            batch = self.trainer.put_batch(batch_fn(step))
            state, _metrics = self.trainer.train_step(state, batch, rng)
            self.ckpt.maybe_save(state, every=self.save_every, step=step)
        # final commit: wait for any in-flight async save first so a
        # cadence-aligned last step doesn't double-save
        self.ckpt.wait()
        if self.ckpt.latest_step() != total_steps:
            self.ckpt.save(state, step=total_steps, wait=True)
        return state

    def _drain(self, state: Any, step: int, data_state: Any = None) -> Any:
        """Preemption exit: force a synchronous final checkpoint (plus the
        input-pipeline sidecar when streaming) so the next run resumes from
        THIS step instead of the last cadence-aligned save."""
        reason = preemption.preemption_reason() or "preempted"
        _LOG.warning("preempted (%s) at step %d: committing a final "
                     "checkpoint before exit", reason, step)
        self.ckpt.wait()
        if step > 0 and self.ckpt.latest_step() != step:
            if data_state is not None:
                self.ckpt.put_data_state(step, data_state)
            self.ckpt.save(state, step=step, wait=True)
        from mmlspark_tpu.observability import events, metrics
        metrics.counter("reliability.preemption_drains").inc()
        if events.events_enabled():
            events.emit("event", "preemption.drain", step=step,
                        reason=reason, kind="train")
        return state

    def run_dataset(self, data, total_steps: int,
                    rng: Optional[Any] = None) -> Any:
        """Crash-safe training over a streaming input pipeline.

        ``data`` is a ``mmlspark_tpu.data.Dataset`` (typically ending in
        ``.batch(...).repeat(...)``) or an already-built
        ``PipelineIterator``. The pipeline's ``state_dict`` persists with
        EVERY checkpoint (``TrainCheckpointer.put_data_state``), so a
        restart restores both the params and the input cursor and the
        resumed run replays the interrupted batch stream mid-epoch,
        bit-for-bit — the streaming-side extension of ``run``'s
        deterministic ``batch_fn(step)`` contract. The snapshot writes
        BEFORE the (async) checkpoint save: an orphan snapshot is
        harmless, a committed step without one would restart the stream.
        """
        import jax
        from mmlspark_tpu.data.pipeline import Dataset
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        state, start = self.restore_or_init()
        if start > 0:
            _LOG.info("resuming from checkpoint step %d", start)
        it = data.iter() if isinstance(data, Dataset) else data
        try:
            if start > 0:
                snapshot = self.ckpt.get_data_state(start)
                if snapshot is not None:
                    it.load_state_dict(snapshot)
                else:
                    _LOG.warning(
                        "checkpoint step %d has no input-pipeline snapshot; "
                        "the stream restarts from its beginning", start)
            if start >= total_steps:
                return state
            for step in range(start + 1, total_steps + 1):
                if preemption.preempted():
                    return self._drain(state, step - 1,
                                       data_state=it.state_dict())
                state = self._maybe_reshard(state, step - 1, it=it)
                try:
                    host = next(it)
                except StopIteration:
                    raise ValueError(
                        f"dataset exhausted at step {step} of {total_steps};"
                        " add .repeat() for multi-epoch runs") from None
                batch = self.trainer.put_batch(host)
                state, _metrics = self.trainer.train_step(state, batch, rng)
                if self.save_every > 0 and step % self.save_every == 0:
                    self.ckpt.put_data_state(step, it.state_dict())
                    self.ckpt.save(state, step=step)
            self.ckpt.wait()
            if self.ckpt.latest_step() != total_steps:
                self.ckpt.put_data_state(total_steps, it.state_dict())
                self.ckpt.save(state, step=total_steps, wait=True)
            return state
        finally:
            closer = getattr(it, "close", None)
            if callable(closer):
                closer()
