"""Retry/backoff primitives for self-healing I/O.

The reference delegated fault tolerance to Spark's task retry and CNTK's MPI
restart; this TPU-native reproduction owns its training loop and I/O, so it
owns retry too. ``RetryPolicy`` is the one retry implementation every
subsystem shares (downloader MANIFEST/model fetches, future elastic-pod
paths): exponential backoff with DETERMINISTIC jitter (seeded hash, no
global RNG — a retried test run replays bit-for-bit), a max-attempt cap, an
optional overall deadline, and a retryable-exception predicate.

Three call shapes::

    policy = RetryPolicy(max_attempts=4, base_delay=0.2)

    @policy                       # decorator
    def fetch(url): ...

    policy.call(fetch, url)       # direct call

    for attempt in policy.attempts():   # context-manager loop (tenacity
        with attempt:                   # style) for multi-statement bodies
            data = fetch(url)

Every retry logs through the framework logger tree
(``mmlspark_tpu.reliability.retry``), so backoff activity is observable at
the same place as training metrics.
"""
from __future__ import annotations

import functools
import hashlib
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("reliability.retry")


def default_retryable(exc: BaseException) -> bool:
    """Transient-I/O default: the OSError family retries (URLError,
    ConnectionError, socket timeouts, truncated-read IOErrors), EXCEPT
    definitive HTTP client errors — a 404 will 404 again, but a 429 or any
    5xx is the server asking for a retry.

    Beyond I/O, any exception may opt in by carrying a truthy
    ``retryable`` attribute — the protocol load-shedding errors use
    (``serve.ServerOverloaded`` sets ``retryable = True`` as a class
    attribute) so new transient failure types classify correctly here
    without this module importing their packages."""
    from urllib.error import HTTPError
    if isinstance(exc, HTTPError):
        return exc.code == 429 or exc.code >= 500
    if isinstance(exc, (OSError, TimeoutError)):
        return True
    return bool(getattr(exc, "retryable", False))


def _unit(seed: int, attempt: int) -> float:
    """Deterministic uniform in [0, 1): sha256 of (seed, attempt)."""
    digest = hashlib.sha256(f"{seed}:{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2 ** 64


class Attempt:
    """One try of a :meth:`RetryPolicy.attempts` loop. ``__exit__`` decides
    whether the raised exception is swallowed (retry) or propagates."""

    __slots__ = ("policy", "number", "_started", "succeeded", "exception")

    def __init__(self, policy: "RetryPolicy", number: int, started: float):
        self.policy = policy
        self.number = number
        self._started = started
        self.succeeded = False
        self.exception: Optional[BaseException] = None

    def __enter__(self) -> "Attempt":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.succeeded = True
            return False
        self.exception = exc
        p = self.policy
        if self.number >= p.max_attempts or not p.retryable(exc):
            return False
        delay = p.delay(self.number)
        # Retry-After protocol: an exception carrying a positive numeric
        # ``retry_after`` (seconds — HttpRepo parses the 429/503 header
        # into it, CircuitOpen sets retry_in_s) RAISES the delay to the
        # server's ask; the deadline check below still caps the total, so
        # an absurd header gives up rather than oversleeping the budget.
        hinted = getattr(exc, "retry_after", None)
        if hinted is None:
            hinted = getattr(exc, "retry_in_s", None)
        try:
            if hinted is not None and float(hinted) > delay:
                delay = float(hinted)
        except (TypeError, ValueError):
            pass
        if p.deadline is not None and \
                (p.clock() - self._started) + delay > p.deadline:
            _LOG.warning(
                "%s: attempt %d/%d failed (%s: %s); deadline %.1fs would be "
                "exceeded, giving up", p.name, self.number, p.max_attempts,
                type(exc).__name__, exc, p.deadline)
            return False
        _LOG.warning("%s: attempt %d/%d failed (%s: %s); retrying in %.2fs",
                     p.name, self.number, p.max_attempts,
                     type(exc).__name__, exc, delay)
        # retries are cold-path by definition; the counter is unconditional,
        # the event only when an events path is configured
        from mmlspark_tpu.observability import events, metrics as obsmetrics
        obsmetrics.counter("reliability.retry_attempts").inc()
        if events.events_enabled():
            events.emit("event", "retry.attempt", policy=p.name,
                        attempt=self.number, delay_s=round(delay, 4),
                        error=f"{type(exc).__name__}: {exc}")
        if p.on_retry is not None:
            p.on_retry(self.number, exc, delay)
        p.sleep(delay)
        return True


@dataclass
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    Attempt ``n`` (1-based) that fails sleeps
    ``min(base_delay * multiplier**(n-1), max_delay)`` scaled by a seeded
    jitter in ``[1-jitter, 1+jitter]`` before attempt ``n+1``. ``deadline``
    bounds the TOTAL elapsed time: a retry whose sleep would cross it gives
    up immediately instead. ``retryable(exc) -> bool`` gates which failures
    retry at all (default: transient-I/O, :func:`default_retryable`).
    ``sleep``/``clock`` are injectable for tests; ``on_retry(attempt, exc,
    delay)`` is an optional per-retry hook on top of the built-in logging.
    """

    max_attempts: int = 3
    base_delay: float = 0.2
    max_delay: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline: Optional[float] = None
    retryable: Callable[[BaseException], bool] = default_retryable
    seed: int = 0
    name: str = "retry"
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None

    def delay(self, attempt: int) -> float:
        """Backoff before the attempt AFTER 1-based ``attempt`` fails."""
        base = min(self.base_delay * self.multiplier ** (attempt - 1),
                   self.max_delay)
        scale = 1.0 + self.jitter * (2.0 * _unit(self.seed, attempt) - 1.0)
        return max(base * scale, 0.0)

    def attempts(self) -> Iterator[Attempt]:
        """Yield :class:`Attempt` context managers until one succeeds, a
        non-retryable/final failure propagates, or the deadline passes."""
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{self.max_attempts}")
        started = self.clock()
        number = 0
        while True:
            number += 1
            attempt = Attempt(self, number, started)
            yield attempt
            if attempt.succeeded:
                return
            if attempt.exception is None:
                raise RuntimeError(
                    "attempt was never entered; use `with attempt:` inside "
                    "the attempts() loop")

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy, returning its
        result; the last exception propagates when retries are exhausted."""
        for attempt in self.attempts():
            with attempt:
                return fn(*args, **kwargs)
        raise AssertionError("unreachable: attempts() ended without success")

    def __call__(self, fn: Callable) -> Callable:
        """Decorator form: ``@RetryPolicy(...)``."""
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapped.retry_policy = self
        return wrapped
