"""mmlspark_tpu — a TPU-native ML pipeline framework.

A brand-new framework with the capabilities of MMLSpark (Microsoft ML for
Apache Spark v0.5), re-designed TPU-first:

- Columnar, partitioned ``Frame`` data pipelines instead of Spark DataFrames.
- ``Estimator``/``Transformer``/``Pipeline`` contracts with a JSON-serializable
  ``Param`` DSL (reference: ``core/contracts/src/main/scala/Params.scala``).
- Schema-carried metadata: categorical levels and score-column tags
  (reference: ``core/schema/src/main/scala/{Categoricals,SparkSchema}.scala``).
- JAX/XLA compute: learners JIT to XLA; distributed training via ``jax.sharding``
  meshes with collectives over ICI/DCN instead of MPI
  (reference: ``cntk-train/src/main/scala/CommandBuilders.scala``).
- Pallas kernels for fused image preprocessing instead of per-row OpenCV JNI
  (reference: ``image-transformer/src/main/scala/ImageTransformer.scala``).
"""

__version__ = "0.1.0"

from mmlspark_tpu.core.disk import DiskFrame, write_frame  # noqa: F401
from mmlspark_tpu.core.frame import Frame  # noqa: F401
from mmlspark_tpu.core.pipeline import (  # noqa: F401
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    Transformer,
)
from mmlspark_tpu.core.params import Param, Params  # noqa: F401
from mmlspark_tpu.core.serialization import load_stage, save_stage  # noqa: F401
