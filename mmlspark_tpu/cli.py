"""``mmlspark-tpu`` CLI: the spark-submit-style launcher.

The reference ships ``tools/bin/mml-exec`` (runs spark-shell/pyspark/
spark-submit against the local build); the TPU-native equivalent launches a
user script into an initialized distributed JAX process group:

    mmlspark-tpu run train.py --mesh data=-1,tensor=2 \
        --coordinator 10.0.0.1:8476 --num-processes 16 --process-id 3 -- \
        --script-arg value

On a single host ``mmlspark-tpu run train.py`` just runs the script (JAX
auto-detects any cluster env). The ``--mesh`` axes land in the config tier
(``runtime.mesh``) where ``parallel.mesh.mesh_from_config`` and
DeepClassifier's default mesh resolution pick them up, so the same script
scales from laptop CPU to a multi-host slice without edits.

Other subcommands: ``info`` (device + config inventory), ``bench`` (runs
the repo benchmark when present), ``serve`` (the micro-batching inference
server over HTTP — docs/SERVING.md), ``check`` (reliability lint),
``chaos`` (seeded train-kill-resume-then-serve fault scenario —
docs/RELIABILITY.md), ``report`` (render a telemetry event log).
"""
from __future__ import annotations

import argparse
import json
import os
import runpy
import sys
from typing import List, Optional


def _parse_mesh(text: str) -> dict:
    """'data=-1,tensor=2' -> {'data': -1, 'tensor': 2} (validated)."""
    from mmlspark_tpu.parallel.mesh import parse_mesh_axes
    try:
        return parse_mesh_axes(text)
    except ValueError as e:
        raise SystemExit(f"--mesh: {e}")


def _resolve_hosts(args) -> None:
    """Fill coordinator/num_processes/process_id from the ``--hosts``
    list or the env contract, unless given explicitly.

    The pod-launch UX (docs/DEPLOY.md): every host runs the IDENTICAL
    command line (the ``gcloud ... ssh --worker=all`` pattern) with
    ``--hosts h0,h1,...``; each process derives its own process-id by
    matching its identity against the list — MMLSPARK_HOST_INDEX when
    set (CI / heterogeneous naming), otherwise hostname/FQDN match.
    host 0 is the coordinator (``--port`` selects the port).

    Env fallbacks (external launchers: k8s indexed jobs, batch systems):
    MMLSPARK_COORDINATOR, MMLSPARK_NUM_PROCESSES, MMLSPARK_PROCESS_ID.
    On a real TPU pod none of this is needed — jax.distributed
    auto-discovers from the TPU metadata when everything is left unset.
    """
    def env_int(name: str):
        raw = os.environ.get(name)
        if raw is None:
            return None
        try:
            val = int(raw)
        except ValueError:
            raise SystemExit(
                f"{name}={raw!r} is not an integer (unexpanded template "
                "variable?)")
        if val < 0:
            raise SystemExit(f"{name}={val} must be >= 0")
        return val

    if args.coordinator is None:
        args.coordinator = os.environ.get("MMLSPARK_COORDINATOR")
    if args.num_processes is None:
        args.num_processes = env_int("MMLSPARK_NUM_PROCESSES")
    if args.process_id is None:
        args.process_id = env_int("MMLSPARK_PROCESS_ID")
    def check_range():
        if args.process_id is not None and args.num_processes is not None \
                and args.process_id >= args.num_processes:
            raise SystemExit(
                f"process id {args.process_id} out of range for "
                f"{args.num_processes} processes")

    if not args.hosts:
        check_range()   # the pure-env contract must fail fast too, not
        return          # hang a jax.distributed rendezvous on a bad id
    hosts = [h.strip() for h in args.hosts.split(",") if h.strip()]
    if not hosts:
        raise SystemExit("--hosts: empty host list")
    if args.coordinator is None:
        args.coordinator = f"{hosts[0]}:{args.port}"
    if args.num_processes is None:
        args.num_processes = len(hosts)
    if args.process_id is None:
        if os.environ.get("MMLSPARK_HOST_INDEX") is not None:
            args.process_id = env_int("MMLSPARK_HOST_INDEX")
        else:
            import socket
            me = {socket.gethostname(), socket.getfqdn(),
                  socket.gethostname().split(".")[0]}
            matches = [i for i, h in enumerate(hosts)
                       if h in me or h.split(".")[0] in me]
            if len(matches) != 1:
                raise SystemExit(
                    f"--hosts: cannot identify this host among {hosts} "
                    f"(I am {sorted(me)}); set MMLSPARK_HOST_INDEX or "
                    "pass --process-id")
            args.process_id = matches[0]
    check_range()


def cmd_run(args, passthrough: List[str]) -> int:
    from mmlspark_tpu.utils import config
    script = args.script
    if not os.path.exists(script):  # before any process-state mutation
        raise SystemExit(f"script not found: {script}")
    _resolve_hosts(args)
    if args.mesh:
        _parse_mesh(args.mesh)  # fail fast on a bad flag
        # config tier: visible to mesh_from_config() in the user script AND
        # to DeepClassifier/DistributedTrainer default mesh resolution
        os.environ["MMLSPARK_TPU_RUNTIME_MESH"] = args.mesh
        config.set("runtime.mesh", args.mesh)
    saved_platform = None
    # main() is also an importable in-process API (tests, notebooks) — every
    # mutation below is restored in the finally, whether the failure is in
    # the process-group join or the script itself (it is scoped to this
    # launch, not the process)
    try:
        if args.platform:
            # must land BEFORE the backend initializes; an explicit config
            # value outranks JAX_PLATFORMS, which ambient site hooks may
            # have pinned to a different platform
            import jax
            saved_platform = (jax.config.jax_platforms,)
            try:
                jax.config.update("jax_platforms", args.platform)
            except RuntimeError as e:
                # backend already live (in-process caller touched JAX
                # first): the platform can no longer be forced
                raise SystemExit(f"--platform: {e}")
        from mmlspark_tpu.parallel.mesh import initialize_multihost
        try:
            initialize_multihost(coordinator_address=args.coordinator,
                                 num_processes=args.num_processes,
                                 process_id=args.process_id)
        except ValueError as e:
            raise SystemExit(str(e))
        if args.platform:
            # some JAX versions accept jax_platforms updates silently after
            # the backend is live; verify the live backend actually matches
            # rather than running the user script on the wrong platform
            import jax
            try:
                backend = jax.default_backend()
            except RuntimeError as e:
                # e.g. --platform tpu on a host with no TPU: surface the
                # launcher's clean error style, not a raw traceback
                raise SystemExit(f"--platform {args.platform}: {e}")
            accept = {"gpu": {"gpu", "cuda", "rocm"}}.get(
                args.platform, {args.platform})
            if backend not in accept:
                raise SystemExit(
                    f"--platform {args.platform}: backend initialized as "
                    f"{backend!r} (JAX was touched before the launcher "
                    "could pin the platform)")
        # persistent compile cache: wire jax_compilation_cache_dir before
        # the user script compiles anything (no-op when the key is unset)
        from mmlspark_tpu import compile_cache
        compile_cache.enable_from_config()
        saved_argv, saved_path = sys.argv, list(sys.path)
        sys.argv = [script] + passthrough
        sys.path.insert(0, os.path.dirname(os.path.abspath(script)))
        try:
            runpy.run_path(script, run_name="__main__")
        finally:
            sys.argv, sys.path[:] = saved_argv, saved_path
    finally:
        if args.mesh:
            config.unset("runtime.mesh")
            os.environ.pop("MMLSPARK_TPU_RUNTIME_MESH", None)
        if saved_platform is not None:
            # restore the config for in-process callers (the already-live
            # backend is not torn down, but the next launch decides afresh)
            import jax
            try:
                jax.config.update("jax_platforms", saved_platform[0])
            except RuntimeError:
                pass
    return 0


def build_pod_argv(args, passthrough: List[str]) -> List[str]:
    """The ``gcloud compute tpus tpu-vm ssh --worker=all`` argv for a pod
    launch (docs/DEPLOY.md §2) — every worker runs the IDENTICAL
    ``mmlspark-tpu run`` command and jax.distributed auto-discovers the
    process group from the TPU metadata. Split out from cmd_launch_pod so
    tests can pin the exact constructed argv (the reference's live-cluster
    E2E — ``_e2e_script_action``/``_e2e_ssh`` in tools/runme/build.sh —
    verified its HDI script action the expensive way; the argv contract
    is the hardware-free part)."""
    import shlex

    def quote_dir(p: str) -> str:
        # a leading ~ (bare, ~/path, or ~user/path) must stay OUTSIDE the
        # quotes or the remote shell never tilde-expands it (cd '~/app'
        # fails where cd ~/app works). The unquoted prefix is allowed ONLY
        # when it is a legal-username shape — anything else (spaces, shell
        # metacharacters) is fully quoted, trading expansion for safety.
        import re
        if p.startswith("~"):
            prefix, sep, rest = p.partition("/")
            if re.fullmatch(r"~[A-Za-z0-9._-]*", prefix):
                if not sep:
                    return prefix          # '~' or '~user'
                return prefix + "/" + (shlex.quote(rest) if rest else "")
        return shlex.quote(p)

    inner = ["mmlspark-tpu", "run", args.script]
    if args.mesh:
        inner += ["--mesh", args.mesh]
    if passthrough:
        inner += ["--"] + list(passthrough)
    command = "cd " + quote_dir(args.app_dir) + " && " \
        + " ".join(shlex.quote(a) for a in inner)
    argv = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", args.name,
            f"--worker={args.worker}"]
    if args.zone:
        argv += ["--zone", args.zone]
    if args.project:
        argv += ["--project", args.project]
    argv += ["--command", command]
    return argv


def cmd_launch_pod(args, passthrough: List[str]) -> int:
    if args.mesh:
        _parse_mesh(args.mesh)  # fail fast before touching the cluster
    argv = build_pod_argv(args, passthrough)
    if args.dry_run:
        print(json.dumps(argv))  # lint: allow-print (stdout IS the contract)
        return 0
    import subprocess
    return subprocess.call(argv)


def cmd_info(args, passthrough) -> int:
    from mmlspark_tpu.parallel.mesh import device_count_summary
    from mmlspark_tpu.utils import config
    info = {"devices": device_count_summary(), "config": config.snapshot()}
    try:
        import jax
        info["backend"] = jax.default_backend()
    except Exception as e:  # pragma: no cover - backendless env
        info["backend_error"] = str(e)
    print(json.dumps(info, indent=2, default=str))  # lint: allow-print
    return 0


def cmd_check(args, passthrough) -> int:
    """Static reliability lint (urlopen-without-timeout, swallowed
    excepts, print-in-library-code, implicit-daemon threads, unbounded
    queues) over the installed package, or explicit roots."""
    from mmlspark_tpu.reliability import lint
    roots = args.roots or [os.path.dirname(
        os.path.abspath(__import__("mmlspark_tpu").__file__))]
    return lint.main(roots)


def cmd_report(args, passthrough) -> int:
    """Render a run report from one or more telemetry event logs (JSONL,
    per-pid sidecars merge natively; --glob adds a pattern); --json for
    the structured form, --trace to also export a Chrome-trace/Perfetto
    timeline of the same log."""
    from mmlspark_tpu.observability.aggregate import expand_event_paths
    paths = expand_event_paths(args.events, getattr(args, "glob", "")
                               or None)
    if not paths:
        raise SystemExit("report: no event logs matched")
    target = paths[0] if len(paths) == 1 else paths
    if getattr(args, "trace", None):
        if len(paths) > 1:
            raise SystemExit(
                "--trace exports one log at a time; pass a single events "
                "path")
        from mmlspark_tpu.observability.trace import export_trace
        stats = export_trace(paths[0], args.trace)
        print(f"trace: {stats['out']} ({stats['spans']} spans, "  # lint: allow-print
              f"{stats['events']} events, {stats['tracks']} tracks) — "
              "open in https://ui.perfetto.dev")
    if getattr(args, "json", False):
        from mmlspark_tpu.observability.report import build_report
        print(json.dumps(build_report(target, top=args.top),  # lint: allow-print
                         sort_keys=True))
    else:
        from mmlspark_tpu.observability.report import render_report
        print(render_report(target, top=args.top))  # lint: allow-print
    return 0


def cmd_top(args, passthrough) -> int:
    """Live fleet dashboard over HTTP replicas: scrapes ``/metrics`` +
    ``/readyz`` from every --replica through per-host circuit breakers
    and redraws a plain-ANSI frame (per-replica readiness, queue depth,
    QPS, p50/p99, shed, SLO burn, HBM occupancy). ``--once`` prints a
    single frame and exits (tests/CI)."""
    from mmlspark_tpu.observability.aggregate import FleetScraper
    from mmlspark_tpu.observability.dashboard import TopDashboard
    from mmlspark_tpu.observability.slo import SloEngine
    from mmlspark_tpu.serve.router import HttpReplica
    if not args.replica:
        raise SystemExit("top: at least one --replica HOST:PORT required")
    replicas = [HttpReplica(addr) for addr in args.replica]
    scraper = FleetScraper(replicas, timeout_s=args.timeout)
    dash = TopDashboard(scraper, SloEngine(), interval_s=args.interval)
    dash.run(once=args.once)
    return 0


def cmd_loadgen(args, passthrough) -> int:
    """Preview a seeded open-loop workload schedule (testing/loadgen):
    prints the trace spec, arrival count, offered QPS, per-bucket
    arrival counts, and the sha256 schedule fingerprint — the replay
    contract (same seed + trace -> same fingerprint, byte for byte)."""
    from mmlspark_tpu.testing import loadgen
    trace = loadgen.Trace(
        duration_s=args.duration, rate=args.rate, shape=args.shape,
        process=args.process, spike_start_s=args.spike_start,
        spike_len_s=args.spike_len, spike_factor=args.spike_factor,
        pareto_alpha=args.pareto_alpha,
        session_turns=args.session_turns, think_s=args.think)
    schedule = loadgen.generate(trace, args.seed)
    fingerprint = loadgen.schedule_fingerprint(schedule)
    buckets = loadgen.bucket_counts(schedule, args.bucket) \
        if args.bucket > 0 else []
    offered_qps = (len(schedule) / trace.duration_s
                   if trace.duration_s > 0 else 0.0)
    if getattr(args, "json", False):
        print(json.dumps({  # lint: allow-print
            "trace": trace.describe(), "seed": args.seed,
            "arrivals": len(schedule), "fingerprint": fingerprint,
            "offered_qps": round(offered_qps, 4),
            "bucket_s": args.bucket, "buckets": buckets},
            sort_keys=True))
        return 0
    print(f"trace: {trace.describe()}")  # lint: allow-print
    print(f"seed {args.seed}: {len(schedule)} arrivals "  # lint: allow-print
          f"({offered_qps:.2f} offered qps)")
    if buckets:
        print(f"per-{args.bucket:g}s buckets: {buckets}")  # lint: allow-print
    print(f"fingerprint: {fingerprint}")  # lint: allow-print
    return 0


def _parse_model_flag(text: str):
    """``NAME=ARCH[:JSON-kwargs]`` -> (name, architecture, kwargs)."""
    name, sep, rest = text.partition("=")
    if not sep or not name or not rest:
        raise SystemExit(
            f"--model: expected NAME=ARCH[:JSON-kwargs], got {text!r}")
    arch, sep2, blob = rest.partition(":")
    kwargs = {}
    if sep2:
        try:
            kwargs = json.loads(blob)
        except json.JSONDecodeError as e:
            raise SystemExit(f"--model {name}: bad JSON kwargs ({e})")
        if not isinstance(kwargs, dict):
            raise SystemExit(
                f"--model {name}: kwargs must be a JSON object, got "
                f"{type(kwargs).__name__}")
    return name, arch, kwargs


def cmd_serve(args, passthrough) -> int:
    """Start the micro-batching inference server behind the stdlib HTTP
    front-end (docs/SERVING.md). Blocks until interrupted; SIGTERM/SIGINT
    drain gracefully — admission stops (503 + Retry-After), in-flight
    batches finish, then the server closes (docs/RELIABILITY.md)."""
    import threading
    from mmlspark_tpu import compile_cache
    from mmlspark_tpu.models.jax_model import JaxModel
    from mmlspark_tpu.reliability import preemption
    from mmlspark_tpu.reliability.watchdog import Watchdog
    from mmlspark_tpu.serve.http import serve_http
    from mmlspark_tpu.serve.server import Server
    from mmlspark_tpu.utils import config as mmlconfig
    if getattr(args, "events_dir", ""):
        # per-pid sidecar convention: this worker appends to its OWN
        # events-<pid>.jsonl under the shared directory; the supervisor
        # (or `mmlspark-tpu report --glob`) merges them into one view
        os.makedirs(args.events_dir, exist_ok=True)
        mmlconfig.set("observability.events_path",
                      os.path.join(args.events_dir,
                                   f"events-{os.getpid()}.jsonl"))
    # second startup against a warm runtime.compile_cache_dir skips every
    # bucket compile: jax's cache for jit paths + the AOT program cache
    # consulted by ModelEntry._compile (docs/PERFORMANCE.md)
    compile_cache.enable_from_config()
    if not args.model:
        raise SystemExit(
            "serve: at least one --model NAME=ARCH[:JSON-kwargs] required "
            '(e.g. --model "mlp=mlp_tabular:{\\"input_dim\\": 8}")')
    models = {}
    for spec in args.model:
        name, arch, kwargs = _parse_model_flag(spec)
        m = JaxModel(inputCol="x", outputCol="y")
        try:
            m.set_model(arch, **kwargs)
        except (KeyError, TypeError, ValueError) as e:
            raise SystemExit(f"--model {name}: {e}")
        models[name] = m
    buckets = [int(b) for b in args.buckets.split(",") if b.strip()] \
        if args.buckets else None
    server_kwargs = dict(max_batch=args.max_batch,
                         max_wait_ms=args.max_wait_ms,
                         queue_depth=args.queue_depth, buckets=buckets)
    from mmlspark_tpu.observability import memory as devmem
    devmem.start_audit_poller()  # no-op unless observability.memory_poll_s
    fleet = None
    scraper = None
    autopilot = None
    if args.replicas > 1:
        # fleet mode: N in-process replicas behind the health-checked
        # router (failover, fairness, rolling rollout; docs/SERVING.md)
        from mmlspark_tpu.observability.aggregate import FleetScraper
        from mmlspark_tpu.serve.fleet import Fleet
        fleet = Fleet(models, replicas=args.replicas,
                      server_kwargs=server_kwargs)
        fleet.router.start_prober()
        # background fleet scrape keeps the aggregated per-replica view
        # (and the HBM ledger gauges) warm for `mmlspark-tpu top`
        scraper = FleetScraper(fleet)
        scraper.start()
        if args.autopilot or bool(mmlconfig.get("autopilot.enabled")):
            # the SLO-driven control loop over this fleet: traffic shift,
            # replica scale, adaptive admission (docs/AUTOPILOT.md); its
            # decisions land in the events sidecar as autopilot.* lines
            from mmlspark_tpu.control.autopilot import Autopilot
            autopilot = Autopilot(fleet)
            autopilot.start()
        front = fleet.router
    elif args.autopilot:
        raise SystemExit("serve: --autopilot needs --replicas > 1 "
                         "(the levers act on a fleet)")
    else:
        server = Server(models, **server_kwargs)
        front = server
    httpd, addr = serve_http(front, host=args.host, port=args.port)
    # stdout contract: one JSON line announcing the bound address, so
    # wrappers can discover an ephemeral --port 0; liveness and readiness
    # are reported SEPARATELY (the /livez vs /readyz split)
    h = front.health()
    print(json.dumps({"serving": addr,                 # lint: allow-print
                      "models": front.registry.names(),
                      "replicas": args.replicas, "pid": os.getpid(),
                      "live": h["live"], "ready": h["ready"]}),
          flush=True)  # a supervisor reads this over a block-buffered pipe
    # graceful preemption: SIGTERM/SIGINT flip the process-wide signal;
    # this monitor turns it into drain (stop admission, finish in-flight)
    # then unblocks serve_forever. Handlers only install on the main
    # thread — in-process callers off-main keep plain Ctrl-C semantics.
    preemption.install_handlers()
    watchdog = Watchdog() \
        if float(mmlconfig.get("reliability.stall_timeout_s")) > 0 else None

    def monitor():
        preemption.get_signal().wait()
        reason = preemption.preemption_reason() or "signal"
        if fleet is not None:
            fleet.drain(reason=reason)
        else:
            server.drain(reason=reason)
        httpd.shutdown()

    mon = threading.Thread(target=monitor, daemon=True,
                           name="mmlspark-tpu-serve-drain")
    mon.start()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass  # clean Ctrl-C shutdown path (no handler installed off-main)
    finally:
        httpd.server_close()
        if autopilot is not None:
            autopilot.stop()
        if scraper is not None:
            scraper.stop()
        if fleet is not None:
            fleet.close()
        else:
            server.close()
        if watchdog is not None:
            watchdog.close()
        devmem.stop_audit_poller()
    return 0


def cmd_fleet(args, passthrough) -> int:
    """Launch a REAL process fleet (docs/SERVING.md "Process fleet"):
    every replica is its own ``mmlspark-tpu serve`` OS process — own
    ephemeral port, own ``events-<pid>.jsonl`` sidecar, the SHARED
    persistent compile cache — supervised with restart-on-crash
    (exponential backoff + per-replica circuit breaker) behind the
    health-checked HTTP router. SIGTERM drains every child before the
    front closes. Args after ``--`` are forwarded to each worker's
    ``serve`` command line verbatim.

    ``--hosts h1,h2`` (or ``--hosts-file``) switches to the multi-host
    launcher: one fleet (supervisor + workers) per host, each announced
    front stitched behind ONE router/scraper control plane here, with
    per-host ``supervisor.*`` event sidecars under
    ``EVENTS_DIR/host-<host>/`` merging into one report. ``--autopilot``
    (single-host mode) runs the SLO-driven control loop with the scale
    lever actuating REAL worker processes through the supervisor
    (``Supervisor.add_slot``/``retire_slot`` via ``ProcessFleet``)."""
    import threading
    from mmlspark_tpu.observability.aggregate import FleetScraper
    from mmlspark_tpu.reliability import preemption
    from mmlspark_tpu.serve.http import serve_http
    from mmlspark_tpu.serve.router import Router
    from mmlspark_tpu.serve.supervisor import ProcessSpawner, Supervisor
    from mmlspark_tpu.utils import config as mmlconfig
    if not args.model:
        raise SystemExit(
            "fleet: at least one --model NAME=ARCH[:JSON-kwargs] required "
            '(e.g. --model "mlp=mlp_tabular:{\\"input_dim\\": 8}")')
    for spec in args.model:
        _parse_model_flag(spec)  # fail fast BEFORE spawning any worker
    replicas = args.replicas if args.replicas is not None \
        else int(mmlconfig.get("fleet.replicas"))
    if replicas < 1:
        raise SystemExit(f"fleet: --replicas must be >= 1, got {replicas}")
    hosts_spec = args.hosts or str(mmlconfig.get("fleet.hosts"))
    if args.hosts_file:
        from mmlspark_tpu.serve.launcher import read_hosts_file
        if hosts_spec:
            raise SystemExit("fleet: --hosts and --hosts-file are "
                             "mutually exclusive")
        hosts = read_hosts_file(args.hosts_file)
    else:
        from mmlspark_tpu.serve.launcher import parse_hosts
        hosts = parse_hosts(hosts_spec)
    if hosts:
        if args.autopilot:
            raise SystemExit(
                "fleet: --autopilot is single-host for now (each host's "
                "fleet supervises its own workers; run the autopilot "
                "per host)")
        return _fleet_multi_host(args, passthrough, hosts, replicas)
    events_dir = args.events_dir or os.path.join(os.getcwd(), "fleet-events")
    os.makedirs(events_dir, exist_ok=True)
    # the supervisor writes its OWN per-pid sidecar next to the workers'
    # so the merged report carries the supervisor.* decisions too:
    #   mmlspark-tpu report --glob 'EVENTS_DIR/events-*.jsonl'
    mmlconfig.set("observability.events_path",
                  os.path.join(events_dir, f"events-{os.getpid()}.jsonl"))
    cache_dir = args.compile_cache_dir \
        or str(mmlconfig.get("runtime.compile_cache_dir"))
    dpw = args.devices_per_worker if args.devices_per_worker is not None \
        else int(mmlconfig.get("fleet.devices_per_worker"))
    if dpw < 0:
        raise SystemExit(
            f"fleet: --devices-per-worker must be >= 0, got {dpw}")
    spawner = ProcessSpawner(
        args.model, host=args.host, events_dir=events_dir,
        compile_cache_dir=cache_dir or None,
        extra_args=list(passthrough), devices_per_worker=dpw)
    sup = Supervisor(spawner, [f"w{i}" for i in range(replicas)])
    scraper = None
    httpd = None
    autopilot = None
    try:
        sup.start()
        router = Router(sup.replicas)
        sup.attach_router(router)
        router.probe()
        router.start_prober()
        # background fleet scrape keeps the aggregated per-replica view
        # warm for `mmlspark-tpu top` pointed at the workers
        scraper = FleetScraper(router)
        scraper.start()
        sup.start_monitor()
        if args.autopilot or bool(mmlconfig.get("autopilot.enabled")):
            backend = str(mmlconfig.get("autopilot.scale_backend"))
            if backend == "inprocess":
                raise SystemExit(
                    "fleet: --autopilot over worker processes needs "
                    "autopilot.scale_backend=process (or auto), got "
                    f"{backend!r}")
            # the scale lever actuates REAL processes: scale_up spawns a
            # supervised worker (warm via the shared compile cache),
            # scale_down drains + retires one (docs/AUTOPILOT.md)
            from mmlspark_tpu.control.autopilot import Autopilot
            from mmlspark_tpu.serve.fleet import ProcessFleet
            autopilot = Autopilot(ProcessFleet(sup, router),
                                  scraper=scraper)
            autopilot.start()
        httpd, addr = serve_http(router, host=args.host, port=args.port)
        h = router.health()
        print(json.dumps({"serving": addr,             # lint: allow-print
                          "replicas": replicas, "pid": os.getpid(),
                          "workers": sup.stats(),
                          "events_dir": events_dir,
                          "live": h["live"], "ready": h["ready"]},
                         default=str), flush=True)
        # SIGTERM/SIGINT -> drain every child through its own preemption
        # handler, stop restarting, then unblock serve_forever
        preemption.install_handlers()

        def monitor():
            preemption.get_signal().wait()
            reason = preemption.preemption_reason() or "signal"
            sup.shutdown(reason=reason)
            httpd.shutdown()

        mon = threading.Thread(target=monitor, daemon=True,
                               name="mmlspark-tpu-fleet-drain")
        mon.start()
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass  # clean Ctrl-C shutdown path
    finally:
        if httpd is not None:
            httpd.server_close()
        if autopilot is not None:
            autopilot.stop()
        if scraper is not None:
            scraper.stop()
        sup.shutdown()
    return 0


def _fleet_multi_host(args, passthrough, hosts, replicas) -> int:
    """The ``fleet --hosts`` control plane: one fleet process per host
    via :class:`~mmlspark_tpu.serve.launcher.HostLauncher`, every
    announced host front behind one router + scraper here, SIGTERM
    fanning the drain out to every host."""
    import threading
    from mmlspark_tpu.observability.aggregate import FleetScraper
    from mmlspark_tpu.reliability import preemption
    from mmlspark_tpu.serve.http import serve_http
    from mmlspark_tpu.serve.launcher import HostLauncher
    from mmlspark_tpu.serve.router import Router
    from mmlspark_tpu.utils import config as mmlconfig
    events_dir = args.events_dir or os.path.join(os.getcwd(), "fleet-events")
    os.makedirs(events_dir, exist_ok=True)
    # the control plane's own sidecar (launcher.* events) sits next to
    # the per-host subdirectories; merge everything with
    #   mmlspark-tpu report --glob 'EVENTS_DIR/**/events-*.jsonl'
    mmlconfig.set("observability.events_path",
                  os.path.join(events_dir, f"events-{os.getpid()}.jsonl"))
    extra = list(passthrough)
    if args.compile_cache_dir:
        extra = ["--compile-cache-dir", args.compile_cache_dir] + extra
    if args.devices_per_worker is not None:
        extra = ["--devices-per-worker",
                 str(args.devices_per_worker)] + extra
    launcher = HostLauncher(hosts, args.model,
                            replicas_per_host=replicas,
                            events_dir=events_dir, extra_args=extra)
    scraper = None
    httpd = None
    try:
        launcher.launch()
        router = Router(launcher.replicas())
        router.probe()
        router.start_prober()
        scraper = FleetScraper(router)
        scraper.start()
        httpd, addr = serve_http(router, host=args.host, port=args.port)
        h = router.health()
        print(json.dumps({"serving": addr,             # lint: allow-print
                          "hosts": launcher.stats(),
                          "replicas_per_host": replicas,
                          "pid": os.getpid(),
                          "events_dir": events_dir,
                          "live": h["live"], "ready": h["ready"]},
                         default=str), flush=True)
        preemption.install_handlers()

        def monitor():
            preemption.get_signal().wait()
            launcher.shutdown()
            httpd.shutdown()

        mon = threading.Thread(target=monitor, daemon=True,
                               name="mmlspark-tpu-hosts-drain")
        mon.start()
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass  # clean Ctrl-C shutdown path
    finally:
        if httpd is not None:
            httpd.server_close()
        if scraper is not None:
            scraper.stop()
        launcher.shutdown()
    return 0


def cmd_autopilot(args, passthrough) -> int:
    """Autopilot offline tooling. ``replay``: re-run the pure decision
    core over recorded ``autopilot_signals`` telemetry under the
    recorded policy (fidelity must be byte-identical) and any number of
    candidate threshold overrides, ranked by counterfactual shed / SLO
    burn / action count (docs/AUTOPILOT.md "Replay runbook")."""
    from mmlspark_tpu.control import replay as rp
    if args.subcommand != "replay":  # pragma: no cover - argparse gates
        raise SystemExit(f"autopilot: unknown subcommand "
                         f"{args.subcommand!r}")
    log = rp.load_log(args.events)
    if not log["ticks"]:
        raise SystemExit(
            "autopilot replay: no autopilot_signals/tick events in the "
            "given log(s) — record a run with observability.events_path "
            "set and the autopilot on")
    recorded = rp.policy_from_fields(log["policy"] or {})
    fidelity = rp.fidelity_check(
        log["decisions"], rp.replay_decisions(log["ticks"], recorded))
    candidates = {"recorded": recorded}
    for spec in args.candidate:
        label, sep, rest = spec.partition(":")
        if not sep or not label:
            raise SystemExit(
                f"--candidate: expected LABEL:key=val[,key=val...], "
                f"got {spec!r}")
        try:
            candidates[label] = rp.policy_from_fields(
                log["policy"] or {}, rp.parse_overrides(rest))
        except ValueError as e:
            raise SystemExit(f"--candidate {label}: {e}")
    ranked = rp.rank_policies(log["ticks"], candidates)
    if args.json:
        print(json.dumps({"fidelity": fidelity,    # lint: allow-print
                          "ranking": ranked}, sort_keys=True))
    else:
        print(rp.format_ranking(ranked, fidelity))  # lint: allow-print
    if log["policy"] is not None and not fidelity["identical"]:
        return 1  # the replay-sufficiency contract broke: make it loud
    return 0


def cmd_chaos(args, passthrough) -> int:
    """Seeded chaos scenario (docs/RELIABILITY.md). ``--scenario train``
    (default): train under a deterministic fault schedule generated from
    --seed, kill + resume to bit-identical params, then serve traffic
    under injected faults while polling /healthz. ``--scenario fleet``:
    kill a replica of an N-wide fleet under fire; zero dropped requests,
    scores bit-identical to a single server, deterministic schedule.
    ``--scenario decode``: kill a replica MID-GENERATION; every sequence
    completes via failover-restart from its prompt with token streams
    bit-identical to a single server (seeded sampling). ``--scenario
    host``: SIGKILL a real worker PROCESS under fire; the supervisor
    warm-restarts it from the shared compile cache with zero failed
    requests, and a crash-looper ends breaker-open, not flapping.
    ``--scenario autopilot``: the same seeded load spike + replica kill
    against a static fleet and an autopiloted one — the autopilot must
    shed strictly less, recover, and never flap (docs/AUTOPILOT.md).
    ``--scenario elastic``: SIGKILL a worker process MID
    autopilot-driven supervised scale-up; zero failed requests, the
    half-spawned slot completes registration or is cleanly reaped (no
    zombie in the router rotation), desired == live after
    reconciliation, and the warm scale-up pays zero XLA compiles.
    ``--scenario recommender``: kill a replica mid-scoring with
    row-sharded embedding tables resident; zero failed requests,
    scores bit-identical to an unsharded single server, and the HBM
    ledger's kind="table" lines reconcile to zero on close.
    ``--scenario fleetprefix``: kill the replica holding the hottest
    ADVERTISED prefix chains mid-stream (docs/SERVING.md "fleet as one
    cache"); zero failed requests, survivors absorb the session keys,
    tokens bit-identical to a single server, and the prefix hit rate
    recovers with zero new compiles.
    ``--scenario reshard``: SIGKILL a replica MID-RESHARD while the
    fleet moves onto a new mesh placement under fire; zero failed
    requests, scores bit-identical to an untouched reference on both
    placements, survivors finish the reshard, and the HBM ledger
    reconciles to zero on close.
    Writes ``chaos_verdict.json`` under --out; exit 0 iff every
    invariant held."""
    if (args.scenario.endswith("_sharded")
            or args.scenario in ("recommender", "reshard")) \
            and "jax" not in sys.modules:
        # the 2-D mesh needs >= 4 devices: raise the host-platform count
        # BEFORE jax first loads so a CPU-only host can form it (same
        # seam as bench.py's xl lanes; on accelerator hosts the flag
        # only shapes the unused CPU platform). Read once at backend
        # init, so too late once jax is imported.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count=8"
            ).strip()
    from mmlspark_tpu.reliability import chaos
    if args.scenario not in chaos.SCENARIOS:
        known = "\n".join(f"  {name:8s} {desc}" for name, desc
                          in sorted(chaos.SCENARIOS.items()))
        print(f"chaos: unknown scenario {args.scenario!r}; "  # lint: allow-print
              f"registered scenarios:\n{known}", file=sys.stderr)
        return 2
    outdir = args.out or os.path.join(
        os.getcwd(), f"chaos-{args.scenario}-seed{args.seed}")
    if args.scenario in ("fleet", "fleet_sharded"):
        verdict = chaos.run_fleet_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests,
            mesh=chaos.SHARDED_MESH if args.scenario.endswith("_sharded")
            else "")
    elif args.scenario in ("decode", "decode_sharded"):
        verdict = chaos.run_decode_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests,
            mesh=chaos.SHARDED_MESH if args.scenario.endswith("_sharded")
            else "")
    elif args.scenario == "host":
        verdict = chaos.run_host_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests)
    elif args.scenario == "autopilot":
        verdict = chaos.run_autopilot_scenario(
            args.seed, outdir, replicas=args.replicas)
    elif args.scenario == "elastic":
        verdict = chaos.run_elastic_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests)
    elif args.scenario == "recommender":
        verdict = chaos.run_recommender_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests)
    elif args.scenario == "fleetprefix":
        verdict = chaos.run_fleetprefix_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests)
    elif args.scenario == "reshard":
        verdict = chaos.run_reshard_scenario(
            args.seed, outdir, replicas=args.replicas,
            requests=args.requests)
    else:
        verdict = chaos.run_scenario(
            args.seed, outdir, total_steps=args.steps,
            save_every=args.save_every, requests=args.requests)
    # stdout contract: the verdict JSON, so wrappers don't re-read the file
    print(json.dumps(verdict, indent=2,       # lint: allow-print
                     sort_keys=True))
    return 0 if verdict["passed"] else 1


def cmd_bench(args, passthrough) -> int:
    path = os.path.join(os.getcwd(), "bench.py")
    if not os.path.exists(path):
        raise SystemExit("no bench.py in the current directory")
    saved_argv = sys.argv
    extra = ["--baseline", args.baseline] if getattr(args, "baseline", "") \
        else []
    sys.argv = [path] + extra + passthrough
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = saved_argv
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # split off script passthrough args after `--`
    passthrough: List[str] = []
    if "--" in argv:
        cut = argv.index("--")
        argv, passthrough = argv[:cut], argv[cut + 1:]

    parser = argparse.ArgumentParser(
        prog="mmlspark-tpu",
        description="TPU-native ML pipeline framework launcher")
    sub = parser.add_subparsers(dest="command", required=True)

    run_p = sub.add_parser("run", help="run a script in the process group")
    run_p.add_argument("script")
    run_p.add_argument("--mesh", default="",
                       help="axis sizes, e.g. data=-1,tensor=2 (-1 absorbs)")
    run_p.add_argument("--coordinator", default=None,
                       help="host:port of process 0 (multi-host)")
    run_p.add_argument("--num-processes", type=int, default=None)
    run_p.add_argument("--process-id", type=int, default=None)
    run_p.add_argument("--hosts", default="",
                       help="comma list of participating hosts; run the "
                       "SAME command on every host and each derives its "
                       "process-id (MMLSPARK_HOST_INDEX or hostname "
                       "match), with host 0 as coordinator — see "
                       "docs/DEPLOY.md")
    run_p.add_argument("--port", type=int, default=8476,
                       help="coordinator port used with --hosts")
    run_p.add_argument("--platform", default=None,
                       choices=["cpu", "tpu", "gpu"],
                       help="force the jax platform before the process "
                       "group forms; outranks env and ambient site hooks "
                       "— e.g. --platform cpu for the virtual-device test "
                       "mesh")
    run_p.set_defaults(fn=cmd_run)

    pod_p = sub.add_parser(
        "launch-pod",
        help="run a script on every worker of a TPU pod via gcloud ssh")
    pod_p.add_argument("name", help="TPU VM / pod slice name")
    pod_p.add_argument("script", help="script path on the workers")
    pod_p.add_argument("--mesh", default="",
                       help="forwarded to `mmlspark-tpu run` on each worker")
    pod_p.add_argument("--zone", default="")
    pod_p.add_argument("--project", default="")
    pod_p.add_argument("--worker", default="all",
                       help="gcloud --worker selector (default: all)")
    pod_p.add_argument("--app-dir", default="~/app",
                       help="directory cd'd into on each worker")
    pod_p.add_argument("--dry-run", action="store_true",
                       help="print the gcloud argv as JSON, don't execute")
    pod_p.set_defaults(fn=cmd_launch_pod)

    info_p = sub.add_parser("info", help="device + config inventory")
    info_p.set_defaults(fn=cmd_info)

    bench_p = sub.add_parser("bench", help="run ./bench.py")
    bench_p.add_argument("--baseline", default="",
                         help="committed bench JSON (e.g. BENCH_r05.json) "
                         "to gate against: per-lane regression thresholds, "
                         "verdict on stdout, exit nonzero on red")
    bench_p.set_defaults(fn=cmd_bench)

    check_p = sub.add_parser(
        "check", help="static reliability lint (timeouts, swallowed "
                      "excepts, unbounded queues)")
    check_p.add_argument("roots", nargs="*",
                         help="files/dirs to lint (default: the installed "
                         "mmlspark_tpu package)")
    check_p.set_defaults(fn=cmd_check)

    serve_p = sub.add_parser(
        "serve",
        help="serve models over HTTP with dynamic micro-batching")
    serve_p.add_argument("--model", action="append", default=[],
                         metavar="NAME=ARCH[:JSON-kwargs]",
                         help="register a model under NAME (repeatable), "
                         'e.g. mlp=mlp_tabular:{"input_dim": 8}')
    serve_p.add_argument("--host", default="127.0.0.1")
    serve_p.add_argument("--port", type=int, default=8080,
                         help="0 = pick an ephemeral port (announced on "
                         "stdout)")
    serve_p.add_argument("--max-batch", type=int, default=None,
                         help="rows per micro-batch (serving.max_batch)")
    serve_p.add_argument("--max-wait-ms", type=float, default=None,
                         help="max coalescing wait (serving.max_wait_ms)")
    serve_p.add_argument("--queue-depth", type=int, default=None,
                         help="admission queue bound (serving.queue_depth)")
    serve_p.add_argument("--buckets", default="",
                         help='batch-shape buckets, e.g. "1,8,64" '
                         "(serving.buckets)")
    serve_p.add_argument("--replicas", type=int, default=1,
                         help="in-process serving replicas behind the "
                         "fleet router (failover, health probing, "
                         "rolling rollout; default 1 = plain server)")
    serve_p.add_argument("--autopilot", action="store_true",
                         help="run the SLO-driven autopilot over the "
                         "fleet (traffic shift, replica scale, adaptive "
                         "admission; needs --replicas > 1; "
                         "docs/AUTOPILOT.md). Also on when "
                         "autopilot.enabled is set")
    serve_p.add_argument("--events-dir", default="",
                         help="write this process's telemetry to "
                         "EVENTS_DIR/events-<pid>.jsonl (the per-pid "
                         "sidecar convention; supervisors and `report "
                         "--glob` merge them)")
    serve_p.set_defaults(fn=cmd_serve)

    fleet_p = sub.add_parser(
        "fleet",
        help="launch N `serve` worker PROCESSES behind the router, "
             "supervised with restart-on-crash (backoff + breaker); "
             "SIGTERM drains every child")
    fleet_p.add_argument("--model", action="append", default=[],
                         metavar="NAME=ARCH[:JSON-kwargs]",
                         help="model spec forwarded to every worker "
                         "(repeatable)")
    fleet_p.add_argument("--host", default="127.0.0.1")
    fleet_p.add_argument("--port", type=int, default=8080,
                         help="front router port (0 = ephemeral, "
                         "announced on stdout); workers always bind "
                         "ephemeral ports")
    fleet_p.add_argument("--replicas", type=int, default=None,
                         help="worker process count (default: "
                         "fleet.replicas config)")
    fleet_p.add_argument("--events-dir", default="",
                         help="shared telemetry directory: every process "
                         "(workers AND supervisor) appends its own "
                         "events-<pid>.jsonl there (default "
                         "./fleet-events)")
    fleet_p.add_argument("--compile-cache-dir", default="",
                         help="shared persistent compile cache exported "
                         "to every worker; restarted replicas LOAD "
                         "compiled programs instead of recompiling "
                         "(default: runtime.compile_cache_dir)")
    fleet_p.add_argument("--devices-per-worker", type=int, default=None,
                         help="pin each worker to K disjoint accelerator "
                         "chips (slot i sees chips [i*K, (i+1)*K) via "
                         "visible-devices env); 0 = no pinning, workers "
                         "share (default: fleet.devices_per_worker "
                         "config)")
    fleet_p.add_argument("--hosts", default="",
                         help="comma list of hosts to fan one fleet out "
                         "to each ('local' runs on this machine, other "
                         "names go over ssh); the announced host fronts "
                         "are stitched behind one router here (default: "
                         "fleet.hosts config; empty = single host)")
    fleet_p.add_argument("--hosts-file", default="",
                         help="file with one host per line (# comments); "
                         "mutually exclusive with --hosts")
    fleet_p.add_argument("--autopilot", action="store_true",
                         help="run the SLO-driven autopilot with the "
                         "scale lever actuating real worker processes "
                         "(Supervisor.add_slot/retire_slot; single-host "
                         "mode only; also on when autopilot.enabled is "
                         "set — see autopilot.scale_backend)")
    fleet_p.set_defaults(fn=cmd_fleet)

    autopilot_p = sub.add_parser(
        "autopilot",
        help="autopilot offline tooling (counterfactual policy replay "
             "over recorded decision telemetry)")
    ap_sub = autopilot_p.add_subparsers(dest="subcommand", required=True)
    replay_p = ap_sub.add_parser(
        "replay",
        help="re-run the pure decide() core over recorded "
             "autopilot_signals events; verify byte-identical fidelity "
             "under the recorded policy and rank candidate threshold "
             "overrides by counterfactual shed/SLO/action outcome")
    replay_p.add_argument("events", nargs="+",
                          help="event JSONL path(s) from a recorded "
                          "autopilot run (per-pid/per-host sidecars "
                          "merge)")
    replay_p.add_argument("--candidate", action="append", default=[],
                          metavar="LABEL:KEY=VAL[,KEY=VAL...]",
                          help="candidate policy: recorded thresholds "
                          "with these overrides (repeatable), e.g. "
                          "eager:scale_up_queue=2,scale_cooldown_s=10")
    replay_p.add_argument("--json", action="store_true",
                          help="emit fidelity + ranking as one JSON "
                          "object instead of the table")
    replay_p.set_defaults(fn=cmd_autopilot)

    chaos_p = sub.add_parser(
        "chaos",
        help="seeded chaos scenario (train-kill-resume-then-serve, or "
             "kill-a-fleet-replica-under-fire); exits 0 iff all "
             "invariants hold")
    chaos_p.add_argument("--scenario", default="train",
                         help="train: kill+resume then serve under faults; "
                         "fleet: kill one of N replicas mid-stream; "
                         "decode: kill a replica mid-generation, every "
                         "sequence completes via failover-restart; "
                         "host: SIGKILL a worker PROCESS under fire, "
                         "warm restart from the shared compile cache; "
                         "autopilot: seeded load spike + replica kill, "
                         "static fleet vs autopiloted fleet; "
                         "elastic: SIGKILL a worker mid autopilot-driven "
                         "supervised scale-up — no zombie slot, desired "
                         "== live after reconciliation; "
                         "recommender: kill a replica mid-scoring with "
                         "row-sharded embedding tables resident — "
                         "bit-identical scores, ledger reconciles "
                         "(default: train; unknown scenarios list the "
                         "registry and exit 2)")
    chaos_p.add_argument("--seed", type=int, default=0,
                         help="fault-schedule seed (same seed => same "
                         "kills, same verdict)")
    chaos_p.add_argument("--out", default="",
                         help="verdict/checkpoint directory (default "
                         "./chaos-<SCENARIO>-seed<SEED>)")
    chaos_p.add_argument("--steps", type=int, default=8,
                         help="train steps in each run (default 8)")
    chaos_p.add_argument("--save-every", type=int, default=2,
                         help="checkpoint cadence in steps (default 2)")
    chaos_p.add_argument("--requests", type=int, default=12,
                         help="serve-phase request count (default 12)")
    chaos_p.add_argument("--replicas", type=int, default=3,
                         help="fleet width for --scenario "
                         "fleet/decode/recommender; worker-process count "
                         "for --scenario host/elastic (default 3)")
    chaos_p.set_defaults(fn=cmd_chaos)

    report_p = sub.add_parser(
        "report", help="render a run report from telemetry event log(s)")
    report_p.add_argument("events", nargs="*",
                          help="path(s) to events.jsonl written with "
                          "observability.events_path set; per-pid "
                          "sidecars merge (inline globs OK; may be "
                          "omitted when --glob is given)")
    report_p.add_argument("--glob", default="",
                          help="additionally merge every log matching "
                          "this glob (e.g. 'run1/events-*.jsonl')")
    report_p.add_argument("--top", type=int, default=10,
                          help="rows in the slowest-span table (default 10)")
    report_p.add_argument("--trace", default="",
                          help="also export a Chrome-trace/Perfetto JSON "
                          "timeline to this path")
    report_p.add_argument("--json", action="store_true",
                          help="emit the structured report as one JSON "
                          "object instead of text")
    report_p.set_defaults(fn=cmd_report)

    top_p = sub.add_parser(
        "top", help="live fleet dashboard (scrapes /metrics + /readyz)")
    top_p.add_argument("--replica", action="append", default=[],
                       metavar="HOST:PORT",
                       help="replica address to scrape (repeatable)")
    top_p.add_argument("--interval", type=float, default=2.0,
                       help="redraw interval in seconds (default 2)")
    top_p.add_argument("--once", action="store_true",
                       help="print one frame and exit (tests/CI)")
    top_p.add_argument("--timeout", type=float, default=2.0,
                       help="per-replica scrape timeout in seconds")
    top_p.set_defaults(fn=cmd_top)

    loadgen_p = sub.add_parser(
        "loadgen", help="preview a seeded open-loop workload schedule")
    loadgen_p.add_argument("--rate", type=float, default=8.0,
                           help="base arrivals/second (default 8)")
    loadgen_p.add_argument("--duration", type=float, default=10.0,
                           help="trace length in seconds (default 10)")
    loadgen_p.add_argument("--shape", default="constant",
                           choices=["constant", "diurnal", "spike"],
                           help="rate curve (default constant)")
    loadgen_p.add_argument("--process", default="poisson",
                           choices=["poisson", "pareto"],
                           help="arrival process (default poisson)")
    loadgen_p.add_argument("--spike-start", type=float, default=0.0,
                           help="spike window start (s)")
    loadgen_p.add_argument("--spike-len", type=float, default=0.0,
                           help="spike window length (s)")
    loadgen_p.add_argument("--spike-factor", type=float, default=1.0,
                           help="rate multiplier inside the spike window")
    loadgen_p.add_argument("--pareto-alpha", type=float, default=1.5,
                           help="pareto tail shape (must be > 1)")
    loadgen_p.add_argument("--session-turns", type=int, default=1,
                           help="max turns per session (default 1: no "
                           "sessions)")
    loadgen_p.add_argument("--think", type=float, default=0.0,
                           help="inter-turn think time (s)")
    loadgen_p.add_argument("--seed", type=int, default=0,
                           help="schedule seed (default 0)")
    loadgen_p.add_argument("--bucket", type=float, default=1.0,
                           help="bucket size for per-bucket counts "
                           "(default 1s; 0 disables)")
    loadgen_p.add_argument("--json", action="store_true",
                           help="emit the preview as one JSON object")
    loadgen_p.set_defaults(fn=cmd_loadgen)

    args = parser.parse_args(argv)
    try:
        return args.fn(args, passthrough)
    except Exception:
        # last-gasp: persist the flight recorder so the crash ships its
        # own context even when observability.events_path was never set
        try:
            from mmlspark_tpu.observability import flightrec
            dumped = flightrec.dump(reason="crash")
            if dumped:
                print(f"flight recorder dumped to {dumped}",  # lint: allow-print
                      file=sys.stderr)
        except (ImportError, OSError):  # dump() itself never raises
            dumped = None
        raise


if __name__ == "__main__":
    sys.exit(main())
