"""Persistent compilation cache: recompilation is the other overhead floor.

BENCH_r05's low-MFU lanes are dispatch-bound (killed by the sync-free
stepping in :mod:`~mmlspark_tpu.parallel.trainer`), but every process
RESTART and every :meth:`Fleet.rollout` replica warm pays a second tax —
recompiling programs whose HLO has not changed. This module removes it in
two layers, both keyed off the ``runtime.compile_cache_dir`` config key
("" = off, nothing touches disk):

1. :func:`enable_from_config` wires jax's own persistent compilation cache
   (``jax_compilation_cache_dir``) so EVERY jit path — trainer steps, eval
   programs, transform closures — reuses XLA output across processes.
   Idempotent; call it once at process entry (the CLI does).

2. :func:`load_or_compile` — an on-disk AOT *program* cache for the serve
   bucket executables behind :meth:`ModelEntry._compile`. jax's cache only
   skips XLA backend work; the serve path AOT-compiles concrete
   executables, and ``jax.experimental.serialize_executable`` lets the
   whole loaded program skip lowering too. Entries are keyed on
   (model name+version, padded bucket shape, dtype) in the file NAME and
   carry the (jax version, jaxlib version, device fingerprint) environment
   in the file HEADER, so a stale toolchain is *detected* (bypass event +
   fresh compile overwrites) rather than silently misloaded. Writes go
   through the reliability layer's tmp-file + ``os.replace`` atomic
   pattern — a concurrent writer loses the race harmlessly and readers
   never observe a torn file; payloads are sha256-verified on load and
   corrupt entries are quarantined aside (``.corrupt``) to a fresh
   compile.

Every outcome is counted (``compile_cache.hits/misses/bypasses/stale/
quarantined/stores`` counters) and evented (``compile_cache.*``), feeding
the ``mmlspark-tpu report`` compile-cache section. This module is also the
sanctioned compile seam for serve code: lint Rule 9 flags any
``lower().compile()`` / ``jax.jit`` call site under ``serve/`` that does
not route here (``# lint: allow-compile`` opts out deliberately).
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("compile_cache")

_FORMAT_VERSION = 1
_SUFFIX = ".xprog"

_lock = threading.Lock()
_enabled_dir: Optional[str] = None  # enable_from_config idempotence


class CacheResult(NamedTuple):
    """What :func:`load_or_compile` did: the executable plus provenance
    (``source`` in {hit, miss, stale, corrupt, bypass}) so callers count
    real compiles separately from cache loads."""
    program: Callable
    source: str

    @property
    def hit(self) -> bool:
        return self.source == "hit"


def cache_dir() -> str:
    """The configured cache root ("" = caching off)."""
    return str(mmlconfig.get("runtime.compile_cache_dir") or "")


def worker_env(root: Optional[str] = None) -> Dict[str, str]:
    """Environment exports that point a CHILD process at the same
    persistent cache. The process-fleet supervisor spawns each replica
    with this merged into its environment, so replica N+1 (and every warm
    restart) cold-starts by LOADING the programs replica N stored —
    multi-reader is safe by construction here: entries publish via
    tmp-file + ``os.replace`` and are sha256-verified on load, so a
    concurrent writer loses the race harmlessly and a reader never
    observes a torn file. Returns ``{}`` when caching is off."""
    root = cache_dir() if root is None else str(root or "")
    if not root:
        return {}
    return {"MMLSPARK_TPU_RUNTIME_COMPILE_CACHE_DIR": os.path.abspath(root)}


def enable_from_config() -> Optional[str]:
    """Wire ``jax_compilation_cache_dir`` from ``runtime.compile_cache_dir``
    for all jit paths. Returns the directory when enabled, None when the
    key is unset. Idempotent per directory; safe to call before or after
    jax initializes its backends."""
    global _enabled_dir
    root = cache_dir()
    if not root:
        return None
    with _lock:
        if _enabled_dir == root:
            return root
        os.makedirs(root, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", root)
        # cache tiny programs too: the serve buckets and bench lanes this
        # exists for compile in well under the 1s default threshold
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _enabled_dir = root
    if events.recording_enabled():
        events.emit("compile_cache", "enabled", dir=root)
    logger.info("persistent compilation cache at %s", root)
    return root


def device_fingerprint() -> str:
    """Stable identity of the toolchain + attached devices: a serialized
    executable is only loadable onto the platform/topology it was built
    for, and a jax/jaxlib bump invalidates the wire format."""
    import jax
    try:
        import jaxlib.version
        jaxlib_v = jaxlib.version.__version__
    except ImportError:
        jaxlib_v = "?"
    devs = jax.devices()
    return "|".join([
        f"jax={jax.__version__}",
        f"jaxlib={jaxlib_v}",
        f"platform={devs[0].platform if devs else '?'}",
        f"kind={getattr(devs[0], 'device_kind', '?') if devs else '?'}",
        f"n={len(devs)}",
    ])


def entry_key(model: str, version: str, bucket: int,
              row_shape: Tuple[int, ...], dtype: str,
              mesh_key: str = "") -> str:
    """Filename stem for one program: the model+shape identity. The
    environment (jax/device fingerprint) lives in the header, not the
    name, so a toolchain bump is a *detected* stale entry, not a silent
    cache miss that leaves garbage behind. ``mesh_key`` is the placement
    identity ('' for single-device): an elastic reshard serves the same
    model+version under DIFFERENT mesh placements, and their partitioned
    executables must coexist, never collide (the score-path twin of the
    generative lane's ``|mesh=`` shape_key suffix)."""
    parts = [model, version, str(int(bucket)),
             ",".join(str(int(d)) for d in row_shape), str(dtype)]
    if mesh_key:
        parts.append(f"mesh={mesh_key}")
    ident = "\x00".join(parts)
    return hashlib.sha256(ident.encode("utf-8")).hexdigest()[:40]


def _aot_dir(root: str) -> str:
    # separate the AOT program entries from jax's own cache files
    return os.path.join(root, "aot")


def _counter(name: str):
    return metrics.counter(f"compile_cache.{name}")


def _event(name: str, **fields: Any) -> None:
    if events.recording_enabled():
        events.emit("compile_cache", name, **fields)


def _quarantine(path: str) -> None:
    """Move a bad entry aside (atomic; never deletes evidence) so the next
    writer starts clean and the corruption is inspectable."""
    try:
        os.replace(path, path + ".corrupt")
    except OSError:
        pass  # raced with another quarantining process: already gone
    _counter("quarantined").inc()


def _load_entry(path: str, fingerprint: str) -> CacheResult | None:
    """Deserialize one on-disk program; None means the caller compiles
    fresh (the entry was absent, stale, or quarantined-corrupt)."""
    try:
        with open(path, "rb") as f:
            header_line = f.readline()
            body = f.read()
        header = json.loads(header_line.decode("utf-8"))
        if header.get("v") != _FORMAT_VERSION:
            raise ValueError(f"format v{header.get('v')}")
    except FileNotFoundError:
        return None
    except (OSError, ValueError, UnicodeDecodeError) as e:
        logger.warning("compile cache entry %s unreadable (%s); "
                       "quarantined", path, e)
        _event("quarantine", path=path, reason=f"header: {e}")
        _quarantine(path)
        return None
    if header.get("env") != fingerprint:
        # a different toolchain/topology wrote this: bypass it and let the
        # fresh compile overwrite the entry for the current environment
        _counter("stale").inc()
        _event("stale", path=path, entry_env=header.get("env"),
               env=fingerprint)
        return CacheResult(None, "stale")  # type: ignore[arg-type]
    digest = hashlib.sha256(body).hexdigest()
    if digest != header.get("sha256"):
        logger.warning("compile cache entry %s failed sha256 verification; "
                       "quarantined", path)
        _event("quarantine", path=path, reason="sha256 mismatch")
        _quarantine(path)
        return None
    try:
        from jax.experimental import serialize_executable
        payload, in_tree, out_tree = pickle.loads(body)
        program = serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree)
    except Exception as e:  # deserialization is version-fragile by nature
        logger.warning("compile cache entry %s failed to deserialize "
                       "(%s: %s); quarantined", path, type(e).__name__, e)
        _event("quarantine", path=path,
               reason=f"{type(e).__name__}: {e}")
        _quarantine(path)
        return None
    _charge_program(header.get("model"), path, len(body))
    return CacheResult(program, "hit")


def _charge_program(model: Any, path: str, nbytes: int) -> None:
    """Report one loaded/stored executable's serialized size into the HBM
    ledger (kind=``program``, keyed by cache path so reloads never
    double-charge). Best-effort: accounting must never fail a compile."""
    if not model:
        return
    try:
        from mmlspark_tpu.observability import memory as devmem
        devmem.get_ledger().note_program(str(model), path, int(nbytes))
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("program-bytes ledger charge failed for %s (%s)",
                       path, e)


def _store_entry(path: str, program, meta: Dict[str, Any],
                 fingerprint: str) -> bool:
    """Serialize + atomically publish one compiled program. False when the
    executable does not support serialization (counted as a bypass — the
    compile still happened and serving proceeds uncached)."""
    try:
        from jax.experimental import serialize_executable
        body = pickle.dumps(serialize_executable.serialize(program))
    except Exception as e:
        _counter("bypasses").inc()
        _event("bypass", reason=f"serialize: {type(e).__name__}: {e}",
               **meta)
        return False
    header = dict(meta, v=_FORMAT_VERSION, env=fingerprint,
                  sha256=hashlib.sha256(body).hexdigest(), size=len(body))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(json.dumps(header, sort_keys=True).encode("utf-8"))
            f.write(b"\n")
            f.write(body)
        os.replace(tmp, path)  # atomic: concurrent writers last-win whole
    except OSError as e:
        logger.warning("compile cache store failed for %s (%s)", path, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    _counter("stores").inc()
    _charge_program(meta.get("model"), path, len(body))
    _event("store", path=path, bytes=len(body), **meta)
    return True


def _cached_compile(stem: str, meta: Dict[str, Any],
                    fresh: Callable[[], Callable]) -> CacheResult:
    """Shared load -> verify -> compile -> store body behind both public
    entry points. ``stem`` is the filename stem (shape identity), ``fresh``
    the closure that actually compiles when the cache cannot serve."""
    root = cache_dir()
    if not root:
        _counter("bypasses").inc()
        _event("bypass", reason="runtime.compile_cache_dir unset", **meta)
        return CacheResult(fresh(), "bypass")
    path = os.path.join(_aot_dir(root), stem + _SUFFIX)
    fingerprint = device_fingerprint()
    loaded = _load_entry(path, fingerprint)
    if loaded is not None and loaded.source == "hit":
        _counter("hits").inc()
        _event("hit", path=path, **meta)
        return loaded
    source = loaded.source if loaded is not None else "miss"
    if source == "miss":
        _counter("misses").inc()
        _event("miss", path=path, **meta)
    program = fresh()
    _store_entry(path, program, meta, fingerprint)
    return CacheResult(program, source)


def load_or_compile(model: str, version: str, bucket: int,
                    row_shape: Tuple[int, ...], dtype: Any,
                    jitted, params, mesh_key: str = "") -> CacheResult:
    """The serve-side compile seam: return the AOT executable for one
    padded bucket shape, loading it from ``runtime.compile_cache_dir``
    when a verified entry exists and compiling (then storing) otherwise.

    ``jitted`` is the model's raw jitted apply (``apply._jitted``) and
    ``params`` its device-resident tree — the compile itself happens HERE
    so serve/ modules never spell ``lower().compile()`` (lint Rule 9).
    The returned program is called as ``program(params, x)``.
    ``mesh_key`` carries the placement identity for mesh-bound models
    (see :func:`entry_key`) so resharded placements get their own
    entries.
    """
    import jax
    import numpy as np
    dtype_name = np.dtype(dtype).name
    spec = jax.ShapeDtypeStruct((int(bucket),) + tuple(row_shape),
                                np.dtype(dtype))
    meta = {"model": model, "version": version, "bucket": int(bucket),
            "row_shape": list(int(d) for d in row_shape),
            "dtype": dtype_name}
    if mesh_key:
        meta["mesh"] = mesh_key

    def fresh() -> Callable:
        return jitted.lower(params, spec).compile()

    return _cached_compile(
        entry_key(model, version, bucket, tuple(row_shape), dtype_name,
                  mesh_key),
        meta, fresh)


def program_key(model: str, version: str, kind: str, shape_key: str) -> str:
    """Filename stem for a generalized AOT program (the generative lane's
    prefill/decode executables): identity is (model+version, program kind,
    caller-provided shape string). Same header-carries-environment contract
    as :func:`entry_key`."""
    ident = "\x00".join([model, version, kind, shape_key])
    return hashlib.sha256(ident.encode("utf-8")).hexdigest()[:40]


def load_or_compile_program(model: str, version: str, kind: str,
                            shape_key: str, jitted,
                            *abstract_args: Any) -> CacheResult:
    """Generalized sibling of :func:`load_or_compile` for programs whose
    signature is richer than ``(params, x)`` — the generative lane's
    bucketed prefill and decode executables take KV arenas, token ids,
    position/block-table operands, and declare arena donation on the
    jitted function itself.

    ``abstract_args`` are exactly what ``jitted.lower`` receives: concrete
    params trees and ``jax.ShapeDtypeStruct`` placeholders. Donation
    semantics ride on ``jitted`` (``jax.jit(..., donate_argnums=...)``);
    backends that cannot donate (CPU test mesh) warn harmlessly, so that
    specific warning is silenced at the compile site here.
    """
    import warnings
    meta = {"model": model, "version": version, "kind": kind,
            "shape_key": shape_key}

    def fresh() -> Callable:
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*[Dd]onat")  # CPU: donation unsupported
            return jitted.lower(*abstract_args).compile()

    return _cached_compile(program_key(model, version, kind, shape_key),
                           meta, fresh)


def stats() -> Dict[str, int]:
    """Hit/miss/bypass/stale/quarantine/store counter snapshot (the report
    section and tests read this)."""
    return {name: int(_counter(name).value)
            for name in ("hits", "misses", "bypasses", "stale",
                         "quarantined", "stores")}
