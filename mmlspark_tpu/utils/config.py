"""Global configuration tier: namespaced knobs with env-var overrides.

Re-expression of the reference's typesafe-config scheme
(``core/env/src/main/scala/Configuration.scala:28-46``), which exposed a
``mmlspark.{sdk,cntk,tlc}`` namespace tree. Here the namespaces are
``mmlspark_tpu.{runtime,logging,profiling}`` and every key resolves, in
order: programmatic ``set()`` > environment variable
``MMLSPARK_TPU_<NAMESPACE>_<KEY>`` (upper-cased) > registered default.

This is the third config tier next to (1) per-stage ``Param``s and (2) the
launcher's CLI flags — the same three-tier split as the reference
(SURVEY.md §5 "Config / flag system").
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_DEFAULTS: Dict[str, Any] = {
    # runtime
    "runtime.prefetch_depth": 2,      # host->device prefetch queue depth
    "runtime.decode_threads": 0,      # 0 = native codec picks (ncpu)
    "runtime.mesh": "",               # launcher default, e.g. "data=-1,tensor=2"
    "runtime.device_cache_mb": 1024,  # HBM budget for device-resident epochs
    "runtime.compile_cache_dir": "",  # non-empty = persist compiled XLA
                                      # programs here: wires jax's
                                      # jax_compilation_cache_dir for every
                                      # jit path AND the serve-side AOT
                                      # program cache (compile_cache.py) so
                                      # restarts/rollouts skip bucket
                                      # compiles (docs/PERFORMANCE.md)
    # train (sync-free stepping; parallel/trainer.py, docs/PERFORMANCE.md)
    "train.metrics_flush_steps": 16,  # steps between device->host metric
                                      # ring flushes; also the dispatch-
                                      # depth bound on the CPU mesh (the
                                      # old throttle synced EVERY step)
    # data (streaming input pipeline; data/ package — see docs/DATA.md).
    # Values are validated at stage construction: window/workers must be
    # >= 1, prefetch_depth >= 0.
    "data.shuffle_window": 1024,   # records per windowed-shuffle block
    "data.decode_workers": 4,      # parallel decode worker threads
    "data.prefetch_depth": 0,      # to_device_iterator queue depth
                                   # (0 = inherit runtime.prefetch_depth)
    # evaluation: rows above which evaluators run as jitted XLA programs
    # instead of driver numpy. The device path wins when chips are
    # locally attached (the scored column crosses PCIe once instead of
    # funneling through single-threaded numpy sorts); on remote/tunneled
    # devices the transfer dominates — raise (or set huge) there.
    "evaluate.device_rows": 1_000_000,
    # reliability (retry/backoff + network timeouts; reliability/ package)
    "reliability.http_timeout": 30.0,  # seconds per urlopen (downloader)
    "reliability.max_attempts": 3,     # default RetryPolicy attempt cap
    "reliability.base_delay": 0.2,     # first backoff delay (seconds)
    # liveness layer (watchdog / circuit breakers; see docs/RELIABILITY.md)
    "reliability.stall_timeout_s": 0.0,   # 0 = watchdog stall detection off
    "reliability.watchdog_poll_s": 1.0,   # monitor thread poll cadence
    "reliability.breaker_failures": 5,    # consecutive failures -> open
    "reliability.breaker_reset_s": 30.0,  # open -> half-open probe delay
    # serving (dynamic micro-batching inference server; serve/ package)
    "serving.max_batch": 64,          # rows per flushed micro-batch
    "serving.max_wait_ms": 5.0,       # max coalescing wait before flush
    "serving.queue_depth": 256,       # bounded admission queue (overload
                                      # beyond this sheds, never queues)
    "serving.buckets": "",            # "" = {1, max/8, max/2, max}; else
                                      # e.g. "1,8,64" (largest >= max_batch)
    "serving.default_deadline_ms": 0.0,  # 0 = requests never expire
    "serving.drain_timeout_s": 10.0,  # graceful-drain budget before close
    "serving.retry_after_s": 0.0,     # Retry-After hint on a queue-full
                                      # shed (draining replicas hint 1.0)
    # generate (autoregressive decode lane; serve/generate.py + kvcache.py
    # — see docs/SERVING.md "Generative lane" and the KV sizing runbook)
    "generate.max_seq_len": 512,      # hard cap on prompt + generated
    "generate.prefill_buckets": "",   # "" = powers of two up to max_seq_len
                                      # starting at kv_block_tokens; else
                                      # e.g. "32,128,512" (prompt-length
                                      # buckets; one prefill program each)
    "generate.kv_block_tokens": 16,   # tokens per paged KV block (the
                                      # arena allocation granule)
    "generate.max_sequences": 8,      # decode batch cap = in-flight
                                      # sequence cap (batch-size buckets
                                      # derive from it: {1, /4, /2, max})
    "generate.max_new_tokens": 64,    # default generation budget per
                                      # request (callers can lower/raise)
    "generate.arena_mb": 0.0,         # fixed KV arena size; 0 = derive
                                      # from max_sequences x max_seq_len.
                                      # Accounted under
                                      # runtime.device_cache_mb either way
    "generate.prefix_cache": True,    # shared-prefix KV reuse: hash full
                                      # prompt blocks so N requests with
                                      # one system prompt pay prefill once
                                      # (refcounted blocks, copy-on-write)
    "generate.prefill_chunk": 0,      # >0: split prompts into chunks of
                                      # this many tokens, interleaved with
                                      # decode steps so a long joiner never
                                      # stalls the running batch's ITL
    "generate.kv_dtype": "",          # "" = model dtype; "int8" stores KV
                                      # blocks quantized (per-row scales,
                                      # dequant fused into decode) — ~2x
                                      # arena capacity, quality-gated
    "generate.draft_model": "",       # registered model name proposing
                                      # draft tokens (speculative decode);
                                      # "" disables speculation
    "generate.spec_tokens": 3,        # draft tokens proposed+verified per
                                      # target step when draft_model is set
    "generate.advertise_top_k": 8,    # resident prefix chains summarized
                                      # into the replica's PrefixDigest
                                      # (kvcache stats -> scraper -> router
                                      # affinity; 0 disables advertisement)
    "generate.shard_kv": True,        # on a tensor-parallel model mesh,
                                      # shard the KV arena's head axis over
                                      # `tensor` (requires heads % |tensor|
                                      # == 0); False keeps it replicated
    # parallel (mesh topology; parallel/mesh.py — see docs/PERFORMANCE.md
    # "2-D data x model mesh")
    "parallel.mesh_shape": "",        # "DxT" shorthand, e.g. "4x2" =
                                      # data=4, tensor=2. Takes precedence
                                      # over runtime.mesh; "" defers to it
    # embed (row-sharded recommender tables; embed/ package — see
    # docs/RECOMMENDER.md)
    "embed.row_multiple": 8,          # table rows round up to this multiple
                                      # so any tensor axis up to it shards
                                      # every table evenly (the shard
                                      # granule; rows beyond the declared
                                      # count are zero pad)
    "embed.fused_lookup": True,       # tensor meshes use the fused
                                      # bucketize/all-to-all lookup and the
                                      # sparse all-gather scatter-add
                                      # gradient; False falls back to the
                                      # reference gather (GSPMD partitions
                                      # it against the sharded table) for
                                      # numerics triage
    # fleet (multi-replica router + rolling rollout; see docs/SERVING.md)
    "fleet.replicas": 2,              # in-process replicas per Fleet
    "fleet.failover_attempts": 2,     # routing tries per request (1 = no
                                      # failover; 2 = one retry elsewhere)
    "fleet.failover_delay_s": 0.0,    # backoff between failover attempts
    "fleet.probe_interval_s": 1.0,    # background health-probe cadence
    "fleet.capacity_rows": 0,         # tenant-fairness capacity (0 =
                                      # derive from replica queue depths)
    "fleet.tenant_weights": "",       # "gold=3,free=1"; unlisted tenants
                                      # get fleet.tenant_default_weight
    "fleet.tenant_default_weight": 1.0,
    # prefix-affinity routing (serve/affinity.py — see docs/SERVING.md
    # "fleet as one cache"): replicas advertise their resident prefix
    # chains; the router scores READY replicas by expected hit depth
    # before the smooth-WRR tie-break. Breaker/overload/failover always
    # override affinity — a cache hit is never worth a down replica.
    "fleet.affinity_enabled": True,   # False = prefix-blind WRR only
    "fleet.affinity_min_depth": 1,    # matched blocks required before
                                      # prefix affinity overrides WRR
    "fleet.affinity_vnodes": 64,      # virtual nodes per replica on the
                                      # session consistent-hash ring
    "fleet.affinity_seed": 0,         # ring placement seed (deterministic)
    "fleet.affinity_prewarm": 4,      # hottest prompt prefixes replayed
                                      # through a rollout canary's prefill
                                      # before it takes weight (0 = off)
    "fleet.affinity_spill_factor": 1.5,  # bounded load: an affinity
                                      # leader whose in-flight count
                                      # exceeds factor*(fleet mean + 1)
                                      # spills the pick back to WRR — a
                                      # cache hit is never worth a hot
                                      # spot (0 = never spill)
    # process-fleet supervisor (serve/supervisor.py — real worker
    # processes with restart-on-crash; see docs/SERVING.md runbook)
    "fleet.supervisor_min_uptime_s": 5.0,   # a child dying sooner counts
                                            # as a crash-loop failure
    "fleet.supervisor_base_delay_s": 0.5,   # first restart backoff
    "fleet.supervisor_max_delay_s": 30.0,   # restart backoff cap
    "fleet.supervisor_ready_timeout_s": 120.0,  # spawn -> ready budget
                                                # (includes child imports)
    "fleet.supervisor_breaker_failures": 3,  # consecutive short-lived
                                             # crashes -> breaker open,
                                             # replica out of rotation
    "fleet.supervisor_breaker_reset_s": 60.0,  # open -> one probe respawn
    "fleet.supervisor_poll_s": 0.2,          # monitor thread cadence
    "fleet.devices_per_worker": 0,    # >0: each spawned worker process is
                                      # pinned to its own disjoint block of
                                      # K local chips via a per-slot
                                      # visible-devices env (CLI:
                                      # `fleet --devices-per-worker K`)
    "fleet.hosts": "",                # comma list of hosts for the multi-
                                      # host launcher (serve/launcher.py;
                                      # "local" runs on this machine, any
                                      # other name goes over ssh); "" =
                                      # single-host supervisor fleet. CLI:
                                      # `fleet --hosts h1,h2` / --hosts-file
    # logging
    "logging.level": "INFO",
    "logging.metrics_every": 0,       # default train-metric log cadence (steps)
    "logging.history_max": 1000,      # MetricLogger history cap (entries)
    # profiling
    "profiling.trace_dir": "",        # non-empty = capture jax traces here
    # observability (spans + event log + metrics registry; observability/)
    "observability.events_path": "",  # non-empty = append JSONL events here
    "observability.metrics": False,   # hot-path (per-step) metric collection
    "observability.annotate": False,  # span() also opens a TraceAnnotation
    "observability.peak_tflops": 197.0,  # MFU denominator (v5e bf16 peak)
    "observability.trace_slow_ms": 0.0,  # >0 = serve requests slower than
                                         # this emit full span detail +
                                         # histogram exemplars (tail
                                         # sampling; docs/OBSERVABILITY.md)
    "observability.flight_recorder_size": 256,  # last-N in-memory event
                                                # ring, dumped on stall/
                                                # chaos-red/crash (0 = off)
    "observability.scrape_interval_s": 5.0,  # FleetScraper background poll
                                             # cadence (start_scraper)
    "observability.memory_poll_s": 0.0,      # >0 = periodic HBM ledger
                                             # audit (jax.live_arrays sweep)
    # SLO objectives (observability/slo.py): evaluated over rolling
    # windows against the aggregated fleet view with multi-window
    # burn-rate alerting (fast/slow windows, SRE-workbook recipe)
    "slo.availability_target": 0.999,  # 1 - bad/admitted objective
    "slo.latency_p99_ms": 0.0,         # >0 = p99 total-latency budget (ms)
    "slo.ttft_p99_ms": 0.0,            # >0 = generate-lane TTFT p99 budget
    "slo.fast_window_s": 300.0,        # fast burn window (page-now signal)
    "slo.slow_window_s": 3600.0,       # slow burn window (sustained burn)
    "slo.fast_burn": 14.4,             # burn-rate threshold, fast window
    "slo.slow_burn": 6.0,              # burn-rate threshold, slow window
    # autopilot (control/autopilot.py — the SLO-driven control loop that
    # actuates router weights, replica count, admission quotas, and
    # rollout aborts from the scraper/SLO/ledger signals; every decision
    # and every suppressed decision is an `autopilot.*` event; see
    # docs/AUTOPILOT.md for the signal -> lever matrix and tuning runbook)
    "autopilot.enabled": False,        # `serve --autopilot` flips this on
    "autopilot.tick_s": 5.0,           # evaluation cadence (injectable
                                       # clock; one decide() per tick)
    "autopilot.min_replicas": 1,       # scale floor — also the repair
                                       # target after a replica death
    "autopilot.max_replicas": 8,       # scale ceiling (bounds veto)
    "autopilot.hbm_limit_bytes": 0,    # >0 = veto scale-up when projected
                                       # fleet HBM (ledger total + one
                                       # replica's share) would exceed it
    "autopilot.scale_up_queue": 4.0,   # mean queue depth per ready
                                       # replica at/above which the fleet
                                       # grows one replica
    "autopilot.scale_down_queue": 0.0,  # mean queue depth at/below which
                                        # an idle, non-burning fleet
                                        # shrinks (hysteresis gap vs up)
    "autopilot.scale_cooldown_s": 25.0,
    "autopilot.shift_error_rate": 0.5,  # per-tick failure fraction
                                        # at/above which traffic ramps
                                        # OFF a replica (outlier shift)
    "autopilot.shift_recover_rate": 0.05,  # fraction at/below which a
                                           # ready replica's weight ramps
                                           # back (separate up threshold)
    "autopilot.shift_step": 0.5,       # router weight moved per action
    "autopilot.shift_cooldown_s": 20.0,
    "autopilot.admission_factor": 0.5,  # capacity_rows multiplier per
                                        # tighten (relax divides by it)
    "autopilot.admission_floor_frac": 0.25,  # tighten floor as a fraction
                                             # of the baseline capacity
    "autopilot.admission_relax_burn": 1.0,  # fast burn at/below which a
                                            # tightened quota relaxes
    "autopilot.admission_cooldown_s": 25.0,
    "autopilot.reshard_wide": "",      # fifth lever: mesh shape to reshard
                                       # TO under HBM-ledger pressure
                                       # (e.g. "2x4" — wider tensor axis,
                                       # smaller per-chip shard); "" = off
    "autopilot.reshard_narrow": "",    # mesh shape to reshard TO when
                                       # queue depth wants replicas past
                                       # max_replicas (e.g. "4x2"); "" =
                                       # off. wide != narrow: the gap is
                                       # the hysteresis band
    "autopilot.reshard_hbm_frac": 0.85,  # HBM fraction of hbm_limit_bytes
                                         # at/above which the wide reshard
                                         # fires
    "autopilot.reshard_cooldown_s": 60.0,  # shared by BOTH directions (one
                                           # "reshard" cooldown key), so
                                           # placements cannot oscillate
    "autopilot.window_s": 120.0,       # rolling actuation-budget window
    "autopilot.max_actions_per_window": 8,  # hard budget: decisions past
                                            # it are suppressed ("window")
    "autopilot.scale_backend": "auto",  # what the scale lever actuates:
                                        # "inprocess" = Fleet server
                                        # threads, "process" = supervised
                                        # worker processes (Supervisor.
                                        # add_slot/retire_slot via
                                        # ProcessFleet), "auto" = process
                                        # when a supervisor backs the
                                        # fleet, else in-process
}

_lock = threading.Lock()
_overrides: Dict[str, Any] = {}


def _env_key(key: str) -> str:
    return "MMLSPARK_TPU_" + key.replace(".", "_").upper()


def get(key: str, default: Any = None) -> Any:
    """Resolve a config key (``namespace.name``)."""
    with _lock:
        if key in _overrides:
            return _overrides[key]
    env = os.environ.get(_env_key(key))
    if env is not None:
        base = _DEFAULTS.get(key, default)
        return _coerce(env, base)
    if key in _DEFAULTS:
        return _DEFAULTS[key]
    if default is not None:
        return default
    raise KeyError(f"unknown config key {key!r}; known: {sorted(_DEFAULTS)}")


def set(key: str, value: Any) -> None:  # noqa: A001 - mirrors typesafe API
    """Programmatic override (highest precedence). Unknown keys are allowed
    so applications can park their own knobs in the same tree."""
    with _lock:
        _overrides[key] = value


def unset(key: str) -> None:
    with _lock:
        _overrides.pop(key, None)


def snapshot() -> Dict[str, Any]:
    """Fully-resolved view of every known key (for logs / debugging)."""
    merged = dict(_DEFAULTS)
    with _lock:
        merged.update(_overrides)
    return {k: get(k, merged[k]) for k in sorted(merged)}


def _coerce(text: str, like: Any) -> Any:
    if isinstance(like, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(like, int):
        return int(text)
    if isinstance(like, float):
        return float(text)
    return text
