"""NativeLoader: build-on-demand + ctypes loading of the C++ runtime.

Re-expression of the reference's jar-resource native loader
(``core/env/src/main/scala/NativeLoader.java:29-193``): where the reference
extracted prebuilt ``.so``s from jars into a temp dir and ``System.load``ed
them per-partition, we compile the checked-in C++ sources once per machine
(g++, cached next to the sources) and bind via ctypes. No JNI, no jars.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")


def _lib_path() -> str:
    """Build target: next to the sources when writable (cached per checkout),
    else a per-user 0700 temp dir (read-only site-packages installs). The
    temp dir must be OWNED by us and not group/world-writable before we will
    dlopen anything out of it — a predictable /tmp name that an attacker
    pre-created with a planted .so must not be trusted. Called lazily from
    load_native() so merely importing this module touches no filesystem."""
    if os.access(_NATIVE_DIR, os.W_OK):
        return os.path.join(_NATIVE_DIR, "libmmlimage.so")
    import tempfile
    d = os.path.join(tempfile.gettempdir(),
                     f"mmlspark_tpu_native_{os.getuid()}")
    try:
        os.makedirs(d, mode=0o700)
    except FileExistsError:
        st = os.lstat(d)
        if (st.st_uid != os.getuid() or not os.path.isdir(d)
                or os.path.islink(d) or (st.st_mode & 0o022)):
            d = tempfile.mkdtemp(prefix="mmlspark_tpu_native_")
    return os.path.join(d, "libmmlimage.so")


_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[str] = None


def _build(lib_path: str) -> None:
    cmd = ["g++", "-O2", "-fPIC", "-shared",
           os.path.join(_NATIVE_DIR, "imagecodec.cc"),
           "-o", lib_path, "-ljpeg", "-lpng", "-lpthread"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr[-2000:]}")


def load_native():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            lib_path = _lib_path()
            src = os.path.join(_NATIVE_DIR, "imagecodec.cc")
            if (not os.path.exists(lib_path)
                    or os.path.getmtime(lib_path) < os.path.getmtime(src)):
                _build(lib_path)
            lib = ctypes.CDLL(lib_path)
            lib.mml_decode_jpeg.restype = ctypes.c_int
            lib.mml_decode_jpeg.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
            lib.mml_decode_png.restype = ctypes.c_int
            lib.mml_decode_png.argtypes = lib.mml_decode_jpeg.argtypes
            lib.mml_encode_jpeg.restype = ctypes.c_int
            lib.mml_encode_jpeg.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.POINTER(ctypes.c_ulong)]
            lib.mml_free.restype = None
            lib.mml_free.argtypes = [ctypes.c_void_p]
            lib.mml_decode_batch.restype = ctypes.c_int
            lib.mml_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_long), ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.c_int]
            _lib = lib
        except (RuntimeError, OSError) as e:
            _load_error = str(e)
            _lib = None
        return _lib


def native_available() -> bool:
    return load_native() is not None


def _take_buffer(lib, out_ptr, w: int, h: int) -> np.ndarray:
    n = w * h * 3
    arr = np.ctypeslib.as_array(out_ptr, shape=(n,)).copy()
    lib.mml_free(out_ptr)
    return arr.reshape(h, w, 3)


def native_decode_jpeg(data: bytes) -> Optional[np.ndarray]:
    lib = load_native()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_ubyte)()
    w, h = ctypes.c_int(), ctypes.c_int()
    rc = lib.mml_decode_jpeg(data, len(data), ctypes.byref(out),
                             ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return None
    return _take_buffer(lib, out, w.value, h.value)


def native_decode_png(data: bytes) -> Optional[np.ndarray]:
    lib = load_native()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_ubyte)()
    w, h = ctypes.c_int(), ctypes.c_int()
    rc = lib.mml_decode_png(data, len(data), ctypes.byref(out),
                            ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return None
    return _take_buffer(lib, out, w.value, h.value)


def native_encode_jpeg(img: np.ndarray, quality: int = 90) -> Optional[bytes]:
    lib = load_native()
    if lib is None:
        return None
    img = np.ascontiguousarray(img, dtype=np.uint8)
    h, w, _ = img.shape
    out = ctypes.POINTER(ctypes.c_ubyte)()
    size = ctypes.c_ulong()
    rc = lib.mml_encode_jpeg(img.tobytes(), w, h, quality,
                             ctypes.byref(out), ctypes.byref(size))
    if rc != 0:
        return None
    data = ctypes.string_at(out, size.value)
    lib.mml_free(out)
    return data


def native_decode_batch(blobs: List[bytes],
                        n_threads: int = 8) -> List[Optional[np.ndarray]]:
    """Threaded batch decode (JPEG/PNG); None entries for failures."""
    lib = load_native()
    if lib is None:
        return [None] * len(blobs)
    n = len(blobs)
    if n == 0:
        return []
    datas = (ctypes.c_char_p * n)(*blobs)
    sizes = (ctypes.c_long * n)(*[len(b) for b in blobs])
    outs = (ctypes.POINTER(ctypes.c_ubyte) * n)()
    widths = (ctypes.c_int * n)()
    heights = (ctypes.c_int * n)()
    lib.mml_decode_batch(datas, sizes, n, outs, widths, heights, n_threads)
    results: List[Optional[np.ndarray]] = []
    for i in range(n):
        if widths[i] == 0 or not outs[i]:
            results.append(None)
        else:
            results.append(_take_buffer(lib, outs[i], widths[i], heights[i]))
    return results
