"""Profiling hooks: jax profiler traces + named step annotations.

SURVEY.md §5 sets the bar above the reference (which had nothing beyond
test wall-clock timing): here any train/score loop can capture an XLA
trace viewable in TensorBoard/Perfetto. The capture dir comes from the
``profiling.trace_dir`` config key or the ``trace`` argument, so a
production run can be flipped into a profiled run by env var alone
(``MMLSPARK_TPU_PROFILING_TRACE_DIR=/tmp/trace``).

Both hooks are failure-safe: a missing/broken jax profiler backend turns
them into logged no-ops (a production run must never die because its
*instrumentation* could not start), and nested ``trace()`` calls — which
the jax profiler rejects with a hard error — degrade to a warning + no-op
for the inner call, keeping the outer capture alive.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from mmlspark_tpu.utils import config
from mmlspark_tpu.utils.logging import get_logger

_lock = threading.Lock()
_tracing = False


@contextlib.contextmanager
def trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax profiler trace for the enclosed region.

    No-op when neither ``trace_dir`` nor the ``profiling.trace_dir`` config
    key is set — safe to leave in production code paths. Also a no-op
    (with a warning) when a trace is already being captured or the
    profiler backend refuses to start.
    """
    global _tracing
    target = trace_dir if trace_dir is not None else config.get(
        "profiling.trace_dir")
    if not target:
        yield
        return
    with _lock:
        if _tracing:
            get_logger("profiling").warning(
                "nested trace(%s) ignored: a capture is already running",
                target)
            nested = True
        else:
            _tracing = True
            nested = False
    if nested:
        yield
        return
    ctx = None
    try:
        try:
            import jax
            ctx = jax.profiler.trace(target)
            ctx.__enter__()
            get_logger("profiling").info("capturing jax trace to %s", target)
        except Exception as e:
            ctx = None
            get_logger("profiling").warning(
                "jax profiler unavailable (%s: %s); trace() is a no-op",
                type(e).__name__, e)
        try:
            yield
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
    finally:
        with _lock:
            _tracing = False


def annotate(name: str):
    """Named trace region (shows up in the profiler timeline); degrades to
    a null context when the jax profiler is unavailable."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception as e:
        get_logger("profiling").debug(
            "TraceAnnotation unavailable (%s: %s); annotate(%r) is a no-op",
            type(e).__name__, e, name)
        return contextlib.nullcontext()
