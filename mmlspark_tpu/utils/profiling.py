"""Profiling hooks: jax profiler traces + named step annotations.

SURVEY.md §5 sets the bar above the reference (which had nothing beyond
test wall-clock timing): here any train/score loop can capture an XLA
trace viewable in TensorBoard/Perfetto. The capture dir comes from the
``profiling.trace_dir`` config key or the ``trace`` argument, so a
production run can be flipped into a profiled run by env var alone
(``MMLSPARK_TPU_PROFILING_TRACE_DIR=/tmp/trace``).
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from mmlspark_tpu.utils import config
from mmlspark_tpu.utils.logging import get_logger


@contextlib.contextmanager
def trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax profiler trace for the enclosed region.

    No-op when neither ``trace_dir`` nor the ``profiling.trace_dir`` config
    key is set — safe to leave in production code paths.
    """
    target = trace_dir if trace_dir is not None else config.get(
        "profiling.trace_dir")
    if not target:
        yield
        return
    import jax
    get_logger("profiling").info("capturing jax trace to %s", target)
    with jax.profiler.trace(target):
        yield


def annotate(name: str):
    """Named trace region (shows up in the profiler timeline)."""
    import jax
    return jax.profiler.TraceAnnotation(name)
