"""Logging + training-metric observability.

Re-expression of the reference's logger factory
(``core/env/src/main/scala/Logging.scala:14-23``): every framework logger
hangs off the ``mmlspark_tpu`` root so one call configures the tree, with
the level driven by the config tier (``utils/config.py``). On top of it,
``MetricLogger`` provides the train-loop observability the reference lacked
(SURVEY.md §5 sets the bar above the reference): step / loss /
examples-per-sec at a configurable cadence, with device scalars fetched
lazily so logging never forces a per-step sync.
"""
from __future__ import annotations

import logging
import sys
from collections import deque
from typing import Any, Dict, Optional

from mmlspark_tpu.utils import config

_ROOT = "mmlspark_tpu"
_configured = False


def get_logger(name: str = "") -> logging.Logger:
    """Logger under the framework root (``mmlspark_tpu.<name>``)."""
    global _configured
    if not _configured:
        root = logging.getLogger(_ROOT)
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%H:%M:%S"))
            root.addHandler(handler)
        root.setLevel(config.get("logging.level"))
        root.propagate = False
        _configured = True
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def set_level(level: str) -> None:
    config.set("logging.level", level)
    logging.getLogger(_ROOT).setLevel(level)


class MetricLogger:
    """Throttled train-loop metrics: step, loss, examples/sec.

    ``log(step, metrics, batch_rows)`` is cheap when the step is off-cadence
    (no device sync, no string work). On-cadence it converts the device
    scalar (one sync), computes throughput over the interval, logs, keeps a
    bounded history (``logging.history_max`` entries — a million-step run
    must not grow a million dicts), and forwards through the telemetry layer
    (registry gauges + a ``train.step`` event when the event log is on), so
    training metrics ride the same pipeline as every other signal.

    The throughput baseline is established on the FIRST call, not at
    construction: the gap between construction and the first step holds jit
    compilation, so an at-construction baseline skews the first interval's
    ``examples_per_sec`` arbitrarily low. A first call that is itself
    on-cadence has no measured interval yet and reports rate 0.0.
    """

    def __init__(self, every: Optional[int] = None, name: str = "train",
                 history_max: Optional[int] = None):
        self.every = (config.get("logging.metrics_every")
                      if every is None else every)
        self.log = get_logger(name)
        self.history: deque = deque(maxlen=(
            config.get("logging.history_max")
            if history_max is None else history_max))
        self._last_time: Optional[float] = None
        self._rows_since = 0

    def __call__(self, step: int, metrics: Dict[str, Any],
                 batch_rows: int = 0) -> None:
        self._rows_since += batch_rows
        if not self.every or step % self.every != 0:
            return
        from mmlspark_tpu.observability import events, metrics as obsmetrics
        now = events.perf()
        if self._last_time is None:
            rate = 0.0  # no baseline yet: unmeasurable, not skewed
        else:
            rate = self._rows_since / max(now - self._last_time, 1e-9)
        vals = {k: float(v) for k, v in metrics.items()}
        self.history.append({"step": step, **vals, "examples_per_sec": rate})
        body = " ".join(f"{k}={v:.5g}" for k, v in vals.items())
        self.log.info("step %d %s examples/sec=%.1f", step, body, rate)
        for k, v in vals.items():
            obsmetrics.gauge(f"train.{k}").set(v)
        obsmetrics.gauge("train.examples_per_sec").set(rate)
        if events.events_enabled():
            events.emit("metric", "train.step", step=step,
                        examples_per_sec=round(rate, 3), values=vals)
        self._last_time = now
        self._rows_since = 0
