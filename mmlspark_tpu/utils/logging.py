"""Logging + training-metric observability.

Re-expression of the reference's logger factory
(``core/env/src/main/scala/Logging.scala:14-23``): every framework logger
hangs off the ``mmlspark_tpu`` root so one call configures the tree, with
the level driven by the config tier (``utils/config.py``). On top of it,
``MetricLogger`` provides the train-loop observability the reference lacked
(SURVEY.md §5 sets the bar above the reference): step / loss /
examples-per-sec at a configurable cadence, with device scalars fetched
lazily so logging never forces a per-step sync.
"""
from __future__ import annotations

import logging
import sys
import time
from typing import Any, Dict, Optional

from mmlspark_tpu.utils import config

_ROOT = "mmlspark_tpu"
_configured = False


def get_logger(name: str = "") -> logging.Logger:
    """Logger under the framework root (``mmlspark_tpu.<name>``)."""
    global _configured
    if not _configured:
        root = logging.getLogger(_ROOT)
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%H:%M:%S"))
            root.addHandler(handler)
        root.setLevel(config.get("logging.level"))
        root.propagate = False
        _configured = True
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def set_level(level: str) -> None:
    config.set("logging.level", level)
    logging.getLogger(_ROOT).setLevel(level)


class MetricLogger:
    """Throttled train-loop metrics: step, loss, examples/sec.

    ``log(step, metrics, batch_rows)`` is cheap when the step is off-cadence
    (no device sync, no string work). On-cadence it converts the device
    scalar (one sync), computes throughput over the interval, logs, and
    remembers the history for post-hoc inspection.
    """

    def __init__(self, every: Optional[int] = None, name: str = "train"):
        self.every = (config.get("logging.metrics_every")
                      if every is None else every)
        self.log = get_logger(name)
        self.history: list = []
        self._last_time = time.perf_counter()
        self._rows_since = 0

    def __call__(self, step: int, metrics: Dict[str, Any],
                 batch_rows: int = 0) -> None:
        self._rows_since += batch_rows
        if not self.every or step % self.every != 0:
            return
        now = time.perf_counter()
        dt = max(now - self._last_time, 1e-9)
        rate = self._rows_since / dt
        vals = {k: float(v) for k, v in metrics.items()}
        self.history.append({"step": step, **vals, "examples_per_sec": rate})
        body = " ".join(f"{k}={v:.5g}" for k, v in vals.items())
        self.log.info("step %d %s examples/sec=%.1f", step, body, rate)
        self._last_time = now
        self._rows_since = 0
