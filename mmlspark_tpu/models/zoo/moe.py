"""Mixture-of-Experts: top-k routed FFN sharded over the ``expert`` axis.

The one parallelism family SURVEY.md §2.6 lists that the reference era never
had — built the TPU way (GShard/Switch style):

- the router is a tiny fp32 Dense; each token picks its top-k experts;
- dispatch/combine are EINSUMS against one-hot capacity tensors — no
  gather/scatter, so the whole layer stays MXU-shaped and XLA lowers the
  token movement to an all-to-all over the ``expert`` mesh axis (the
  sharding rules place the leading E dim of ``experts_up``/``experts_down``
  on ``expert``, ``parallel/sharding.DEFAULT_RULES``);
- per-expert capacity C = ceil(capacity_factor * S * k / E); overflow
  tokens fall through the residual (standard GShard drop policy);
- the load-balancing auxiliary loss (Shazeer et al.: E * mean_e(frac
  tokens routed to e) . mean_e(router prob of e)) is sown under
  ``("losses", "moe_aux")`` for the trainer to add.

``transformer_lm_moe`` swaps the dense MLP of every other decoder block
for this layer (via TransformerLM's pluggable block/ffn factories) — the flagship composition: ring/Ulysses attention over ``seq``,
tensor-parallel projections, expert-parallel FFNs, all in one jitted step.
"""
from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from mmlspark_tpu.models.zoo import register_model


class MoeMlp(nn.Module):
    dim: int
    num_experts: int = 8
    expert_hidden: Optional[int] = None   # default 4*dim
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        """x (B, L, D) -> (B, L, D); sows the aux loss under losses/moe_aux."""
        B, L, D = x.shape
        E, K = self.num_experts, self.top_k
        H = self.expert_hidden or 4 * D
        S = B * L
        C = max(1, math.ceil(self.capacity_factor * S * K / E))
        xf = x.reshape(S, D)

        # Router in fp32: tiny matmul, numerically owns the gating decision.
        logits = nn.Dense(E, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="router")(xf.astype(jnp.float32))   # (S, E)
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, K)              # (S, K)
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # Position of each (token, choice) within its expert's capacity:
        # choices fill expert slots in (choice-priority, token-order) —
        # first every token's 1st choice, then 2nd choices, like GShard.
        # Counting is int32: an fp32 cumsum loses exactness past 2^24
        # token-choices, silently colliding capacity slots at long context.
        onehot_i = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)    # (S, K, E)
        flat = onehot_i.transpose(1, 0, 2).reshape(K * S, E)       # (K*S, E)
        pos_flat = jnp.cumsum(flat, axis=0) - flat                 # slots used
        position = (pos_flat.reshape(K, S, E).transpose(1, 0, 2)
                    * onehot_i).sum(-1)                            # (S, K)
        keep = (position < C) & (onehot_i.sum(-1) > 0)             # (S, K)
        onehot = onehot_i.astype(jnp.float32)

        # dispatch (S, K, E, C) collapsed over K -> (S, E, C)
        cap_onehot = jax.nn.one_hot(position, C, dtype=jnp.float32)
        dispatch = jnp.einsum("ske,skc->sec",
                              onehot * keep[..., None], cap_onehot)
        combine = jnp.einsum("ske,skc->sec",
                             onehot * (gate_vals * keep)[..., None],
                             cap_onehot)

        w_up = self.param("experts_up", nn.initializers.lecun_normal(),
                          (E, D, H), jnp.float32).astype(self.dtype)
        w_down = self.param("experts_down", nn.initializers.lecun_normal(),
                            (E, H, D), jnp.float32).astype(self.dtype)
        # all-to-all happens here under GSPMD: xe is expert-sharded, xf is
        # batch-sharded
        xe = jnp.einsum("sec,sd->ecd", dispatch.astype(self.dtype), xf)
        h = nn.gelu(jnp.einsum("ecd,edh->ech", xe, w_up))
        ye = jnp.einsum("ech,ehd->ecd", h, w_down)
        y = jnp.einsum("sec,ecd->sd", combine.astype(self.dtype), ye)

        # Load-balancing aux loss (fp32, scheme-standard scale E). Sown only
        # outside init so the 'losses' collection never leaks into the
        # trainable param tree (the optimizer must not "train" a buffer).
        if not self.is_initializing():
            frac_routed = (onehot[:, 0, :]).mean(axis=0)  # 1st-choice share
            mean_prob = probs.mean(axis=0)
            aux = E * jnp.sum(frac_routed * mean_prob)
            self.sow("losses", "moe_aux", aux)
        return y.reshape(B, L, D)


def _moe_lm(vocab, dim, depth, heads, max_len, num_experts, top_k,
            capacity_factor, dtype, attention_fn):
    """TransformerLM whose odd blocks swap the dense MLP for MoeMlp via the
    pluggable block/ffn factories — zero duplication of the attention half
    or the embedding/tied-head trunk (``zoo/transformer.py``)."""
    from mmlspark_tpu.models.zoo.transformer import DecoderBlock, TransformerLM

    def block_factory(i, name):
        ffn = None
        if i % 2 == 1:
            def ffn(fname):
                return MoeMlp(dim, num_experts=num_experts, top_k=top_k,
                              capacity_factor=capacity_factor, dtype=dtype,
                              name=fname)
        return DecoderBlock(dim, heads, dtype=dtype,
                            attention_fn=attention_fn, ffn_factory=ffn,
                            name=name)

    return TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                         max_len=max_len, dtype=dtype,
                         attention_fn=attention_fn,
                         block_factory=block_factory)


def moe_aux_loss(variables) -> jnp.ndarray:
    """Sum of every sown moe_aux term in a ``mutable=['losses']`` pass."""
    losses = variables.get("losses", {})
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(losses):
        total = total + jnp.sum(leaf)
    return total


@register_model("transformer_lm_moe")
def transformer_lm_moe(vocab: int = 32000, dim: int = 512, depth: int = 6,
                       heads: int = 8, max_len: int = 2048,
                       num_experts: int = 8, top_k: int = 2,
                       capacity_factor: float = 1.25,
                       dtype=jnp.bfloat16, attention_fn=None):
    return dict(
        module=_moe_lm(vocab, dim, depth, heads, max_len, num_experts,
                       top_k, capacity_factor, dtype, attention_fn),
        input_shape=(max_len,), input_dtype="int32",
        feature_layer="hidden", feature_dim=dim,
        layer_names=["hidden", "logits"],
        # decoder blocks use the (q, k, v, causal) attention contract, so
        # the ring/Ulysses kernels can be swapped in for seq-parallel runs
        seq_attention=True,
    )


@register_model("transformer_lm_moe_tiny")
def transformer_lm_moe_tiny(vocab: int = 256, dim: int = 64, depth: int = 2,
                            heads: int = 4, max_len: int = 128,
                            num_experts: int = 4, top_k: int = 2,
                            capacity_factor: float = 2.0,
                            dtype=jnp.float32, attention_fn=None):
    """Test-scale MoE LM (fp32; generous capacity so tiny batches route)."""
    return dict(
        module=_moe_lm(vocab, dim, depth, heads, max_len, num_experts,
                       top_k, capacity_factor, dtype, attention_fn),
        input_shape=(max_len,), input_dtype="int32",
        feature_layer="hidden", feature_dim=dim,
        layer_names=["hidden", "logits"],
        # decoder blocks use the (q, k, v, causal) attention contract, so
        # the ring/Ulysses kernels can be swapped in for seq-parallel runs
        seq_attention=True,
    )
