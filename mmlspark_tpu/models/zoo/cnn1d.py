"""1-D CNN text classifier — TextFeaturizer + CNN on Amazon reviews
(BASELINE.json config 4).

Input: integer token ids (B, L) -> embedding -> parallel conv widths ->
global max pool -> dense head. All convs NWC so XLA maps them to the MXU.
"""
from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.zoo import register_model


class TextCNN(nn.Module):
    vocab_size: int
    embed_dim: int = 128
    kernel_sizes: Sequence[int] = (3, 4, 5)
    filters: int = 128
    num_classes: int = 2
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, ids):
        x = nn.Embed(self.vocab_size, self.embed_dim, name="embedding",
                     dtype=self.dtype)(ids)
        pools = []
        for k in self.kernel_sizes:
            h = nn.Conv(self.filters, (k,), padding="SAME", dtype=self.dtype,
                        name=f"conv{k}")(x)
            h = nn.relu(h)
            pools.append(jnp.max(h, axis=1))
        x = jnp.concatenate(pools, axis=-1).astype(jnp.float32)
        self.sow("intermediates", "pool", x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


@register_model("textcnn")
def textcnn(vocab_size: int = 1 << 15, embed_dim: int = 128,
            num_classes: int = 2, seq_len: int = 256, dtype=jnp.bfloat16):
    m = TextCNN(vocab_size=vocab_size, embed_dim=embed_dim,
                num_classes=num_classes, dtype=dtype)
    return dict(
        module=m, input_shape=(seq_len,), input_dtype="int32",
        feature_layer="pool", feature_dim=128 * 3,
        layer_names=["pool", "head"],
    )
