"""Decoder-only transformer LM — the long-context flagship.

The model family the sequence-parallel layer exists for: every block calls a
pluggable ``attention_fn(q, k, v, causal=...)`` so the same module runs
single-device (``full_attention``), context-parallel (``ring_attention``)
or all-to-all (``ulysses_attention``) — see ``parallel/sequence.py``.

Param names are chosen to hit the tensor-parallel sharding rules
(``parallel/sharding.DEFAULT_RULES``): ``attn_query/key/value`` kernels shard
(fsdp, tensor), ``attn_out`` (tensor, fsdp), ``mlp_up``/``mlp_down``
likewise, token embedding shards vocab over ``tensor``.

bfloat16 compute, fp32 norms and logits (MXU-friendly).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.zoo import register_model
from mmlspark_tpu.parallel.sequence import full_attention


class DecoderBlock(nn.Module):
    """Pre-norm decoder block with pluggable attention AND FFN.

    ``ffn_factory(name) -> nn.Module`` swaps the dense MLP for a routed one
    (``zoo/moe.MoeMlp``) without duplicating the attention half — there is
    exactly one attention implementation to fix.
    """
    dim: int
    heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    ffn_factory: Optional[Callable[[str], nn.Module]] = None

    @nn.compact
    def __call__(self, x):
        B, L, _ = x.shape
        D = self.dim // self.heads
        attn_fn = self.attention_fn or full_attention
        y = nn.LayerNorm(dtype=jnp.float32, name="norm1")(x)
        q = nn.Dense(self.dim, dtype=self.dtype, name="attn_query")(y)
        k = nn.Dense(self.dim, dtype=self.dtype, name="attn_key")(y)
        v = nn.Dense(self.dim, dtype=self.dtype, name="attn_value")(y)
        shape = (B, L, self.heads, D)
        o = attn_fn(q.reshape(shape), k.reshape(shape), v.reshape(shape),
                    causal=True)
        x = x + nn.Dense(self.dim, dtype=self.dtype,
                         name="attn_out")(o.reshape(B, L, self.dim))
        y = nn.LayerNorm(dtype=jnp.float32, name="norm2")(x)
        if self.ffn_factory is not None:
            return x + self.ffn_factory("ffn")(y)
        h = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype,
                     name="mlp_up")(y)
        h = nn.gelu(h)
        x = x + nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x


class TransformerLM(nn.Module):
    """Decoder LM trunk. ``block_factory(layer_idx, name) -> nn.Module``
    customizes individual layers (e.g. MoE FFNs on odd layers) while the
    embedding / positional / tied-head plumbing stays in one place."""
    vocab: int = 32000
    dim: int = 512
    depth: int = 6
    heads: int = 8
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    block_factory: Optional[Callable[[int, str], nn.Module]] = None

    @nn.compact
    def __call__(self, tokens):
        """tokens (B, L) int32 -> logits (B, L, vocab) fp32."""
        B, L = tokens.shape
        emb = nn.Embed(self.vocab, self.dim, dtype=self.dtype,
                       name="token_embedding")
        x = emb(tokens)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (1, self.max_len, self.dim), jnp.float32)
        x = x + pos[:, :L].astype(x.dtype)
        for i in range(self.depth):
            if self.block_factory is not None:
                block = self.block_factory(i, f"block{i}")
            else:
                block = DecoderBlock(self.dim, self.heads, dtype=self.dtype,
                                     attention_fn=self.attention_fn,
                                     name=f"block{i}")
            x = block(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_norm")(x)
        self.sow("intermediates", "hidden", x)
        # tied head, explicitly fp32 (Embed.attend would demote to self.dtype)
        table = self.get_variable("params", "token_embedding")["embedding"]
        return jnp.einsum("bld,vd->blv", x.astype(jnp.float32),
                          table.astype(jnp.float32))


@register_model("transformer_lm")
def transformer_lm(vocab: int = 32000, dim: int = 512, depth: int = 6,
                   heads: int = 8, max_len: int = 2048,
                   dtype=jnp.bfloat16, attention_fn=None):
    return dict(
        module=TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                             max_len=max_len, dtype=dtype,
                             attention_fn=attention_fn),
        input_shape=(max_len,), input_dtype="int32",
        feature_layer="hidden", feature_dim=dim,
        layer_names=["hidden", "logits"],
        # decoder blocks use the (q, k, v, causal) attention contract, so
        # the ring/Ulysses kernels can be swapped in for seq-parallel runs
        seq_attention=True,
    )


@register_model("transformer_lm_tiny")
def transformer_lm_tiny(vocab: int = 256, dim: int = 64, depth: int = 2,
                        heads: int = 4, max_len: int = 128,
                        dtype=jnp.float32, attention_fn=None):
    """Test-scale LM (fp32 so CPU-mesh parity checks are tight)."""
    return dict(
        module=TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads,
                             max_len=max_len, dtype=dtype,
                             attention_fn=attention_fn),
        input_shape=(max_len,), input_dtype="int32",
        feature_layer="hidden", feature_dim=dim,
        layer_names=["hidden", "logits"],
        # decoder blocks use the (q, k, v, causal) attention contract, so
        # the ring/Ulysses kernels can be swapped in for seq-parallel runs
        seq_attention=True,
    )
