"""Model zoo registry: architecture name -> constructor.

The TPU-native analogue of the reference's pretrained-model repository
schema (``downloader/src/main/scala/Schema.scala:31-92``): every
architecture registers under a stable name with its input spec and the
ordered layer names available for feature extraction (the reference's
``layerNames``/``cutOutputLayers`` contract, ``ImageFeaturizer.scala:85-120``).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

_ZOO: Dict[str, Callable] = {}


def register_model(name: str):
    def wrap(fn):
        _ZOO[name] = fn
        return fn
    return wrap


def build_model(name: str, **kwargs):
    if name not in _ZOO:
        raise KeyError(f"unknown architecture {name!r}; have {sorted(_ZOO)}")
    return _ZOO[name](**kwargs)


def available_models() -> List[str]:
    return sorted(_ZOO)


# populate the registry
from mmlspark_tpu.models.zoo import resnet as _resnet  # noqa: E402,F401
from mmlspark_tpu.models.zoo import mlp as _mlp  # noqa: E402,F401
from mmlspark_tpu.models.zoo import cnn1d as _cnn1d  # noqa: E402,F401
from mmlspark_tpu.models.zoo import vit as _vit  # noqa: E402,F401
from mmlspark_tpu.models.zoo import transformer as _transformer  # noqa: E402,F401
from mmlspark_tpu.models.zoo import moe as _moe  # noqa: E402,F401
from mmlspark_tpu.embed import model as _recommender  # noqa: E402,F401
