"""ViT-B/16 — target of the fused-Pallas-preprocessing config
(BASELINE.json config 5) and the long-context flagship: every encoder block
takes a pluggable ``attention_fn``, the hook through which the sequence-
parallel/ring attention implementations in ``mmlspark_tpu.parallel`` are
swapped in for long inputs.

Standard pre-norm ViT: patchify conv -> [CLS] -> encoder blocks
(MHA + MLP, GELU) -> head. bfloat16 compute, fp32 norms/logits.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.zoo import register_model


class MlpBlock(nn.Module):
    dim: int
    hidden: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden, dtype=self.dtype, name="mlp_up")(x)
        h = nn.gelu(h)
        return nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)


class EncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None  # pluggable (ring attention)

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=jnp.float32, name="norm1")(x)
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, dtype=self.dtype, name="attn",
            attention_fn=self.attention_fn or nn.dot_product_attention)
        x = x + attn(y, y)
        y = nn.LayerNorm(dtype=jnp.float32, name="norm2")(x)
        x = x + MlpBlock(self.dim, self.dim * self.mlp_ratio, self.dtype,
                         name="mlp")(y)
        return x


class ViT(nn.Module):
    patch: int = 16
    dim: int = 768
    depth: int = 12
    heads: int = 12
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        B = x.shape[0]
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), dtype=self.dtype,
                    name="patch_embedding")(x.astype(self.dtype))
        x = x.reshape(B, -1, self.dim)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.dim),
                         jnp.float32)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(x.dtype),
                                              (B, 1, self.dim)), x], axis=1)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.dim), jnp.float32)
        x = x + pos.astype(x.dtype)
        for i in range(self.depth):
            x = EncoderBlock(self.dim, self.heads, dtype=self.dtype,
                             attention_fn=self.attention_fn,
                             name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_norm")(x)
        x = x[:, 0]
        self.sow("intermediates", "pool", x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


@register_model("vit_b16")
def vit_b16(num_classes: int = 1000, image_size: int = 224,
            dtype=jnp.bfloat16, attention_fn=None):
    return dict(
        module=ViT(patch=16, dim=768, depth=12, heads=12,
                   num_classes=num_classes, dtype=dtype,
                   attention_fn=attention_fn),
        input_shape=(image_size, image_size, 3),
        feature_layer="pool", feature_dim=768,
        layer_names=["pool", "head"],
    )


@register_model("vit_tiny")
def vit_tiny(num_classes: int = 10, image_size: int = 32, patch: int = 4,
             dtype=jnp.bfloat16, attention_fn=None):
    """Small ViT for tests and CIFAR-scale experiments."""
    return dict(
        module=ViT(patch=patch, dim=192, depth=4, heads=3,
                   num_classes=num_classes, dtype=dtype,
                   attention_fn=attention_fn),
        input_shape=(image_size, image_size, 3),
        feature_layer="pool", feature_dim=192,
        layer_names=["pool", "head"],
    )
