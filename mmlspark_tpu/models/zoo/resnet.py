"""ResNets: CIFAR-style ResNet-20 (the benchmark flagship) and ResNet-50.

TPU-first choices:
- GroupNorm instead of BatchNorm: no running statistics to synchronize
  across data-parallel replicas, fully functional apply (one pure fn to jit
  and shard), identical behavior train/eval — the SPMD-friendly norm.
- NHWC layout (XLA TPU's native conv layout), bfloat16 compute with fp32
  params and fp32 logits: convs hit the MXU at full rate.
- Named stages/blocks so intermediates can be selected by layer name for
  transfer-learning featurization (the reference's ``cutOutputLayers``
  contract on CNTK graphs, ``image-featurizer/src/main/scala/ImageFeaturizer.scala:93-120``).

Scoring parity target: the CNTK CIFAR-10 ConvNet eval path of notebook 301
(``cntk-model/src/test/scala/CNTKTestUtils.scala``).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mmlspark_tpu.models.zoo import register_model


class ResidualBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        y = nn.GroupNorm(num_groups=min(32, self.features),
                         dtype=jnp.float32, name="norm1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = nn.GroupNorm(num_groups=min(32, self.features),
                         dtype=jnp.float32, name="norm2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj")(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.features),
                                    dtype=jnp.float32, name="proj_norm")(residual)
        return nn.relu(y + residual.astype(y.dtype))


class BottleneckBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        y = nn.GroupNorm(num_groups=min(32, self.features), dtype=jnp.float32,
                         name="norm1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = nn.GroupNorm(num_groups=min(32, self.features), dtype=jnp.float32,
                         name="norm2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv3")(y)
        y = nn.GroupNorm(num_groups=min(32, self.features * 4),
                         dtype=jnp.float32, name="norm3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype,
                               name="proj")(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.features * 4),
                                    dtype=jnp.float32, name="proj_norm")(residual)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    """stage_sizes blocks per stage; CIFAR stem (3x3) or ImageNet stem (7x7)."""
    stage_sizes: Sequence[int]
    num_classes: int = 10
    width: int = 16
    bottleneck: bool = False
    cifar_stem: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype, name="stem_conv")(x)
        else:
            x = nn.Conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype, name="stem_conv")(x)
            x = nn.GroupNorm(num_groups=min(32, self.width), dtype=jnp.float32,
                             name="stem_norm")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block = BottleneckBlock if self.bottleneck else ResidualBlock
        for i, n_blocks in enumerate(self.stage_sizes):
            features = self.width * (2 ** i)
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(features, strides, self.dtype,
                          name=f"stage{i}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        self.sow("intermediates", "pool", x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


@register_model("resnet20_cifar")
def resnet20_cifar(num_classes: int = 10, dtype=jnp.bfloat16):
    return dict(
        module=ResNet(stage_sizes=[3, 3, 3], num_classes=num_classes,
                      width=16, bottleneck=False, cifar_stem=True, dtype=dtype),
        input_shape=(32, 32, 3),
        feature_layer="pool", feature_dim=64,
        layer_names=["pool", "head"],
    )


@register_model("resnet50")
def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16):
    return dict(
        module=ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes,
                      width=64, bottleneck=True, cifar_stem=False, dtype=dtype),
        input_shape=(224, 224, 3),
        feature_layer="pool", feature_dim=2048,
        layer_names=["pool", "head"],
    )


def apply_with_intermediates(module: nn.Module, params, x,
                             capture_all: bool = False):
    """Forward returning (logits, {layer_name: activation}) for layer
    selection. By default only EXPLICITLY sown layers are recorded (the
    zoo's named feature layers) — ``capture_intermediates=True`` records
    every submodule output, which costs ~3x at runtime on a ResNet-50 even
    after DCE; pass ``capture_all=True`` only when the requested node is
    not an explicit sow."""
    kwargs = {"capture_intermediates": True} if capture_all else {}
    logits, state = module.apply(params, x, mutable=["intermediates"],
                                 **kwargs)
    inters = {}

    def walk(prefix, tree):
        for k, v in tree.items():
            if isinstance(v, dict):
                walk(f"{prefix}{k}/", v)
            else:
                inters[f"{prefix}{k}".replace("__call__", "out").rstrip("/")] = \
                    v[0] if isinstance(v, tuple) else v
    # modules that sow nothing return a state dict without the collection
    walk("", state.get("intermediates", {}))
    inters["head"] = logits
    return logits, inters
