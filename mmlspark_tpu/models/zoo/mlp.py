"""MLP for tabular frames — the deep path of TrainClassifier on Adult Census
(BASELINE.json config 3)."""
from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.zoo import register_model


class MLP(nn.Module):
    hidden: Sequence[int]
    num_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h, dtype=self.dtype, name=f"mlp_fc{i}")(x)
            x = nn.relu(x)
        x = x.astype(jnp.float32)
        self.sow("intermediates", "pool", x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


@register_model("mlp_tabular")
def mlp_tabular(input_dim: int = 128, hidden=(512, 256), num_classes: int = 2,
                dtype=jnp.bfloat16):
    return dict(
        module=MLP(hidden=tuple(hidden), num_classes=num_classes, dtype=dtype),
        input_shape=(input_dim,),
        feature_layer="pool", feature_dim=hidden[-1],
        layer_names=["pool", "head"],
    )
