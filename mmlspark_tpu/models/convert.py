"""Pretrained-weight import: standard checkpoint formats -> zoo params.

The reference's ModelDownloader served ~20 actually-trained CNTK models
(``downloader/src/main/scala/ModelDownloader.scala:24-260``); the repository
mechanics here (LocalRepo/HttpRepo, sha256, MANIFEST) are format-complete
but need real payloads. This module feeds them from the two checkpoint
formats a JAX/torch user actually has:

- **flax msgpack** (``flax.serialization.msgpack_serialize``): the native
  JAX checkpoint container — restored 1:1 into zoo param pytrees;
- **torch state_dict exported as npz** (``numpy.savez(**{k: v.numpy()})``):
  torch's dotted module paths become the flax nesting, and each tensor is
  re-laid-out from torch's conventions to flax's (Linear ``weight``
  (out, in) -> ``kernel`` (in, out); Conv2d OIHW -> HWIO; Conv1d (out, in,
  k) -> (k, in, out); BatchNorm ``weight``/``bias``/``running_*`` ->
  ``scale``/``bias``/``mean``/``var``).

``validate_params`` checks an imported pytree leaf-by-leaf against the zoo
architecture's ``init`` structure (paths AND shapes) before anything is
published, so a converted checkpoint either drops in exactly or fails with
the full mismatch list. ``import_pretrained`` then publishes through
``LocalRepo.save_model`` with the schema's ``layerNames`` filled from the
zoo spec — the ``cutOutputLayers`` transfer-learning contract
(``ImageFeaturizer.scala:85-120``).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from mmlspark_tpu.models.downloader import LocalRepo, ModelSchema


# -- flax msgpack ------------------------------------------------------------

def from_flax_msgpack(source: Union[str, bytes]) -> Dict[str, Any]:
    """Restore a flax msgpack checkpoint (path or raw bytes) into a plain
    nested dict of numpy arrays."""
    from flax import serialization
    if isinstance(source, (str, os.PathLike)):
        with open(source, "rb") as f:
            source = f.read()
    tree = serialization.msgpack_restore(source)
    return _to_numpy(tree)


def to_flax_msgpack(params: Any, path: Optional[str] = None) -> bytes:
    """Serialize a param pytree to flax msgpack bytes (optionally saved)."""
    from flax import serialization
    data = serialization.msgpack_serialize(_to_numpy(params))
    if path is not None:
        with open(path, "wb") as f:
            f.write(data)
    return data


def _to_numpy(tree: Any) -> Any:
    if isinstance(tree, dict):
        return {k: _to_numpy(v) for k, v in tree.items()}
    return np.asarray(tree)


# -- torch state_dict (npz container) ----------------------------------------

_TORCH_DROP = ("num_batches_tracked",)


def _convert_torch_leaf(leaf_name: str, arr: np.ndarray
                        ) -> Optional[Tuple[str, np.ndarray]]:
    """(flax leaf name, re-laid-out array) for one torch tensor, or None
    for bookkeeping tensors that have no flax counterpart."""
    if leaf_name in _TORCH_DROP:
        return None
    if leaf_name == "weight":
        if arr.ndim == 2:          # Linear (out, in) -> kernel (in, out)
            return "kernel", arr.T
        if arr.ndim == 4:          # Conv2d OIHW -> HWIO
            return "kernel", arr.transpose(2, 3, 1, 0)
        if arr.ndim == 3:          # Conv1d (out, in, k) -> (k, in, out)
            return "kernel", arr.transpose(2, 1, 0)
        return "scale", arr        # norm layers keep 1-D weight as scale
    if leaf_name == "running_mean":
        return "mean", arr
    if leaf_name == "running_var":
        return "var", arr
    return leaf_name, arr          # bias and friends pass through


def from_torch_npz(source: Union[str, Dict[str, np.ndarray]]
                   ) -> Dict[str, Any]:
    """Torch ``state_dict`` (exported as npz, or an in-memory dict of
    numpy arrays) -> flax-style nested params under ``{"params": ...}``.

    The dotted torch key path becomes the flax module nesting verbatim —
    the torch module names must match the flax submodule names (the zoo's
    names are stable and documented per architecture); only the LEAF
    name/layout is translated.
    """
    if isinstance(source, (str, os.PathLike)):
        with np.load(source, allow_pickle=False) as z:
            flat = {k: np.asarray(z[k]) for k in z.files}
    else:
        flat = {k: np.asarray(v) for k, v in source.items()}
    tree: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = key.split(".")
        converted = _convert_torch_leaf(parts[-1], arr)
        if converted is None:
            continue
        leaf, value = converted
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[leaf] = value
    return {"params": tree}


# -- validation + publishing -------------------------------------------------

def _flat_shapes(tree: Any, prefix: str = "") -> Dict[str, Tuple]:
    out: Dict[str, Tuple] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flat_shapes(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = tuple(np.shape(tree))
    return out


def validate_params(architecture: str, params: Any, _spec=None,
                    **arch_kwargs) -> Dict[str, Any]:
    """Check an imported pytree against ``architecture``'s own init
    structure (leaf paths and shapes). Returns the params cast to the init
    dtypes; raises ValueError listing every mismatch otherwise."""
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.zoo import build_model
    spec = _spec if _spec is not None else build_model(architecture,
                                                      **arch_kwargs)
    module = spec["module"]
    shape = (1,) + tuple(spec["input_shape"])
    dt = jnp.int32 if spec.get("input_dtype") == "int32" else jnp.float32
    # abstract only — ShapeDtypeStructs carry shape/dtype, nothing allocates
    target = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0), jnp.zeros(shape, dt)))
    got = _flat_shapes(_to_numpy(params))
    want = {k: tuple(s.shape) for k, s in _flat_leaves(target).items()}
    missing = sorted(set(want) - set(got))
    unexpected = sorted(set(got) - set(want))
    wrong = sorted(k for k in set(want) & set(got) if want[k] != got[k])
    if missing or unexpected or wrong:
        raise ValueError(
            f"params do not match architecture {architecture!r}:\n"
            f"  missing: {missing}\n  unexpected: {unexpected}\n"
            f"  shape mismatches: "
            f"{[(k, got[k], want[k]) for k in wrong]}")
    # cast to the init leaf dtypes (e.g. a float64 numpy export -> float32)
    dtypes = {k: s.dtype for k, s in _flat_leaves(target).items()}

    def cast(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: cast(v, f"{prefix}{k}/") for k, v in tree.items()}
        return np.asarray(tree, dtype=dtypes[prefix.rstrip("/")])
    return cast(_to_numpy(params))


def _flat_leaves(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flat_leaves(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def import_pretrained(repo: LocalRepo, name: str, architecture: str,
                      params: Any, dataset: str = "",
                      input_mean: Optional[List[float]] = None,
                      input_std: Optional[List[float]] = None,
                      **arch_kwargs) -> ModelSchema:
    """Validate ``params`` against ``architecture`` and publish them into
    ``repo`` with a complete ModelSchema (layerNames from the zoo spec, the
    reference's transfer-learning contract; ``input_mean``/``input_std``
    record the normalization the net was trained with). Returns the
    written schema."""
    from mmlspark_tpu.models.zoo import build_model
    spec = build_model(architecture, **arch_kwargs)
    params = validate_params(architecture, params, _spec=spec, **arch_kwargs)
    layer_names: List[str] = list(spec.get("layer_names", []))
    schema = ModelSchema(
        name=name, architecture=architecture, dataset=dataset,
        inputNode=spec.get("feature_layer", ""),
        numLayers=len(layer_names), layerNames=layer_names,
        architectureArgs=dict(arch_kwargs),
        inputMean=[float(v) for v in (input_mean or [])],
        inputStd=[float(v) for v in (input_std or [])])
    return repo.save_model(schema, params)
