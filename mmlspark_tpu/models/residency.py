"""Device-resident scoring inputs: transfer once, score from HBM slices.

DeviceEpochCache (``parallel/trainer.py``) made TRAINING epochs resident;
this is the same move for INFERENCE, the path the reference re-streamed on
every pass (``CNTKModel.scala:50-104`` re-fills its minibatch buffers per
``transform``; ``FindBestModel.scala:135-143`` re-scores the shared
featurized DataFrame once per candidate model). Scoring workloads re-read
one immutable frame many times — K FindBestModel candidates, repeated
evaluation passes — so the win is caching the device upload ACROSS calls:

- keyed weakly on the Frame object (frames are immutable-by-convention;
  the upload dies with the frame, never goes stale);
- sub-keyed on the coercion fingerprint (column, batch shape, dtype,
  preprocessing), so models that feed identically share one upload while
  a model with different coercion gets its own;
- budget-checked against ``runtime.device_cache_mb`` exactly like
  DeviceEpochCache.fits — an over-budget frame falls back to the
  streaming loop, it never OOMs the chip;
- single-frame: uploading a NEW frame evicts the previous frame's
  entries (scoring passes don't interleave frames; bounding residency to
  one frame keeps worst-case HBM cost at one budget, not one per frame
  the process ever scored).
"""
from __future__ import annotations

import weakref
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np

from mmlspark_tpu.utils import config as mmlconfig

# frame -> {fingerprint: stacked device array (steps, bs, ...)}; consumers
# recompute per-batch valid rows from frame.count()
_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_TOTAL_UPLOADS = 0   # cumulative device puts since clear() (observability)


def resident_batches(frame, fingerprint: Tuple, build: Callable[[], np.ndarray],
                     force: bool = False,
                     budget_mb: Optional[float] = None,
                     nbytes_hint: Optional[int] = None):
    """The device-resident (steps, bs, ...) stack for ``frame``, or None.

    ``build()`` returns the fully coerced, tail-padded host stack; it runs
    only on a cache miss. ``nbytes_hint`` (the stack size computed from
    shapes/dtypes) lets an over-budget frame be rejected BEFORE build()
    materializes a full-dataset host copy — without it, every transform of
    an over-budget frame would allocate and discard ~dataset-sized RAM on
    the way to streaming anyway. The post-build check on actual nbytes
    still runs (the hint is an estimate). ``force=True`` skips both
    (deviceCache='on'). Each fingerprint budgets independently; feeding
    one frame to models with many DIFFERENT coercions multiplies
    residency, but the dominant callers (FindBestModel candidates,
    repeated eval passes) share one.
    """
    if getattr(frame, "_out_of_core", False):
        # DiskFrame and friends must never materialize through build() —
        # streaming them is their whole point. Guarded HERE so every
        # caller inherits it; callers that want to surface the conflict
        # loudly (an explicit force request) check before calling.
        return None
    entries = _CACHE.get(frame)
    if entries is not None and fingerprint in entries:
        return entries[fingerprint]
    if not force and nbytes_hint is not None \
            and not _fits(nbytes_hint, budget_mb):
        return None
    host = build()
    if not force and not _fits(host.nbytes, budget_mb):
        return None
    global _TOTAL_UPLOADS
    _TOTAL_UPLOADS += 1
    dev = jax.device_put(host)
    if entries is None:
        _CACHE.clear()          # single-frame policy: evict other frames
        entries = _CACHE.setdefault(frame, {})
    entries[fingerprint] = dev
    return dev


def _fits(nbytes: int, budget_mb: Optional[float] = None) -> bool:
    """2x charge like DeviceEpochCache.fits unshuffled: the resident stack
    plus the transiently-live batch slices at the consumer's peak."""
    if budget_mb is None:
        budget_mb = float(mmlconfig.get("runtime.device_cache_mb"))
    return nbytes * 2 <= budget_mb * 1e6


def clear() -> None:
    """Drop every resident upload (tests; explicit HBM release)."""
    global _TOTAL_UPLOADS
    _TOTAL_UPLOADS = 0
    _CACHE.clear()


def stats() -> Dict[str, int]:
    """Introspection for tests: live cached frames/uploads, plus the
    cumulative upload count since ``clear()`` (visible even after a
    frame's weak entry died with the frame)."""
    return {"frames": len(_CACHE),
            "uploads": sum(len(v) for v in _CACHE.values()),
            "total_uploads": _TOTAL_UPLOADS}
