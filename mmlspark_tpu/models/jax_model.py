"""JaxModel: score a serialized neural net over frame columns.

The CNTKModel re-expression (``cntk-model/src/main/scala/CNTKModel.scala``):

- the reference broadcast model bytes and ran a per-partition minibatch loop
  filling ``FloatVectorVector`` element-by-element (``:50-104``) — the perf
  sin SURVEY.md §7 calls out. Here the model jits ONCE per batch shape and
  whole contiguous host arrays stream to HBM;
- final-batch padding + unpadding matches the reference's workaround
  (``:71-76``, ``:95-97``) but exists for a TPU reason: one static batch
  shape = one compiled program, no retrace;
- input coercion Double/Vector -> float32 (``:195-212``) happens in numpy on
  the host side;
- output node selection by layer name (``:185-193``) maps to capturing a
  named intermediate of the zoo module (``cutOutputLayers``/``layerNames``
  contract used by ImageFeaturizer).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    AnyParam, DictParam, HasInputCol, HasOutputCol, IntParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.core.schema import ColumnSchema, DType, SchemaError
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.models.zoo import build_model
from mmlspark_tpu.observability import syncs as obssyncs


@register_stage
class JaxModel(HasInputCol, HasOutputCol, Model):
    """Scores a zoo architecture with given params over a vector/image column."""

    architecture = StringParam("architecture", "model zoo architecture name", "")
    architectureArgs = DictParam("architectureArgs",
                                 "kwargs for the architecture builder", {})
    miniBatchSize = IntParam("miniBatchSize", "rows per device batch", 1024,
                             validator=lambda v: v > 0)
    outputNodeName = StringParam(
        "outputNodeName", "layer to emit ('' = final output)", "")
    devicePreprocess = DictParam(
        "devicePreprocess", "on-device input preprocessing fused into the "
        "scoring jit: {'srcShape': [h, w, c], 'crop': [ch, cw], "
        "'resize': [H, W]} reshapes the flat wire vector to srcShape, "
        "center-crops, and bilinear-resizes to the model input ON DEVICE "
        "({} = off; crop/resize each optional). The north-star fusion: "
        "raw uint8 crosses host->HBM and crop+resize+normalize run as "
        "ONE Pallas kernel ahead of the first layer instead of per-image "
        "on the host.", {})
    meshSpec = AnyParam(
        "meshSpec", "shard SCORING over a device mesh (MeshSpec / "
        "axis-size dict / Mesh; None = single-device jit). Params shard "
        "by the standard rules (tensor/fsdp for the big matmuls) and the "
        "batch over the data axes — model-parallel inference for nets one "
        "chip cannot hold, a capability the reference's single-graph "
        "CNTKModel had no analogue for. Per-host: each process scores its "
        "own rows on a process-local mesh.", None)
    deviceCache = StringParam(
        "deviceCache", "keep the coerced input resident in HBM across "
        "transform calls and slice batches on device: 'auto' caches when "
        "it fits runtime.device_cache_mb, 'on' forces, 'off' streams. "
        "Repeat scoring of the same frame (FindBestModel candidates, "
        "evaluation passes) then transfers the input ONCE — the "
        "inference face of DeviceEpochCache.", "auto",
        domain=("auto", "on", "off"))
    computeDtype = StringParam(
        "computeDtype", "matmul/conv compute precision: 'bfloat16' casts "
        "float params + activations to bf16 inside the jit (MXU-native) "
        "AND keeps the fetched output in bf16 on the wire — half the "
        "device->host bytes, which on remote/tunneled links is the "
        "scoring bottleneck for wide feature outputs; the emitted column "
        "is still float32 (cast on host). 'float32' preserves exact "
        "CNTKModel-parity numerics. Integer inputs (token models) are "
        "never cast.", "float32", domain=("float32", "bfloat16"))

    def set_model(self, architecture: str, params: Optional[Any] = None,
                  seed: int = 0, input_mean=None, input_std=None,
                  **arch_kwargs) -> "JaxModel":
        """Attach architecture + params (random-init if params is None).

        ``input_mean``/``input_std`` (per-channel, scalar, or anything
        broadcastable against the model input) record the normalization
        the net was trained with — fused on device ahead of the first
        layer. THE single place this plumbing lives; downloader and
        featurizer route through here."""
        self.set_params(architecture=architecture,
                        architectureArgs=dict(arch_kwargs))
        spec = build_model(architecture, **arch_kwargs)
        if params is None:
            module = spec["module"]
            shape = (1,) + tuple(spec["input_shape"])
            dtype = jnp.int32 if spec.get("input_dtype") == "int32" else jnp.float32
            x = jnp.zeros(shape, dtype)
            params = module.init(jax.random.PRNGKey(seed), x)
        state = {"params": _to_plain(params)}
        if input_mean is not None or input_std is not None:
            state["input_mu"] = np.asarray(
                input_mean if input_mean is not None else [0.0], np.float32)
            state["input_sigma"] = np.asarray(
                input_std if input_std is not None else [1.0], np.float32)
        # _set_state (not a bare assignment) so a previously compiled
        # closure over OLD params is invalidated
        self._set_state(state)
        return self

    # -- internals ---------------------------------------------------------
    def _spec(self, mesh=None) -> Dict[str, Any]:
        """Build the zoo spec; with a ``seq``-parallel scoring mesh, inject
        the ring/Ulysses attention_fn into builders that accept one — long-
        context INFERENCE rides the same sequence-parallel machinery as
        training, chosen by mesh shape rather than serialized state (an
        attention_fn is process-bound and never persists)."""
        if not self.architecture:
            raise SchemaError("JaxModel: no architecture set; call set_model()")
        args = dict(self.get("architectureArgs"))
        spec = build_model(self.architecture, **args)
        if mesh is not None and mesh.shape.get("seq", 1) > 1 \
                and "attention_fn" not in args:
            # OPT-IN per architecture (spec flag), never by signature
            # sniffing: the ring/Ulysses kernels implement the decoder
            # (q, k, v, causal) contract — injecting them into, e.g., a
            # ViT (bidirectional, CLS token making the length odd) would
            # crash or silently corrupt
            if spec.get("seq_attention"):
                from mmlspark_tpu.parallel.sequence import make_attention_fn
                args["attention_fn"] = make_attention_fn(mesh, "auto")
                spec = build_model(self.architecture, **args)
        return spec

    @property
    def layer_names(self):
        return list(self._spec()["layer_names"])

    def _resolve_score_mesh(self):
        """The scoring mesh, or None for the single-device fast path."""
        if self.get("meshSpec") is None:
            return None
        from mmlspark_tpu.parallel.mesh import resolve_mesh
        from mmlspark_tpu.parallel.sharding import mesh_spans_processes
        mesh = resolve_mesh(self.get("meshSpec"))
        if mesh_spans_processes(mesh):
            raise SchemaError(
                "JaxModel scoring is per-host (each process scores its own "
                "rows); use a process-local mesh, not one spanning "
                "processes")
        return mesh

    def _build_apply(self):
        mesh = self._resolve_score_mesh()
        spec = self._spec(mesh)
        module = spec["module"]
        # params are ARGUMENTS of the jitted function, never closure
        # captures: closed-over arrays inline into the HLO as constants,
        # which for a ResNet-50/ViT-B bloats the program by the full
        # parameter size and multiplies compile time (or overflows
        # remote-compile request limits outright)
        cdt = (jnp.bfloat16 if self.get("computeDtype") == "bfloat16"
               else None)
        if mesh is not None:
            # model-parallel scoring: HOST numpy -> sharded device arrays
            # in one hop (device_put against the NamedSharding tree), so
            # each chip receives only its shard — a model bigger than one
            # chip's HBM never materializes a full replica on any device.
            # The bf16 cast happens on host for the same reason.
            from mmlspark_tpu.parallel.sharding import param_shardings
            params = self._state["params"]
            if cdt is not None:
                params = jax.tree_util.tree_map(
                    lambda a: a.astype(cdt)
                    if np.issubdtype(np.asarray(a).dtype, np.floating)
                    else np.asarray(a), params)
            with mesh:
                params = jax.device_put(
                    params, param_shardings(params, mesh))
        else:
            params = jax.tree_util.tree_map(jnp.asarray,
                                            self._state["params"])
            if cdt is not None:
                params = jax.tree_util.tree_map(
                    lambda a: a.astype(cdt)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
        node = self.outputNodeName

        # Optional input standardization: models trained on z-scored inputs
        # (e.g. DeepClassifier) carry fit-time statistics so extraction sees
        # the same distribution the net was trained on. Shapes must broadcast
        # against the model input shape.
        dp = self.get("devicePreprocess")
        mu = self._state.get("input_mu")
        if dp:
            src = tuple(int(v) for v in dp["srcShape"])
            dst = tuple(int(v) for v in dp.get("resize") or ())
            crop = tuple(int(v) for v in dp.get("crop") or ()) or None

            from mmlspark_tpu.ops.pallas_preprocess import (
                device_resize_bilinear, make_fused_preprocess_fn,
            )

            # scalar / per-channel normalization folds INTO the Pallas
            # kernel; anything wider (a full-image mean) can't ride its
            # per-row constants and takes the jnp path below
            mean_a = (np.asarray(mu, np.float32).ravel()
                      if mu is not None else np.zeros(1, np.float32))
            std_a = (np.asarray(self._state["input_sigma"],
                                np.float32).ravel()
                     if mu is not None else np.ones(1, np.float32))
            foldable = mean_a.size in (1, src[2]) \
                and std_a.size in (1, src[2])
            fused = make_fused_preprocess_fn(
                src, resize=dst or None, crop=crop,
                mean=mean_a, std=std_a,
                out_dtype=jnp.float32) if foldable else None

            def base(x):
                was_u8 = x.dtype == jnp.uint8
                x = _to_float(x.reshape((x.shape[0],) + src))
                if crop:
                    oh = (src[0] - crop[0]) // 2
                    ow = (src[1] - crop[1]) // 2
                    x = x[:, oh:oh + crop[0], ow:ow + crop[1]]
                if dst and dst != (crop or src[:2]):
                    x = device_resize_bilinear(x, dst[0], dst[1])
                    if was_u8:
                        # emulate the host path's uint8 re-quantization
                        # (image/ops.py _resize_stack clips+rints back to
                        # uint8), so a dataset mixing fused and host routes
                        # scores identical images identically
                        x = jnp.clip(jnp.round(x), 0.0, 255.0)
                return x
        else:
            base = _to_float
            fused = None

        if mu is not None:
            mu_d = jnp.asarray(mu)
            sigma_d = jnp.asarray(self._state["input_sigma"])
            norm = lambda x: (base(x) - mu_d) / sigma_d
        else:
            norm = base

        if fused is not None:
            # uint8 wire input runs the single fused Pallas kernel
            # (crop+resize+requantize+normalize, SURVEY §7); float input —
            # the lossless path — keeps the jnp route, numerically the
            # same pipeline
            pre = lambda x: fused(x) if x.dtype == jnp.uint8 else norm(x)
        else:
            pre = norm

        if cdt is not None:
            # bf16 enters HERE, after the full-precision preprocess
            # (resize interpolation + normalization stay fp32-exact);
            # integer token inputs pass through untouched
            def pre(x, _pre=pre):
                y = _pre(x)
                return (y.astype(cdt)
                        if jnp.issubdtype(y.dtype, jnp.floating) else y)

        def bind(jitted):
            if mesh is None:
                call = lambda x: jitted(params, x)
            else:
                def call(x):
                    with mesh:
                        return jitted(params, x)
            # the serving registry AOT-compiles one executable per batch
            # bucket via jitted.lower(params, spec).compile(); expose the
            # raw jitted fn + bound params on the closure rather than
            # widening the transform-path return tuple
            call._jitted = jitted
            call._params = params
            call._mesh = mesh
            return call

        def bind_stack(fn):
            """Whole-pass program over the resident (steps, bs, ...) stack:
            ``lax.map`` runs the per-batch body as ONE compiled scan — one
            dispatch and one fetch for the entire pass, where a Python
            loop pays per-batch dispatch (murder over a tunneled link; the
            body still compiles once, and per-iteration activations free
            across scan steps, so memory stays at one batch's worth plus
            the output). Single-device only; mesh scoring keeps its loop
            (batch shardings don't thread through lax.map's carry)."""
            if mesh is not None:
                return None
            stack_jit = jax.jit(
                lambda p, stack: jax.lax.map(lambda x: fn(p, x), stack))
            return lambda stack: stack_jit(params, stack)

        if not node:
            jitted = jax.jit(lambda p, x: module.apply(p, pre(x)))
            inner = lambda p, x: module.apply(p, pre(x))
            return bind(jitted), bind_stack(inner), None, mesh

        from mmlspark_tpu.models.zoo.resnet import apply_with_intermediates

        def select(inters):
            return [v for k, v in sorted(inters.items())
                    if k == node or k.endswith("/" + node)]

        # Probe (shape-only, no compile) whether the node is an explicitly
        # sown layer; capture_intermediates=True records EVERY submodule
        # output and costs ~3x at runtime, so it is the fallback, not the
        # default. On a scoring mesh the probe batch must satisfy the
        # shard_map divisibility of any injected seq-parallel attention
        # (ring shards the batch over the data axes), so probe with one
        # row per batch shard instead of one row total.
        probe_rows = 1
        if mesh is not None:
            from mmlspark_tpu.parallel.sharding import batch_share
            probe_rows = batch_share(mesh)[1]
        if dp:
            probe_shape = (probe_rows, int(np.prod(src)))
        else:
            probe_shape = (probe_rows,) + tuple(spec["input_shape"])
        dt = jnp.int32 if spec.get("input_dtype") == "int32" else jnp.float32
        probe = jax.eval_shape(
            lambda x: apply_with_intermediates(module, params, pre(x))[1],
            jax.ShapeDtypeStruct(probe_shape, dt))
        capture_all = not select(probe)

        def inner(p, x):
            _, inters = apply_with_intermediates(module, p, pre(x),
                                                 capture_all=capture_all)
            matches = select(inters)
            if not matches:
                raise SchemaError(
                    f"output node {node!r} not found; have {sorted(inters)}")
            return matches[0]

        jitted = jax.jit(inner)
        return bind(jitted), bind_stack(inner), node, mesh

    def _coerce_batch(self, arr: np.ndarray, spec) -> np.ndarray:
        """Host-side input coercion (reference UDFs :195-212) + reshape.
        uint8 inputs stay uint8 — they cross host->HBM at 1/4 the bytes and
        cast to float INSIDE the jit (the fused-preprocess fast path)."""
        want_int = spec.get("input_dtype") == "int32"
        arr = np.asarray(arr)
        if arr.dtype != np.uint8 or want_int:
            arr = arr.astype(np.int32 if want_int else np.float32)
        dp = self.get("devicePreprocess")
        if dp:
            # the jit reshapes/resizes on device; ship the flat wire vector
            want = int(np.prod(dp["srcShape"]))
            if arr.ndim != 2 or arr.shape[1] != want:
                raise SchemaError(
                    f"devicePreprocess srcShape {dp['srcShape']} wants flat "
                    f"width {want}, got {arr.shape}")
            return arr
        in_shape = tuple(spec["input_shape"])
        if arr.ndim == 2 and len(in_shape) > 1:
            if int(np.prod(in_shape)) != arr.shape[1]:
                raise SchemaError(
                    f"input width {arr.shape[1]} != prod{in_shape}")
            arr = arr.reshape((arr.shape[0],) + in_shape)
        return arr

    def transform(self, frame: Frame) -> Frame:
        spec = self._spec()
        apply, apply_stack, _, mesh = self._cached_jit(
            lambda: self._build_apply(),
            key=(self.architecture, repr(self.get("architectureArgs")),
                 self.outputNodeName, repr(self.get("devicePreprocess")),
                 repr(self.get("meshSpec")), self.get("computeDtype"),
                 ))
        bs = self.miniBatchSize
        if mesh is not None:
            return self._transform_sharded(frame, spec, apply, mesh, bs)
        if self.get("deviceCache") == "on" \
                and getattr(frame, "_out_of_core", False):
            raise ValueError(
                "deviceCache='on' would materialize an out-of-core "
                "DiskFrame; score it with deviceCache='auto'/'off' "
                "(streams), or materialize it to an in-memory Frame "
                "first if it fits")
        if self.get("deviceCache") != "off" and frame.count():
            dev = self._resident_input(frame, spec, bs)
            if dev is not None:
                # the whole-pass program materializes the ENTIRE output
                # stack in HBM before the one fetch — fine for logits or
                # pooled features, not for a wide intermediate layer on a
                # big frame. Over-budget outputs fall back to per-batch
                # slices of the resident input with bounded retire windows.
                from mmlspark_tpu.models import residency
                # eval_shape abstractly traces the whole stack program
                # (milliseconds for a ResNet-50 — real per-call overhead);
                # the answer depends only on the input aval and the built
                # closure, so memoize on exactly those (a rebuilt closure
                # after set_model/_set_state gets a fresh entry)
                spec_key = (dev.shape, str(dev.dtype), apply_stack)
                cached = getattr(self, "_out_spec_cache", None)
                if cached is not None and cached[0] == spec_key:
                    out_spec = cached[1]
                else:
                    out_spec = jax.eval_shape(apply_stack, dev)
                    self._out_spec_cache = (spec_key, out_spec)
                out_bytes = int(np.prod(out_spec.shape)
                                * out_spec.dtype.itemsize)
                if self.get("deviceCache") == "on" \
                        or residency._fits(dev.nbytes + out_bytes):
                    return self._transform_resident(frame, apply_stack,
                                                    dev, bs)
                return self._transform_resident_windowed(frame, apply,
                                                         dev, bs)
        # Async scoring loop: a batch's transfer + forward is DISPATCHED
        # before earlier results are fetched (JAX dispatch returns
        # immediately), so host->device DMA overlaps compute instead of the
        # reference's strictly serial fill/evaluate/copy-back minibatch
        # loop (CNTKModel.scala:50-104).
        #
        # Transfers are BATCHED: ``put_window`` minibatches stack into ONE
        # host->HBM put, then each batch is a device-side slice. A transfer
        # issued while executes are in flight drains the pipeline (tens of
        # ms on PCIe-contended or tunneled links), so fewer, larger puts
        # keep the device fed — the scoring-side face of DeviceEpochCache.
        #
        # Outputs retire in bounded windows: one device-side concat + ONE
        # transfer per window — a round trip per window instead of per
        # batch, without accumulating the whole output (which for
        # intermediate-layer extraction is NOT small) or building a concat
        # whose operand count scales with the dataset.
        put_window = 8         # minibatches per host->device transfer
        window = 32            # output batches fetched per round trip
        in_flight = 8          # bound dispatched-but-unexecuted inputs (HBM)
        dev_outs: list = []
        outs: list = []
        pending: list = []     # coerced host batches awaiting one put

        def retire():
            if not dev_outs:
                return
            stacked = dev_outs[0] if len(dev_outs) == 1 \
                else jnp.concatenate(dev_outs, axis=0)
            outs.append(np.asarray(
                obssyncs.device_get(stacked, "transform.retire")))
            dev_outs.clear()

        def flush():
            if not pending:
                return
            dev = jnp.asarray(np.stack([x for x, _ in pending]))
            for i, (_, n) in enumerate(pending):
                dev_outs.append(apply(dev[i])[:n])
                if len(dev_outs) >= window:
                    retire()
                elif len(dev_outs) >= in_flight:
                    obssyncs.block_until_ready(
                        dev_outs[-in_flight], "transform.backpressure")
            pending.clear()

        for batch in frame.batches(bs, cols=[self.inputCol]):
            x = self._coerce_batch(batch[self.inputCol], spec)
            n = x.shape[0]
            if n < bs:  # pad final batch: keep ONE compiled shape
                pad = np.zeros((bs - n,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            pending.append((x, n))
            if len(pending) >= put_window:
                flush()
        flush()
        retire()
        return self._emit(frame, outs)

    def _resident_input(self, frame: Frame, spec, bs: int):
        """The frame's coerced input as a device-resident (steps, bs, ...)
        stack shared across transform calls (and across models with the
        same coercion — the FindBestModel case), or None when over budget
        with deviceCache='auto'."""
        from mmlspark_tpu.models import residency
        # everything that shapes the coerced stack is part of the key:
        # input_shape drives _coerce_batch's reshape, so two models with
        # different input shapes must not share an upload (architecture
        # itself stays OUT — identical-input models sharing is the point)
        fingerprint = (self.inputCol, bs, spec.get("input_dtype"),
                       tuple(spec["input_shape"]),
                       repr(self.get("devicePreprocess")))
        # size hint from one coerced row, so an over-budget frame is
        # rejected before build() materializes a full-dataset host copy
        steps = int(np.ceil(frame.count() / bs))
        head = self._coerce_batch(
            np.asarray([np.asarray(frame.head(1)[0][self.inputCol])]), spec)
        hint = steps * bs * head[0].nbytes

        def build() -> np.ndarray:
            stacked = []
            for batch in frame.batches(bs, cols=[self.inputCol]):
                x = self._coerce_batch(batch[self.inputCol], spec)
                if x.shape[0] < bs:
                    pad = np.zeros((bs - x.shape[0],) + x.shape[1:], x.dtype)
                    x = np.concatenate([x, pad], axis=0)
                stacked.append(x)
            return np.stack(stacked)

        return residency.resident_batches(
            frame, fingerprint, build,
            force=self.get("deviceCache") == "on", nbytes_hint=hint)

    def _transform_resident(self, frame: Frame, apply_stack, dev,
                            bs: int) -> Frame:
        """Score from the resident stack as ONE compiled whole-pass
        program (``lax.map`` over the (steps, bs, ...) stack): zero
        steady-state host->HBM input transfer AND a single dispatch +
        single output fetch for the entire pass. Pad rows sit at the tail
        of the last batch, so one flat slice drops them."""
        n_total = frame.count()
        out = apply_stack(dev)                      # (steps, bs, ...)
        out = np.asarray(obssyncs.device_get(out, "transform.resident"))
        out = out.reshape((out.shape[0] * out.shape[1],) + out.shape[2:])
        return self._emit(frame, [out[:n_total]])

    def _transform_resident_windowed(self, frame: Frame, apply, dev,
                                     bs: int) -> Frame:
        """Resident INPUT, bounded output: per-batch device slices of the
        resident stack through the per-batch apply, outputs retired in
        windows — for outputs too wide to co-reside as one stack."""
        window, in_flight = 32, 8
        n_total = frame.count()
        dev_outs: list = []
        outs: list = []

        def retire():
            if not dev_outs:
                return
            stacked = dev_outs[0] if len(dev_outs) == 1 \
                else jnp.concatenate(dev_outs, axis=0)
            outs.append(np.asarray(
                obssyncs.device_get(stacked, "transform.retire")))
            dev_outs.clear()

        for i in range(dev.shape[0]):
            n = min(bs, n_total - i * bs)
            dev_outs.append(apply(dev[i])[:n])
            if len(dev_outs) >= window:
                retire()
            elif len(dev_outs) >= in_flight:
                obssyncs.block_until_ready(
                    dev_outs[-in_flight], "transform.backpressure")
        retire()
        return self._emit(frame, outs)

    def _emit(self, frame: Frame, outs: list) -> Frame:
        """Fetched output batches -> the scored frame column.

        Copy-frugal on purpose: a whole-pass transform hands exactly one
        multi-MB batch here, where a single-element ``np.concatenate``
        still copies and ``astype(float32)`` copies even when the dtype
        already matches — two dataset-sized host copies of pure overhead
        on the resident fast path.

        Ownership contract: on the single-batch path the emitted column
        ALIASES ``outs[0]`` (no copy is taken when it is already 2-D
        float32). Callers hand the buffers over — every internal caller
        builds ``outs`` from freshly fetched device outputs and drops its
        reference. A caller that keeps the input reachable and mutates it
        afterwards would corrupt the scored frame; defensively copy on
        that side, not here."""
        if not outs:
            out = np.zeros((0, 1), np.float32)
        elif len(outs) == 1:
            out = outs[0]
        else:
            out = np.concatenate(outs, axis=0)
        if out.ndim == 1:
            out = out[:, None]
        out = np.asarray(out, np.float32)   # no-copy when already fp32
        col = ColumnSchema(self.outputCol, DType.VECTOR, int(out.shape[1]),
                           metadata={"model_uid": self.uid,
                                     "architecture": self.architecture})
        return frame.with_column_values(col, out)

    def _transform_sharded(self, frame: Frame, spec, apply, mesh,
                           bs: int) -> Frame:
        """Mesh-mode scoring loop: each padded batch is committed with its
        batch dim over the data axes and runs through the pjit'd apply —
        the sharded counterpart of the single-device windowed loop (the
        transfer-batching optimization matters on tunneled single chips;
        model-parallel scoring targets big models where compute, not the
        wire, dominates)."""
        from mmlspark_tpu.parallel.sharding import batch_share, shard_batch
        _, total = batch_share(mesh)
        bs = int(np.ceil(bs / total) * total)  # divisible over data axes
        outs: list = []
        pending: list = []

        def retire(down_to: int) -> None:
            while len(pending) > down_to:
                out, n = pending.pop(0)
                outs.append(np.asarray(
                    obssyncs.device_get(out, "transform.sharded"))[:n])

        # sequence dim (tokens are (B, L)) shards over `seq` only for
        # architectures that OPTED INTO seq-parallel attention — for
        # anything else dim 1 is features/spatial, where a seq sharding
        # would at best crash on divisibility and at worst hit the
        # spatial-sharding miscompiles the sharding rules guard against
        seq_axis = ("seq" if mesh.shape.get("seq", 1) > 1
                    and spec.get("seq_attention") else None)
        # no outer mesh context: `apply` is self-contained (bind() enters
        # the mesh), and device_put/device_get need none
        for batch in frame.batches(bs, cols=[self.inputCol]):
            x = self._coerce_batch(batch[self.inputCol], spec)
            n = x.shape[0]
            if n < bs:
                pad = np.zeros((bs - n,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            xd = shard_batch(mesh, {"x": x}, seq_axis=seq_axis)["x"]
            pending.append((apply(xd), n))  # async dispatch
            retire(down_to=8)  # bound outputs resident in HBM
        retire(down_to=0)
        return self._emit(frame, outs)

    def transform_schema(self, schema):
        return schema.add(ColumnSchema(self.outputCol, DType.VECTOR, None))


def _to_float(x):
    """uint8 wire format -> float32 on device; other dtypes untouched
    (int32 token models must stay integer)."""
    return x.astype(jnp.float32) if x.dtype == jnp.uint8 else x


def _to_plain(tree):
    """FrozenDict / jax arrays -> plain dict of numpy (serializable).

    Device leaves start their host copies ASYNC before any is awaited:
    a per-leaf ``np.asarray`` is one synchronous round trip per leaf,
    which on a remote/tunneled chip turns a 100-leaf param tree into
    minutes of serial latency; overlapped it is one latency plus the
    wire time of the whole tree."""
    try:
        from flax.core import unfreeze
        tree = unfreeze(tree)
    except (ImportError, TypeError, ValueError):
        pass  # no flax, or already a plain container
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except (RuntimeError, ValueError):
                pass  # committed-to-host or non-device arrays
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)
