"""ModelDownloader: pretrained-model repository with manifest + sha256.

Re-expression of ``downloader/src/main/scala/ModelDownloader.scala:24-260``
and ``Schema.scala:31-92``:

- ``ModelSchema`` keeps the reference's fields (name/dataset/modelType/uri/
  hash/size/inputNode/numLayers/layerNames) so repository listings are
  drop-in compatible;
- ``LocalRepo`` = the reference's HDFSRepo idea: a cache directory holding
  model blobs + ``.meta`` JSON sidecars;
- ``HttpRepo`` = DefaultModelRepo: a base URL serving a MANIFEST file of
  schema JSON lines (fetch via urllib; sha256-verified on arrival);
- model payloads are ``.npz`` param archives loadable straight into JaxModel.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import urllib.error
import urllib.request
from dataclasses import asdict, dataclass, field
from email.utils import parsedate_to_datetime
from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

from mmlspark_tpu.observability import events as obsevents
from mmlspark_tpu.observability import metrics as obsmetrics
from mmlspark_tpu.reliability.faults import fault_site
from mmlspark_tpu.reliability.retry import RetryPolicy


@dataclass
class ModelSchema:
    name: str
    architecture: str = ""           # zoo key (the reference's modelType)
    dataset: str = ""
    uri: str = ""
    hash: str = ""                   # sha256 hex of the payload
    size: int = 0
    inputNode: str = ""
    numLayers: int = 0
    layerNames: List[str] = field(default_factory=list)
    architectureArgs: Dict[str, Any] = field(default_factory=dict)
    # input preprocessing the net was trained with (per-channel or scalar;
    # empty = raw). The reference's CNTK graphs embedded their own input
    # normalization; here it rides the schema so a downloaded model scores
    # the distribution it was trained on.
    inputMean: List[float] = field(default_factory=list)
    inputStd: List[float] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "ModelSchema":
        return ModelSchema(**json.loads(s))


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


class Repository:
    """Abstract model repository (reference Repository[S <: Schema])."""

    def list_schemas(self) -> Iterable[ModelSchema]:
        raise NotImplementedError

    def get_model_path(self, schema: ModelSchema) -> str:
        raise NotImplementedError

    def find_by_name(self, name: str) -> ModelSchema:
        for s in self.list_schemas():
            if s.name == name:
                return s
        raise KeyError(f"model {name!r} not found in repository")


class LocalRepo(Repository):
    """Directory cache: <name>.npz payload + <name>.meta sidecar."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def list_schemas(self) -> List[ModelSchema]:
        out = []
        for fn in sorted(os.listdir(self.root)):
            if fn.endswith(".meta"):
                with open(os.path.join(self.root, fn)) as f:
                    out.append(ModelSchema.from_json(f.read()))
        return out

    def get_model_path(self, schema: ModelSchema) -> str:
        path = os.path.join(self.root, f"{schema.name}.npz")
        if not os.path.exists(path):
            raise FileNotFoundError(f"model payload missing: {path}")
        if schema.hash:
            actual = sha256_file(path)
            if actual != schema.hash:
                raise IOError(f"sha256 mismatch for {schema.name}: "
                              f"{actual} != {schema.hash}")
        return path

    def save_model(self, schema: ModelSchema, params: Any) -> ModelSchema:
        """Flatten a param pytree into an npz payload + write sidecar."""
        flat = _flatten_params(params)
        path = os.path.join(self.root, f"{schema.name}.npz")
        np.savez(path, **flat)
        schema.hash = sha256_file(path)
        schema.size = os.path.getsize(path)
        with open(os.path.join(self.root, f"{schema.name}.meta"), "w") as f:
            f.write(schema.to_json())
        return schema

    def write_manifest(self) -> str:
        """Write the ``MANIFEST`` file (one schema JSON per line) that
        HttpRepo clients list — serving this directory over any static
        HTTP server makes it a remote model repository, the publishing
        half of the reference's DefaultModelRepo."""
        path = os.path.join(self.root, "MANIFEST")
        with open(path, "w") as f:
            for s in self.list_schemas():
                f.write(s.to_json() + "\n")
        return path


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    """``Retry-After`` header -> seconds (delta-seconds or HTTP-date form);
    None when absent or unparseable. Never raises — a malformed header
    must not turn a retryable 503 into a crash."""
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        import datetime
        when = parsedate_to_datetime(value)
        now = datetime.datetime.now(datetime.timezone.utc)
        if when.tzinfo is None:
            when = when.replace(tzinfo=datetime.timezone.utc)
        return max(0.0, (when - now).total_seconds())
    except (TypeError, ValueError):
        return None


class HttpRepo(Repository):
    """Remote repository: <base>/MANIFEST lists schema JSON, one per line.

    Hardened fetch path (reliability subsystem): every ``urlopen`` carries a
    timeout, MANIFEST and model fetches run under a :class:`RetryPolicy`,
    payloads land in a ``.tmp`` file that is sha256-verified (when the
    schema carries a hash) BEFORE ``os.replace`` into the cache — a
    truncated or corrupt transfer is retried, never cached, and a crash
    mid-download leaves no partial file at the cache path. A cached file
    that no longer matches its hash (torn write from a pre-hardening
    client, bitrot) is re-fetched instead of erroring forever.
    """

    def __init__(self, base_url: str, cache: Union[LocalRepo, str],
                 timeout: Optional[float] = None,
                 retry: Optional["RetryPolicy"] = None,
                 breaker: Optional["CircuitBreaker"] = None):
        from urllib.parse import urlparse
        from mmlspark_tpu.reliability.breaker import breaker_for
        from mmlspark_tpu.utils import config
        self.base_url = base_url.rstrip("/")
        self.cache = LocalRepo(cache) if isinstance(cache, str) else cache
        self.timeout = (float(config.get("reliability.http_timeout"))
                        if timeout is None else timeout)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=int(config.get("reliability.max_attempts")),
            base_delay=float(config.get("reliability.base_delay")),
            name="downloader")
        # one breaker per repo HOST (process-wide): when the registry is
        # down, every HttpRepo instance pointed at it fails fast together
        # instead of each burning its own backoff schedule
        host = urlparse(self.base_url).netloc or self.base_url
        self.breaker = breaker if breaker is not None \
            else breaker_for(f"downloader.{host}")

    def _fetch(self, url: str) -> bytes:
        """One guarded fetch: the circuit breaker wraps the socket work,
        and a 429/503 response's ``Retry-After`` header is attached to the
        re-raised error (``retry_after`` seconds) so the retry layer backs
        off for as long as the server asked, not just its own schedule."""
        fault_site("downloader.fetch")

        def _read() -> bytes:
            try:
                with urllib.request.urlopen(url, timeout=self.timeout) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                if e.code in (429, 503):
                    e.retry_after = _parse_retry_after(
                        e.headers.get("Retry-After"))
                raise

        data = self.breaker.call(_read)
        return fault_site("downloader.payload", payload=data)

    def list_schemas(self) -> List[ModelSchema]:
        fault_site("downloader.manifest")
        data = self.retry.call(self._fetch, f"{self.base_url}/MANIFEST")
        lines = data.decode("utf-8").splitlines()
        return [ModelSchema.from_json(l) for l in lines if l.strip()]

    def _download(self, url: str, schema: ModelSchema, path: str) -> None:
        """One fetch attempt: tmp file -> sha256 verify -> atomic replace.
        A hash mismatch raises IOError (retryable: it means a truncated or
        corrupted transfer) and leaves the cache untouched."""
        data = self._fetch(url)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            if schema.hash:
                actual = sha256_file(tmp)
                if actual != schema.hash:
                    raise IOError(
                        f"sha256 mismatch downloading {schema.name} "
                        f"({len(data)} bytes): {actual} != {schema.hash}")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def get_model_path(self, schema: ModelSchema) -> str:
        path = os.path.join(self.cache.root, f"{schema.name}.npz")
        cached_ok = os.path.exists(path) and (
            not schema.hash or sha256_file(path) == schema.hash)
        # cache telemetry: counters are cold-path (a download dwarfs an int
        # add), so they are unconditional; events stay behind the path gate
        if cached_ok:
            obsmetrics.counter("downloader.cache_hits").inc()
        else:
            obsmetrics.counter("downloader.cache_misses").inc()
            url = schema.uri or f"{self.base_url}/{schema.name}.npz"
            self.retry.call(self._download, url, schema, path)
            with open(os.path.join(self.cache.root,
                                   f"{schema.name}.meta"), "w") as f:
                f.write(schema.to_json())
            obsmetrics.counter("downloader.downloads").inc()
            if obsevents.events_enabled():
                obsevents.emit("event", "downloader.download",
                               model=schema.name, url=url,
                               bytes=os.path.getsize(path))
        return self.cache.get_model_path(schema)


class ModelDownloader:
    """Facade (reference ModelDownloader): resolve name -> local npz path,
    and hydrate a JaxModel from it."""

    def __init__(self, repo: Repository):
        self.repo = repo

    def download_by_name(self, name: str) -> str:
        return self.repo.get_model_path(self.repo.find_by_name(name))

    def load_params(self, name: str) -> Any:
        path = self.download_by_name(name)
        with np.load(path, allow_pickle=False) as z:
            return _unflatten_params({k: z[k] for k in z.files})

    def to_jax_model(self, name: str, **jax_model_kwargs):
        from mmlspark_tpu.models.jax_model import JaxModel
        schema = self.repo.find_by_name(name)
        params = self.load_params(name)
        m = JaxModel(**jax_model_kwargs)
        m.set_model(schema.architecture, params=params,
                    input_mean=schema.inputMean or None,
                    input_std=schema.inputStd or None,
                    **schema.architectureArgs)
        return m


def _flatten_params(tree, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_params(v, f"{prefix}{k}␟"))
    else:
        out[prefix.rstrip("␟")] = np.asarray(tree)
    return out


def _unflatten_params(flat: Dict[str, np.ndarray]):
    tree: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = key.split("␟")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree
