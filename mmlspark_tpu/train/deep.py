"""DeepClassifier: the distributed deep-learning Estimator.

The TPU-native equivalent of the reference's CNTKLearner
(``cntk-train/src/main/scala/CNTKLearner.scala:52-162``): an Estimator that
takes a featurized Frame, launches distributed training, and returns a
scoring model. Where the reference wrote the dataset out as CNTK text files
and shelled out to ``mpiexec -n G cntk ... parallelTrain=true``
(``CommandBuilders.scala:73-93``), here the whole thing is in-process:

- minibatches stream host->HBM through ``DistributedTrainer.put_batch``
  (one contiguous ``device_put`` per input — no text-file hand-off);
- the train step is one pjit'd XLA program over a ``MeshSpec`` mesh; the
  gradient allreduce is the psum XLA inserts over the ``data``/``fsdp``
  axes, riding ICI instead of an MPI ring;
- mid-training checkpoint/resume is opt-in via ``TrainCheckpointer``
  (``checkpointDir``) — elastic restart picks up at the saved step, a
  capability the reference delegates entirely to CNTK;
- the fitted ``DeepClassifierModel`` scores through the same zoo
  architecture (and can hand out a ``JaxModel`` for feature extraction, the
  ``cutOutputLayers`` contract of ``ImageFeaturizer.scala:85-120``).

``DeepClassifier`` is a drop-in learner for ``TrainClassifier`` — it carries
``FeaturizeHints`` and the featuresCol/labelCol params like every learner in
``train/learners.py`` — so the reference's flagship flow ("fit a deep net
distributed from the pipeline API, get a scoring model back") is one line:

    TrainClassifier(model=DeepClassifier(epochs=5), labelCol="income").fit(df)

Final-batch handling: every step runs at ONE compiled shape (global
``batchSize``); the tail batch is zero-padded and masked out of the loss via
a per-row weight, the reference's pad-and-drop workaround
(``CNTKModel.scala:71-76``) done the XLA way.
"""
from __future__ import annotations

import inspect
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    AnyParam, BooleanParam, DictParam, FloatParam, HasFeaturesCol, HasLabelCol,
    IntParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.observability import syncs as obssyncs
from mmlspark_tpu.train.learners import (
    FeaturizeHints, JaxEstimator, _score_classifier,
)


def _resolve_mesh(mesh_spec):
    from mmlspark_tpu.parallel.mesh import resolve_mesh
    return resolve_mesh(mesh_spec)


def _build_spec(architecture: str, arch_args: Dict[str, Any],
                input_dim: int, n_classes: int,
                train_dtype: str = "") -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Build the zoo spec, injecting input_dim/num_classes (and the compute
    dtype, when ``train_dtype`` is set) where the builder accepts them and
    the caller didn't pin them. Returns (spec, resolved_args) so the fitted
    model can rebuild the exact same architecture. Dtypes pass as STRINGS
    ('bfloat16') — flax accepts them and they stay JSON-serializable in
    ``architectureArgs``."""
    from mmlspark_tpu.models.zoo import _ZOO, build_model
    args = dict(arch_args or {})
    builder = _ZOO.get(architecture)
    accepted = set()
    if builder is not None:
        try:
            accepted = set(inspect.signature(builder).parameters)
        except (TypeError, ValueError):
            accepted = set()
    if "input_dim" in accepted:
        args.setdefault("input_dim", int(input_dim))
    if "num_classes" in accepted:
        args.setdefault("num_classes", int(n_classes))
    if train_dtype and "dtype" in accepted:
        args.setdefault("dtype", train_dtype)
    return build_model(architecture, **args), args


def _train_val_split(frame: Frame, frac: float, seed: int
                     ) -> Tuple[Frame, Frame]:
    """Deterministic PER-PARTITION row split (seeded; both sides keep their
    original row order and partitioning). Splitting partition-by-partition
    keeps peak memory at O(one partition) — no global collect — at the
    price of the split depending on the partition layout. Multi-process
    fits split each host's LOCAL shard; validation metrics then aggregate
    globally through the sharded eval."""
    if not frame.schema.names:
        raise ValueError("cannot split an empty-schema frame")
    if getattr(frame, "_out_of_core", False):
        raise ValueError(
            "validationSplit would materialize an out-of-core DiskFrame; "
            "stage separate train/val DiskFrame directories instead")
    rng = np.random.default_rng([seed, 715])
    first = frame.schema.names[0]
    tr_parts, va_parts = [], []
    for p in frame.partitions:
        n = len(p[first])
        k = int(round(n * frac))
        perm = rng.permutation(n)
        va, tr = np.sort(perm[:k]), np.sort(perm[k:])
        tr_parts.append({name: p[name][tr] for name in frame.schema.names})
        va_parts.append({name: p[name][va] for name in frame.schema.names})
    train = Frame(frame.schema, tr_parts)
    val = Frame(frame.schema, va_parts)
    if val.count() < 1 or train.count() < 1:
        raise ValueError(
            f"validationSplit={frac} leaves an empty split of "
            f"{frame.count()} rows")
    return train, val


class _DeepEstimatorBase(JaxEstimator):
    """Shared distributed streaming-fit machinery for the deep Estimators.

    Subclasses provide the task head: label dtype, output width, and the
    per-batch loss — everything else (mesh resolution, batch quantum,
    streaming stats, checkpoint/resume with seeded epoch replay, prefetch,
    metric logging, profiling) is one implementation.
    """

    hints = FeaturizeHints(one_hot=True, num_features=1 << 12)
    _y_dtype = np.int32

    architecture = StringParam(
        "architecture", "model zoo architecture name", "mlp_tabular")
    architectureArgs = DictParam(
        "architectureArgs", "extra kwargs for the architecture builder", {})
    batchSize = IntParam("batchSize", "global minibatch size", 256,
                         validator=lambda v: v > 0)
    epochs = IntParam("epochs", "training epochs over the frame", 5,
                      validator=lambda v: v > 0)
    learningRate = FloatParam("learningRate", "peak learning rate", 1e-3)
    weightDecay = FloatParam("weightDecay", "weight decay (adamw/lamb)", 1e-4)
    optimizer = StringParam(
        "optimizer", "optimizer family", "adamw",
        domain=("adamw", "adam", "sgd", "lamb", "adafactor"))
    lrSchedule = StringParam(
        "lrSchedule", "learning-rate schedule over the whole fit: "
        "'constant', 'cosine' (decay to 0), 'linear' (decay to 0); all "
        "start with warmupSteps of linear warmup", "constant",
        domain=("constant", "cosine", "linear"))
    warmupSteps = IntParam("warmupSteps", "linear LR warmup steps", 0,
                           validator=lambda v: v >= 0)
    trainDtype = StringParam(
        "trainDtype", "compute dtype for architectures that accept one "
        "('' = architecture default, typically bfloat16 — the MXU-native "
        "choice)", "", domain=("", "bfloat16", "float32"))
    validationSplit = FloatParam(
        "validationSplit", "fraction of rows held out for per-epoch "
        "validation metrics (0 = off)", 0.0,
        validator=lambda v: 0.0 <= v < 1.0)
    earlyStoppingPatience = IntParam(
        "earlyStoppingPatience", "stop after N epochs without val-loss "
        "improvement (0 = off; requires validationSplit > 0)", 0,
        validator=lambda v: v >= 0)
    accumSteps = IntParam(
        "accumSteps", "gradient-accumulation microbatches per step", 1,
        validator=lambda v: v >= 1)
    remat = BooleanParam("remat", "rematerialize the forward pass", False)
    standardize = BooleanParam(
        "standardize", "z-score features with fit-time statistics", True)
    seed = IntParam("seed", "PRNG seed", 0)
    meshSpec = AnyParam(
        "meshSpec", "MeshSpec / axis-size dict / Mesh (None = all devices "
        "data-parallel)", None)
    checkpointDir = StringParam(
        "checkpointDir", "orbax checkpoint dir ('' = checkpointing off)", "")
    checkpointEvery = IntParam(
        "checkpointEvery", "save every N steps when checkpointDir is set", 100)
    logEvery = IntParam("logEvery", "log train metrics every N steps (0=off)", 0)
    deviceCache = StringParam(
        "deviceCache", "keep the padded epoch resident in HBM and slice "
        "batches on device: 'auto' (when it fits runtime.device_cache_mb), "
        "'on', 'off' (stream host batches)", "auto",
        domain=("auto", "on", "off"))

    # -- data streaming ----------------------------------------------------
    # Stats and padding come from JaxEstimator._streaming_stats / _pad_xyw
    # (learners.py) — one implementation of the streaming moment pass and the
    # pad-and-mask batch builder shared by every streaming learner.
    @classmethod
    def _pad_batch(cls, hb: Dict[str, np.ndarray], fcol: str, lcol: str,
                   bs: int) -> Dict[str, np.ndarray]:
        """Fixed-shape training batch: zero-pad the tail, mask it via `w`."""
        from mmlspark_tpu.train.learners import _pad_xyw
        x, y, w = _pad_xyw(hb, fcol, lcol, bs, cls._y_dtype)
        return {"x": x, "y": y, "w": w}

    def _make_device_cache(self, frame: Frame, fcol: str, lcol: str,
                           bs: int, mesh, mode: str = None,
                           local_batch: int = None, steps: int = None):
        """DeviceEpochCache over the pad-and-masked epoch, or None.

        'auto' caches when the padded epoch fits ``runtime.device_cache_mb``
        (see ``DeviceEpochCache.fits`` for the peak-residency accounting);
        'on' forces it; 'off' streams. Construction is shared with the
        built-in learners (``learners._epoch_device_cache``). ``mode``
        overrides the ``deviceCache`` param (checkpoint-resume pinning);
        ``local_batch``/``steps`` carry the multi-process quota (this
        process pads its shard to ``steps * local_batch`` rows)."""
        mode = mode if mode is not None else self.get("deviceCache")
        if mode == "off":
            return None
        from mmlspark_tpu.train.learners import _epoch_device_cache
        return _epoch_device_cache(frame, fcol, lcol, bs, self._y_dtype,
                                   mesh=mesh, seed=self.seed,
                                   force=mode == "on",
                                   local_batch=local_batch, steps=steps)

    # -- optimizer / schedule ----------------------------------------------
    def _build_optimizer(self, total_steps: int):
        """optax transform from the optimizer/lrSchedule/warmupSteps params.

        The schedule reads the optimizer step count, which checkpoints
        restore — an elastic resume continues the schedule where it left
        off (CNTKLearner exposed the full BrainScript training config,
        ``CNTKLearner.scala:16-43``; this is the in-process equivalent)."""
        lr, warm = float(self.learningRate), int(self.warmupSteps)
        sched_name = self.get("lrSchedule")
        total = max(int(total_steps), warm + 1)
        if sched_name == "cosine":
            sched = optax.warmup_cosine_decay_schedule(
                0.0 if warm else lr, lr, warm, total, end_value=0.0)
        elif sched_name == "linear":
            sched = optax.join_schedules(
                [optax.linear_schedule(0.0, lr, max(warm, 1)),
                 optax.linear_schedule(lr, 0.0, total - warm)], [warm])
        elif warm:
            sched = optax.join_schedules(
                [optax.linear_schedule(0.0, lr, warm),
                 optax.constant_schedule(lr)], [warm])
        else:
            sched = lr
        wd = float(self.weightDecay)
        name = self.get("optimizer")
        return {
            "adamw": lambda: optax.adamw(sched, weight_decay=wd),
            "adam": lambda: optax.adam(sched),
            "sgd": lambda: optax.sgd(sched, momentum=0.9),
            "lamb": lambda: optax.lamb(sched, weight_decay=wd),
            "adafactor": lambda: optax.adafactor(sched),
        }[name]()

    # -- task hooks (subclass responsibility) -------------------------------
    def _n_out(self, frame: Frame, ymax, ymu, ysigma) -> int:
        raise NotImplementedError

    def _make_val_step(self, module, prep, ymu, ysigma):
        """(jitted f(params, batch) -> stacked sums, finalize(sums) -> dict
        with at least 'val_loss'). Weighted sums, so zero-weight pad rows
        (and multi-process filler batches) drop out of the metrics."""
        raise NotImplementedError

    def _make_loss(self, module, prep, ymu, ysigma):
        raise NotImplementedError

    def _build_fitted(self, fcol, lcol, resolved_args, state_arrays, n_out,
                      ymu, ysigma):
        raise NotImplementedError

    # -- multi-process -----------------------------------------------------
    @staticmethod
    def _allreduce_moments(moments):
        """Sum/max the per-process streaming moments so fit-time statistics
        describe the GLOBAL dataset even though each host scanned only its
        own Frame shard (the reference's CNTK ranks re-read the whole
        dataset from the shared filesystem instead).

        Tolerates empty LOCAL shards (n=0, d unknown): a header exchange
        agrees on the feature width first, then empty hosts contribute
        zero accumulators — the global-empty case surfaces at the caller's
        ``moments[0] == 0`` check, and uneven hosts train via the
        zero-weight filler batches in ``host_batches``."""
        from jax.experimental import multihost_utils
        n, d, s, ss, ymax, ysum, ysumsq = moments
        header = np.asarray([n, -1 if d is None else d], np.float64)
        h = np.asarray(multihost_utils.process_allgather(header))
        d_all = int(h[:, 1].max())
        if d_all < 0:
            return 0, None, None, None, -1, 0.0, 0.0
        if d is not None and d != d_all:
            raise ValueError(
                f"feature width differs across processes: {d} vs {d_all}")
        if d is None:
            s, ss = np.zeros(d_all), np.zeros(d_all)
        packed = np.concatenate(
            [np.asarray([n], np.float64), s, ss,
             np.asarray([ymax, ysum, ysumsq], np.float64)])
        g = np.asarray(multihost_utils.process_allgather(packed))
        return (int(g[:, 0].sum()), d_all, g[:, 1:1 + d_all].sum(axis=0),
                g[:, 1 + d_all:1 + 2 * d_all].sum(axis=0),
                int(g[:, -3].max()), float(g[:, -2].sum()),
                float(g[:, -1].sum()))

    # -- fit ---------------------------------------------------------------
    def fit(self, frame: Frame):
        from mmlspark_tpu.parallel.trainer import DistributedTrainer

        fcol, lcol = self.featuresCol, self.labelCol
        # per-epoch validation history, readable after fit() on BOTH the
        # estimator and the fitted model (TrainClassifier fits a COPY of
        # the learner, so the model is the reliable handle)
        self.validation_history = []
        mesh = _resolve_mesh(self.get("meshSpec"))

        # Batch must split evenly over the data axes and accum microbatches.
        from mmlspark_tpu.parallel.sharding import (
            active_batch_axes, local_batch_rows, mesh_spans_processes,
        )
        axes = active_batch_axes(mesh) or ()
        dp = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        quantum = dp * self.accumSteps
        bs = int(math.ceil(self.batchSize / quantum) * quantum)
        spans = mesh_spans_processes(mesh)
        # each process feeds only the rows its devices hold (its
        # batch_share of every global batch); single-process: the whole bs
        local_bs = local_batch_rows(mesh, bs) if spans else bs

        seed = self.seed
        patience = int(self.get("earlyStoppingPatience"))
        val_frac = float(self.get("validationSplit"))
        if patience and not val_frac:
            raise ValueError(
                "earlyStoppingPatience requires validationSplit > 0")
        val_frame = None
        if val_frac:
            frame, val_frame = _train_val_split(frame, val_frac, seed)

        moments = self._streaming_moments(frame)
        if spans:
            moments = self._allreduce_moments(moments)
        if moments[0] == 0:
            raise ValueError(f"{type(self).__name__}: empty frame")
        n, d, mu, sigma, ymax, ymu, ysigma = self._finalize_stats(*moments)
        n_out = self._n_out(frame, ymax, ymu, ysigma)

        spec, resolved_args = _build_spec(
            self.architecture, self.get("architectureArgs"), d, n_out,
            train_dtype=self.get("trainDtype"))
        module = spec["module"]
        in_shape = tuple(spec["input_shape"])
        standardize = self.standardize
        mu_d, sigma_d = jnp.asarray(mu), jnp.asarray(sigma)

        def prep(x):
            if standardize:
                x = (x - mu_d) / sigma_d
            if len(in_shape) > 1:
                x = x.reshape((x.shape[0],) + in_shape)
            return x

        loss_fn = self._make_loss(module, prep, ymu, ysigma)

        steps_per_epoch = math.ceil(n / bs)
        if spans and math.ceil(frame.count() / local_bs) > steps_per_epoch:
            raise ValueError(
                f"process {jax.process_index()} holds {frame.count()} rows "
                f"but its per-epoch quota is {steps_per_epoch * local_bs} "
                f"({steps_per_epoch} steps x {local_bs} local rows); "
                "rebalance the per-host shards (Frame.process_shard splits "
                "evenly)")
        total_steps = steps_per_epoch * self.epochs

        trainer = DistributedTrainer(
            loss_fn, self._build_optimizer(total_steps),
            mesh=mesh, accum_steps=self.accumSteps, remat=self.remat)

        init_params_fn = lambda: module.init(jax.random.PRNGKey(seed),
                                             prep(jnp.zeros((1, d))))

        ckpt = None
        if self.checkpointDir:
            from mmlspark_tpu.parallel.checkpoint import TrainCheckpointer
            ckpt = TrainCheckpointer(self.checkpointDir)
            state, resumed = ckpt.restore_or_init(trainer, init_params_fn)
        else:
            state, resumed = trainer.init(init_params_fn), False
        # Elastic resume: whole epochs already trained are skipped
        # arithmetically; only the partial epoch streams batches past.
        done = min(int(obssyncs.device_get(state["step"], "deep.resume_step")),
                   total_steps)
        start_epoch = done // steps_per_epoch
        skip_in_epoch = done - start_epoch * steps_per_epoch
        rng = jax.random.PRNGKey(seed)
        step, last_loss = done, None

        # a fully-resumed fit runs zero steps — don't pay the epoch transfer
        cache = None
        if done < total_steps:
            mode = None
            if ckpt is not None and resumed:
                # The two batch-order modes draw different per-epoch
                # permutations (host rng vs device fold_in), so resuming in
                # the other mode would replay/omit different rows in the
                # partial epoch and break the bit-parity elastic-restart
                # contract everywhere. Pin to the recorded mode.
                recorded = ckpt.get_meta().get("batch_order")
                if recorded == "streamed":
                    mode = "off"
                elif recorded == "cached":
                    mode = "on"
            cache = self._make_device_cache(frame, fcol, lcol, bs, mesh,
                                            mode=mode, local_batch=local_bs,
                                            steps=steps_per_epoch)
            if ckpt is not None:
                ckpt.put_meta(
                    batch_order="cached" if cache is not None else "streamed")

        def host_batches(epoch):
            """Padded fixed-shape LOCAL batches of one epoch, shuffled. The
            permutation is seeded by (seed, epoch[, process]) so an elastic
            resume replays the SAME order and the arithmetic skip stays
            aligned. Multi-process: each host shuffles only its own shard
            and, when shards are uneven, pads with zero-weight batches so
            every process dispatches the same number of steps (the global
            batch still carries real rows from the fuller shards)."""
            epoch_rng = np.random.default_rng(
                [seed, epoch] + ([jax.process_index()] if spans else []))
            j = 0
            for hb in frame.shuffled_batches(
                    local_bs, cols=[fcol, lcol], rng=epoch_rng):
                if not (epoch == start_epoch and j < skip_in_epoch):
                    yield self._pad_batch(hb, fcol, lcol, local_bs)
                j += 1
            while j < steps_per_epoch:  # lockstep filler (uneven shards)
                if not (epoch == start_epoch and j < skip_in_epoch):
                    yield {"x": np.zeros((local_bs, d), np.float32),
                           "y": np.zeros((local_bs,), self._y_dtype),
                           "w": np.zeros((local_bs,), np.float32)}
                j += 1

        def cached_batches(epoch):
            """Same epoch/skip arithmetic as host_batches, but every batch
            is an on-device slice of the resident epoch — zero steady-state
            host->HBM transfer. The device-side shuffle is seeded per epoch,
            so resume replays the same order WITHIN this mode (the two modes
            draw different permutations; each is deterministic, and a
            checkpoint resume pins the mode via the sidecar)."""
            for j, b in enumerate(cache.batches(epoch)):
                if epoch == start_epoch and j < skip_in_epoch:
                    continue
                yield b

        from mmlspark_tpu.parallel.trainer import DevicePrefetcher
        from mmlspark_tpu.utils.logging import MetricLogger
        from mmlspark_tpu.utils.profiling import trace
        metric_log = MetricLogger(every=self.logEvery,
                                  name=type(self).__name__)

        # Validation residency: the held-out split pads once and lives on
        # device for the whole fit — per-epoch evaluation is pure compute.
        val_fn = finalize = None
        val_dev = []
        if val_frame is not None and done < total_steps:
            val_fn, finalize = self._make_val_step(module, prep, ymu, ysigma)
            with mesh:
                val_dev = [
                    trainer.put_batch(self._pad_batch(hb, fcol, lcol,
                                                      local_bs))
                    for hb in val_frame.batches(local_bs, cols=[fcol, lcol])]
            val_steps = len(val_dev)
            if spans:
                # every process must dispatch the same number of eval
                # programs; uneven val shards pad with zero-weight batches
                from jax.experimental import multihost_utils
                counts = np.asarray(multihost_utils.process_allgather(
                    np.asarray([val_steps], np.int64)))
                val_steps = int(counts.max())
                zero = {"x": np.zeros((local_bs, d), np.float32),
                        "y": np.zeros((local_bs,), self._y_dtype),
                        "w": np.zeros((local_bs,), np.float32)}
                with mesh:
                    val_dev += [trainer.put_batch(zero)
                                for _ in range(val_steps - len(val_dev))]
            val_log = MetricLogger(every=1, name=type(self).__name__ + ".val")

        best_val, stale, stopped = float("inf"), 0, False
        if ckpt is not None and resumed:
            # early-stopping state rides the checkpoint sidecar so an
            # elastic restart neither re-trains past a recorded stop nor
            # resets the patience counter
            es = ckpt.get_meta().get("early_stop") or {}
            best_val = float(es.get("best_val", best_val))
            stale = int(es.get("stale", stale))
            stopped = bool(es.get("stopped", False))
            self.validation_history = list(es.get("history", []))
        with trace():  # captures a jax trace iff profiling.trace_dir set
            for epoch in range(start_epoch, self.epochs if not stopped
                               else start_epoch):
                if cache is not None:
                    it, closer = cached_batches(epoch), None
                else:
                    it = closer = DevicePrefetcher(host_batches(epoch),
                                                   trainer.put_batch)
                try:
                    for batch in it:
                        state, metrics = trainer.train_step(state, batch, rng)
                        last_loss = metrics["loss"]  # device scalar
                        step += 1
                        metric_log(step, {"loss": last_loss}, batch_rows=bs)
                        if ckpt is not None:
                            ckpt.maybe_save(state, every=self.checkpointEvery,
                                            step=step)
                finally:
                    if closer is not None:
                        closer.close()  # stops producer on early exit
                if val_fn is not None:
                    sums_dev = None
                    with mesh:
                        # accumulate the tiny metric vector ON device —
                        # one host round trip per epoch, not per batch
                        for b in val_dev:
                            out = val_fn(state["params"], b)
                            sums_dev = out if sums_dev is None \
                                else sums_dev + out
                    vm = finalize(np.asarray(
                        obssyncs.device_get(sums_dev, "deep.validation")))
                    val_log(epoch + 1, vm)
                    self.validation_history.append(
                        {"epoch": epoch + 1, **vm})
                    if vm["val_loss"] < best_val - 1e-12:
                        best_val, stale = vm["val_loss"], 0
                    else:
                        stale += 1
                        stopped = bool(patience and stale >= patience)
                    if ckpt is not None:
                        ckpt.put_meta(early_stop={
                            "best_val": best_val, "stale": stale,
                            "stopped": stopped,
                            "history": self.validation_history})
                    if stopped:
                        break
        if ckpt is not None:
            ckpt.save(state, step=step, wait=True)
        if last_loss is None:
            # fully-resumed fit (no step ran): evaluate the restored params
            hb = next(iter(frame.batches(local_bs, cols=[fcol, lcol])))
            last_loss = trainer.eval_step(
                state,
                trainer.put_batch(self._pad_batch(hb, fcol, lcol, local_bs)),
                rng)

        params = state["params"]
        if spans:
            # gather fsdp-sharded params into fully-replicated arrays so
            # every process can fetch the fitted model without touching
            # non-addressable shards
            from mmlspark_tpu.parallel.sharding import replicated
            with mesh:
                params = jax.jit(
                    lambda p: p,
                    out_shardings=replicated(mesh))(params)
        params_host = obssyncs.device_get(params, "deep.fetch_params")
        from mmlspark_tpu.models.jax_model import _to_plain
        state_arrays = {
            "params": _to_plain(params_host),
            "mu": mu, "sigma": sigma,
            "standardize": np.asarray(standardize),
            "final_loss": np.asarray(float(
                obssyncs.device_get(last_loss, "deep.final_loss"))),
            # plain list-of-dicts: JSON side of the state, survives
            # save_stage/load_stage (models expose it as a property)
            "validation_history": list(self.validation_history),
        }
        return self._build_fitted(fcol, lcol, resolved_args, state_arrays,
                                  n_out, ymu, ysigma)


@register_stage
class DeepClassifier(_DeepEstimatorBase):
    """Distributed deep-net classifier over a device mesh (CNTKLearner parity)."""

    def _n_out(self, frame, ymax, ymu, ysigma):
        return self._num_classes(frame, ymax)

    def _make_loss(self, module, prep, ymu, ysigma):
        def loss_fn(params, batch, rng):
            logits = module.apply(params, prep(batch["x"]))
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            w = batch["w"]
            return (ce * w).sum() / jnp.maximum(w.sum(), 1.0)
        return loss_fn

    def _make_val_step(self, module, prep, ymu, ysigma):
        @jax.jit
        def f(params, batch):
            logits = module.apply(params, prep(batch["x"])).astype(
                jnp.float32)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"])
            w = batch["w"]
            hit = (jnp.argmax(logits, axis=-1) == batch["y"]).astype(
                jnp.float32)
            return jnp.stack([(ce * w).sum(), (hit * w).sum(), w.sum()])

        def finalize(sums):
            denom = max(float(sums[2]), 1.0)
            return {"val_loss": float(sums[0]) / denom,
                    "val_accuracy": float(sums[1]) / denom}
        return f, finalize

    def _build_fitted(self, fcol, lcol, resolved_args, state_arrays, n_out,
                      ymu, ysigma):
        model = DeepClassifierModel(featuresCol=fcol, labelCol=lcol)
        model.set_params(architecture=self.architecture,
                         architectureArgs=resolved_args)
        model._state = {**state_arrays, "n_classes": np.asarray(int(n_out))}
        return model



class _HasValidationHistory:
    """Mixin: per-epoch validation metrics recorded at fit time, surviving
    save/load (stored on the JSON side of the model state)."""

    @property
    def validation_history(self):
        return list(self._get_state().get("validation_history", []))


def _scoring_prep(model):
    """Shared scoring scaffolding for the fitted deep models: the zoo
    module, device params (jit ARGUMENTS — closure captures inline into the
    HLO as constants), and the standardize/reshape preamble."""
    spec = model._spec()
    module = spec["module"]
    in_shape = tuple(spec["input_shape"])
    params = jax.tree_util.tree_map(jnp.asarray, model._state["params"])
    standardize = bool(model._state.get("standardize", True))
    mu = jnp.asarray(model._state["mu"])
    sigma = jnp.asarray(model._state["sigma"])

    def pre(mu_, sigma_, X):
        x = (X - mu_) / sigma_ if standardize else X
        if len(in_shape) > 1:
            x = x.reshape((x.shape[0],) + in_shape)
        return x

    return module, params, mu, sigma, pre


@register_stage
class DeepClassifierModel(HasFeaturesCol, HasLabelCol,
                          _HasValidationHistory, Model):
    """Fitted deep classifier: streams minibatches through the jitted net.

    The scoring side of the CNTKLearner round trip — the reference wrapped the
    trained model file in a CNTKModel (``CNTKLearner.scala:158-161``); here the
    trained params score through the same flax module, and ``to_jax_model()``
    hands out a JaxModel for intermediate-layer feature extraction."""

    architecture = StringParam("architecture", "model zoo architecture", "")
    architectureArgs = DictParam("architectureArgs", "builder kwargs", {})

    def _spec(self):
        from mmlspark_tpu.models.zoo import build_model
        return build_model(self.architecture, **self.get("architectureArgs"))

    def scores_fn(self):
        module, params, mu, sigma, pre = _scoring_prep(self)

        @jax.jit
        def f(p, mu_, sigma_, X):
            logits = module.apply(p, pre(mu_, sigma_, X))
            return logits, jax.nn.softmax(logits, axis=-1)
        return lambda X: f(params, mu, sigma, X)

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)

    def to_jax_model(self, output_node: str = "",
                     mini_batch_size: int = 1024):
        """A JaxModel over the trained params (layer selection via
        outputNodeName) — the ImageFeaturizer/cutOutputLayers hand-off."""
        from mmlspark_tpu.models.jax_model import JaxModel
        jm = JaxModel(inputCol=self.featuresCol, outputCol="features",
                      miniBatchSize=mini_batch_size,
                      outputNodeName=output_node)
        jm.set_params(architecture=self.architecture,
                      architectureArgs=self.get("architectureArgs"))
        jm._state = {"params": self._state["params"]}
        if bool(self._state.get("standardize", True)):
            # extraction must see the z-scored distribution the net trained on
            spec = self._spec()
            in_shape = tuple(spec["input_shape"])
            jm._state["input_mu"] = np.asarray(
                self._state["mu"], np.float32).reshape(in_shape)
            jm._state["input_sigma"] = np.asarray(
                self._state["sigma"], np.float32).reshape(in_shape)
        return jm


@register_stage
class DeepRegressor(_DeepEstimatorBase):
    """Distributed deep-net regressor over a device mesh (CNTKLearner parity).

    The regression face of the CNTKLearner-parity Estimator (the reference's
    CNTKLearner trained whatever net the BrainScript described —
    classification or regression — ``CNTKLearner.scala:52-162``). Drop-in
    learner for ``TrainRegressor``.

    Targets are z-scored with fit-time statistics (like MLPRegressor) so
    the loss is well-conditioned regardless of label scale; predictions are
    un-scaled on the way out.
    """

    is_classifier = False
    _y_dtype = np.float32

    def _n_out(self, frame, ymax, ymu, ysigma):
        return 1

    def _make_loss(self, module, prep, ymu, ysigma):
        ymu_, ysig_ = float(ymu), float(ysigma)

        def loss_fn(params, batch, rng):
            pred = module.apply(params, prep(batch["x"]))[:, 0]
            target = (batch["y"] - ymu_) / ysig_
            w = batch["w"]
            se = (pred - target) ** 2
            return (se * w).sum() / jnp.maximum(w.sum(), 1.0)
        return loss_fn

    def _make_val_step(self, module, prep, ymu, ysigma):
        ymu_, ysig_ = float(ymu), float(ysigma)

        @jax.jit
        def f(params, batch):
            pred = module.apply(params, prep(batch["x"]))[:, 0].astype(
                jnp.float32) * ysig_ + ymu_
            w = batch["w"]
            se = (pred - batch["y"]) ** 2
            return jnp.stack([(se * w).sum(), w.sum()])

        def finalize(sums):
            denom = max(float(sums[1]), 1.0)
            return {"val_loss": float(sums[0]) / denom}  # MSE, label units
        return f, finalize

    def _build_fitted(self, fcol, lcol, resolved_args, state_arrays, n_out,
                      ymu, ysigma):
        model = DeepRegressorModel(featuresCol=fcol, labelCol=lcol)
        model.set_params(architecture=self.architecture,
                         architectureArgs=resolved_args)
        model._state = {**state_arrays, "ymu": np.asarray(float(ymu)),
                        "ysigma": np.asarray(float(ysigma))}
        return model


@register_stage
class DeepRegressorModel(HasFeaturesCol, HasLabelCol,
                         _HasValidationHistory, Model):
    """Fitted deep regressor scoring through the jitted zoo architecture.

    Streams minibatches through the net and un-scales z-scored predictions
    with the fit-time target statistics."""

    architecture = StringParam("architecture", "model zoo architecture", "")
    architectureArgs = DictParam("architectureArgs", "builder kwargs", {})

    def _spec(self):
        from mmlspark_tpu.models.zoo import build_model
        return build_model(self.architecture, **self.get("architectureArgs"))

    def predict_fn(self):
        module, params, mu, sigma, pre = _scoring_prep(self)
        ymu = float(self._state["ymu"])
        ysigma = float(self._state["ysigma"])

        @jax.jit
        def f(p, mu_, sigma_, X):
            pred = module.apply(p, pre(mu_, sigma_, X))[:, 0]
            return pred * ysigma + ymu
        return lambda X: f(params, mu, sigma, X)

    def transform(self, frame: Frame) -> Frame:
        from mmlspark_tpu.train.learners import _score_regressor
        return _score_regressor(self, frame)
