"""JAX learners: the XLA-compiled replacements for the reference's MLlib zoo.

The reference's TrainClassifier accepts {LogisticRegression, DecisionTree,
RandomForest, GBT, NaiveBayes, MLP} MLlib learners and TrainRegressor the
regression analogues (``train-classifier/src/main/scala/TrainClassifier.scala:94-168``).
Here each learner is an Estimator whose ``fit`` jits one training step (or a
closed form) to XLA and runs it on device; multiclass is handled natively by
a multinomial softmax head instead of the reference's OneVsRest wrapping
(``TrainClassifier.scala:94-106``) — one large batched matmul beats K wrapped
binary problems on the MXU.

Tree learners (DecisionTree/RandomForest/GBT) live in ``train/trees.py``.

Data-parallel training over a device mesh is layered on by
``mmlspark_tpu.parallel``: learners expose pure ``loss_fn``/``init_fn`` so the
trainer can pjit them over the ``data`` axis with psum allreduce over ICI —
the in-process replacement for the reference's `mpiexec ... parallelTrain=true`
CNTK launch (``cntk-train/src/main/scala/CommandBuilders.scala:73-93``).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    FloatParam, HasFeaturesCol, HasLabelCol, IntParam, ListParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.serialization import register_stage


# --------------------------------------------------------------------------
# featurize hints: how TrainClassifier should featurize for this learner
# (reference getFeaturizeParams, TrainClassifier.scala:170-185)
class FeaturizeHints:
    def __init__(self, one_hot: bool = True, num_features: int = 1 << 18):
        self.one_hot = one_hot
        self.num_features = num_features


class HasBatchSize:
    """Mixin for learners that stream minibatches (trees instead stream a
    binning pass into a uint8 matrix — histogram CART keeps the whole
    BINNED dataset, at 1 byte/cell)."""
    batchSize = IntParam("batchSize", "minibatch rows per optimizer step",
                         8192, validator=lambda v: v > 0)


class JaxEstimator(HasFeaturesCol, HasLabelCol, Estimator):
    """Base for JAX learners: streaming stats + minibatch fit helpers.

    Iterative learners train in O(batch) device memory: one jitted step at a
    single compiled shape, tail batches zero-padded and masked by a per-row
    weight (the reference's pad-and-drop workaround ``CNTKModel.scala:71-76``
    done the XLA way). Tree learners (`train/trees.py`) stream a binning
    pass instead and keep only the uint8 bin matrix.
    """

    hints = FeaturizeHints()
    is_classifier = True

    def _collect_xy(self, frame: Frame) -> Tuple[np.ndarray, np.ndarray]:
        X = np.asarray(frame.column(self.featuresCol), dtype=np.float32)
        if X.ndim != 2:
            raise ValueError(f"features column {self.featuresCol!r} must be a "
                             "vector column")
        y = np.asarray(frame.column(self.labelCol))
        return X, y

    def _peek_dim(self, frame: Frame) -> int:
        """Feature-vector width from the first row only (no data scan)."""
        for hb in frame.batches(1, cols=[self.featuresCol]):
            x = np.asarray(hb[self.featuresCol])
            if x.ndim != 2:
                raise ValueError(f"features column {self.featuresCol!r} must "
                                 "be a vector column")
            return x.shape[1]
        raise ValueError(f"{type(self).__name__}: empty frame")

    def _label_max(self, frame: Frame) -> int:
        """Max label value, streaming the label column only."""
        ymax = -1
        for hb in frame.batches(1 << 18, cols=[self.labelCol]):
            y = np.asarray(hb[self.labelCol])
            if len(y):
                ymax = max(ymax, int(y.max()))
        if ymax < 0:
            raise ValueError(f"{type(self).__name__}: empty frame")
        return ymax

    def _streaming_moments(self, frame: Frame):
        """One streaming pass over (features, label): the RAW accumulators
        ``(n, d, s, ss, ymax, ysum, ysumsq)`` — additive across data
        shards, so a multi-process fit can allreduce them before
        ``_finalize_stats`` (each host scans only its own rows)."""
        fcol, lcol = self.featuresCol, self.labelCol
        bs = self.get("batchSize") if any(
            p.name == "batchSize" for p in self.params()) else 1 << 16
        n, d = 0, None
        s = ss = None
        ymax, ysum, ysumsq = -1, 0.0, 0.0
        for hb in frame.batches(bs, cols=[fcol, lcol]):
            x = np.asarray(hb[fcol], dtype=np.float64)
            if x.ndim != 2:
                raise ValueError(
                    f"features column {fcol!r} must be a vector column")
            if d is None:
                d = x.shape[1]
                s, ss = np.zeros(d), np.zeros(d)
            n += x.shape[0]
            s += x.sum(axis=0)
            ss += (x * x).sum(axis=0)
            y = np.asarray(hb[lcol], dtype=np.float64)
            if len(y):
                ymax = max(ymax, int(y.max()))
                ysum += y.sum()
                ysumsq += (y * y).sum()
        return n, d, s, ss, ymax, ysum, ysumsq

    @staticmethod
    def _finalize_stats(n, d, s, ss, ymax, ysum, ysumsq):
        """Moments -> (n, d, mu, sigma, ymax, ymu, ysigma)."""
        if n == 0:
            raise ValueError("empty frame")
        mu = (s / n).astype(np.float32)
        sigma = (np.sqrt(np.maximum(ss / n - (s / n) ** 2, 0.0)) + 1e-6
                 ).astype(np.float32)
        ymu = ysum / n
        ysigma = float(np.sqrt(max(ysumsq / n - ymu * ymu, 0.0))) + 1e-6
        return n, d, mu, sigma, max(int(ymax), 0), float(ymu), ysigma

    def _streaming_stats(self, frame: Frame):
        """One streaming pass over (features, label):
        (n, d, mu, sigma, ymax, ymu, ysigma)."""
        moments = self._streaming_moments(frame)
        if moments[0] == 0:
            raise ValueError(f"{type(self).__name__}: empty frame")
        return self._finalize_stats(*moments)

    def _num_classes(self, frame: Frame, y) -> int:
        """Class count from the label column's level metadata when present —
        rows of a class may have been dropped by NaN cleaning, so max(y)
        alone can under-count. ``y`` is the max label (int) or a label array."""
        if isinstance(y, np.ndarray):
            y = int(y.max()) if len(y) else 1
        seen = int(y) + 1
        cmap = frame.schema[self.labelCol].categorical
        if cmap is not None:
            seen = max(seen, cmap.num_levels)
        return max(seen, 2)


def _pad_xyw(hb: Dict[str, np.ndarray], fcol: str, lcol: str, bs: int,
             y_dtype) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Fixed-shape (x, y, w) batch: zero-pad the tail, mask it via w."""
    x = np.asarray(hb[fcol], dtype=np.float32)
    y = np.asarray(hb[lcol]).astype(y_dtype)
    k = x.shape[0]
    w = np.ones((bs,), np.float32)
    if k < bs:
        x = np.concatenate([x, np.zeros((bs - k,) + x.shape[1:], x.dtype)])
        y = np.concatenate([y, np.zeros((bs - k,), y.dtype)])
        w[k:] = 0.0
    return x, y, w


def _epoch_device_cache(frame: Frame, fcol: str, lcol: str, batch_size: int,
                        y_dtype, mesh=None, seed: int = 0,
                        force: bool = False, local_batch: int = None,
                        steps: int = None):
    """Pad-and-masked epoch -> shuffled DeviceEpochCache, or None when it
    exceeds the ``runtime.device_cache_mb`` budget (unless ``force``).

    THE single constructor behind the deep estimators' ``deviceCache`` and
    the built-in learners' epoch residency. The budget check runs on
    shape/dtype stand-ins so an over-budget frame costs no host
    materialization; the tail rows are padded ONCE with zero weight and
    ride through every shuffled epoch masked out of the loss. Single-batch
    epochs skip the shuffle: batch composition is invariant under
    permutation and the per-epoch gather isn't free.

    Multi-process: ``batch_size`` stays the GLOBAL batch while
    ``local_batch``/``steps`` set this process's quota — its shard pads to
    ``steps * local_batch`` rows and the cache assembles the global epoch
    from every host's contribution (``DeviceEpochCache`` multi-process
    contract).
    """
    from mmlspark_tpu.parallel.trainer import DeviceEpochCache
    local_batch = batch_size if local_batch is None else local_batch
    n = frame.count()
    if n == 0:
        raise ValueError("empty frame")
    d = np.asarray(frame.head(1)[0][fcol]).size
    steps = int(np.ceil(n / local_batch)) if steps is None else steps
    padded = steps * local_batch
    if n > padded:
        raise ValueError(
            f"shard of {n} rows exceeds its epoch quota {padded} "
            f"({steps} steps x {local_batch} local rows)")
    shuffle = steps > 1
    stand_in = {
        "x": np.broadcast_to(np.float32(0), (padded, d)),
        "y": np.broadcast_to(np.zeros((), y_dtype), (padded,)),
        "w": np.broadcast_to(np.float32(0), (padded,))}
    if not force:
        fits = DeviceEpochCache.fits(stand_in, shuffle=shuffle)
        from mmlspark_tpu.parallel.sharding import mesh_spans_processes
        if mesh is not None and mesh_spans_processes(mesh):
            # The verdict must be a GLOBAL decision: each process evaluated
            # fits() on its local padded shard against its local budget, and
            # near the boundary (or with heterogeneous hosts) they can
            # disagree — one running the cached program while another
            # streams means mismatched collectives (hang) or divergent
            # epoch permutations. AND-reduce, like _allreduce_moments.
            from jax.experimental import multihost_utils
            verdicts = np.asarray(multihost_utils.process_allgather(
                np.asarray([1.0 if fits else 0.0])))
            fits = bool(verdicts.min() > 0.5)
        if not fits:
            return None
    x = np.asarray(frame.column(fcol), np.float32)
    y = np.asarray(frame.column(lcol))
    epoch = dict(zip(("x", "y", "w"),
                     _pad_xyw({fcol: x, lcol: y}, fcol, lcol, padded,
                              y_dtype)))
    return DeviceEpochCache(epoch, batch_size, mesh=mesh, shuffle=shuffle,
                            seed=seed)


def _stream_adam(loss_fn: Callable, params: Any, frame: Frame,
                 fcol: str, lcol: str, *, lr: float, max_steps: int,
                 batch_size: int, y_dtype=np.int32, seed: int = 0,
                 prox: Optional[Callable] = None,
                 opt: Optional[optax.GradientTransformation] = None) -> Any:
    """Minibatch Adam streamed from the frame: ONE compiled step shape,
    epochs cycled until ``max_steps`` optimizer steps have run.

    Each epoch streams a FRESH global row permutation, so ordered data
    (label- or time-sorted) never biases a step and every row participates
    as long as ``max_steps`` covers an epoch. ``loss_fn(params, x, y, w)``
    must be a per-row-weighted loss.

    Epoch residency: when the pad-and-masked epoch fits the
    ``runtime.device_cache_mb`` HBM budget (the common case for tabular
    learners), it is placed on device ONCE and every batch is an XLA slice
    of the resident array with a device-side per-epoch shuffle — zero
    steady-state host->HBM transfer. Larger-than-budget frames fall back to
    streaming shuffled host batches. Learners are single-device by design
    (the data-parallel path is DeepClassifier); the cache mesh is pinned to
    one device so the plain-jit step sees uncommitted-compatible inputs.
    """
    opt = optax.adam(lr) if opt is None else opt
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s, x, y, w):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y, w)
        updates, s = opt.update(g, s, p)
        p = optax.apply_updates(p, updates)
        # proximal operator after the smooth step (e.g. L1 soft-threshold
        # for elastic-net LR) — non-smooth penalties don't belong in grad
        return (prox(p) if prox is not None else p), s, loss

    from jax.sharding import Mesh
    # local_devices, not devices: under a multi-process launch the global
    # device 0 belongs to process 0 only — a mesh pinned to it would make
    # every other process's device_put raise on a non-addressable device
    one_dev = Mesh(np.asarray(jax.local_devices()[:1]), ("data",))
    cache = _epoch_device_cache(frame, fcol, lcol, batch_size, y_dtype,
                                mesh=one_dev, seed=seed)
    steps = 0
    if cache is not None:
        # commit state to the cache's mesh up front: otherwise step 1 runs
        # with uncommitted params, step 2 with committed outputs — two
        # compiles of the same step
        from mmlspark_tpu.parallel.sharding import replicated
        rep = replicated(one_dev)
        params = jax.device_put(params, rep)
        opt_state = jax.device_put(opt_state, rep)
        epoch_i = 0
        while steps < max_steps:
            for b in cache.batches(epoch_i):
                params, opt_state, _ = step(params, opt_state,
                                            b["x"], b["y"], b["w"])
                steps += 1
                if steps >= max_steps:
                    break
            epoch_i += 1
        return params

    host_rng = np.random.default_rng(seed)
    while steps < max_steps:
        for hb in frame.shuffled_batches(batch_size, cols=[fcol, lcol],
                                         rng=host_rng):
            dev = tuple(jax.device_put(a)
                        for a in _pad_xyw(hb, fcol, lcol, batch_size, y_dtype))
            params, opt_state, _ = step(params, opt_state, *dev)
            steps += 1
            if steps >= max_steps:
                break
    return params


# --------------------------------------------------------------------------
@register_stage
class LogisticRegression(HasBatchSize, JaxEstimator):
    """Multinomial logistic regression trained by streamed minibatch Adam.

    Epochs are shuffled, the step compiles at one shape, and the Spark
    elastic-net objective applies to the weights
    (``regParam * (elasticNetParam*||w||_1 + (1-elasticNetParam)/2*||w||_2^2)``,
    intercept unregularized, features standardized — the objective Spark
    ML's LogisticRegression minimizes, so a converged fit lands on the
    same convex optimum the reference's benchmark numbers came from).
    The L1 part runs as a proximal soft-threshold after each Adam step.
    ``maxIter`` counts minibatch optimizer steps, not full-dataset
    passes."""

    maxIter = IntParam("maxIter", "number of minibatch optimizer steps", 200)
    regParam = FloatParam("regParam", "regularization strength", 1e-4)
    elasticNetParam = FloatParam(
        "elasticNetParam", "L1 ratio in [0,1]: 0 = pure L2, 1 = pure L1",
        0.0, validator=lambda v: 0.0 <= v <= 1.0)
    learningRate = FloatParam("learningRate", "Adam learning rate", 0.1)

    def fit(self, frame: Frame) -> "LinearClassifierModel":
        n, d, mu, sigma, ymax, _, _ = self._streaming_stats(frame)
        n_classes = self._num_classes(frame, ymax)

        params = {"w": jnp.zeros((d, n_classes), jnp.float32),
                  "b": jnp.zeros((n_classes,), jnp.float32)}
        alpha = float(self.elasticNetParam)
        l1 = float(self.regParam) * alpha
        l2 = float(self.regParam) * (1.0 - alpha) / 2.0
        mu_d, sigma_d = jnp.asarray(mu), jnp.asarray(sigma)

        def loss(p, X, y, w):
            logits = ((X - mu_d) / sigma_d) @ p["w"] + p["b"]
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return (ce * w).sum() / jnp.maximum(w.sum(), 1.0) \
                + l2 * (p["w"] ** 2).sum()

        prox = opt = None
        if l1 > 0:
            # proximal SGD, not Adam: the soft-threshold lr*l1 only matches
            # the smooth step when the step is lr*gradient — Adam's
            # per-coordinate normalization drives every consistent
            # gradient to a ~lr step, so under it L1 can't zero weak
            # features and the fit misses the elastic-net optimum Spark's
            # OWL-QN reaches. learningRate stays the knob, but note SGD on
            # the standardized logistic loss wants ~0.5 where Adam wants
            # ~0.1.
            sgd_lr = float(self.learningRate)
            opt = optax.sgd(sgd_lr)
            shrink = jnp.float32(sgd_lr * l1)

            def prox(p):
                w = p["w"]
                return {**p, "w": jnp.sign(w)
                        * jnp.maximum(jnp.abs(w) - shrink, 0.0)}

        params = _stream_adam(loss, params, frame, self.featuresCol,
                              self.labelCol, lr=self.learningRate,
                              max_steps=self.maxIter,
                              batch_size=self.batchSize, prox=prox, opt=opt)
        model = LinearClassifierModel(featuresCol=self.featuresCol,
                                      labelCol=self.labelCol)
        model._state = {"w": np.asarray(params["w"]), "b": np.asarray(params["b"]),
                        "mu": mu, "sigma": sigma, "n_classes": n_classes}
        return model


@register_stage
class LinearClassifierModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        w = jnp.asarray(self._state["w"])
        b = jnp.asarray(self._state["b"])
        mu = jnp.asarray(self._state["mu"])
        sigma = jnp.asarray(self._state["sigma"])

        @jax.jit
        def f(X):
            logits = ((X - mu) / sigma) @ w + b
            return logits, jax.nn.softmax(logits, axis=-1)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class MLPClassifier(HasBatchSize, JaxEstimator):
    """Multi-layer perceptron classifier (ReLU hidden layers, softmax head)."""

    hints = FeaturizeHints(one_hot=True, num_features=1 << 12)

    layers = ListParam("layers", "hidden layer sizes", [128])
    maxIter = IntParam("maxIter", "number of optimizer steps", 300)
    learningRate = FloatParam("learningRate", "Adam learning rate", 1e-2)
    seed = IntParam("seed", "PRNG seed", 0)

    def fit(self, frame: Frame) -> "MLPClassifierModel":
        n, d, mu, sigma, ymax, _, _ = self._streaming_stats(frame)
        n_classes = self._num_classes(frame, ymax)
        sizes = [d] + [int(h) for h in self.layers] + [n_classes]
        key = jax.random.PRNGKey(self.seed)
        params = []
        for i in range(len(sizes) - 1):
            key, k = jax.random.split(key)
            scale = float(np.sqrt(2.0 / sizes[i]))
            params.append({
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1]), jnp.float32) * scale,
                "b": jnp.zeros((sizes[i + 1],), jnp.float32)})

        mu_d, sigma_d = jnp.asarray(mu), jnp.asarray(sigma)

        def forward(p, X):
            h = (X - mu_d) / sigma_d
            for layer in p[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            return h @ p[-1]["w"] + p[-1]["b"]

        def loss(p, X, y, w):
            ce = optax.softmax_cross_entropy_with_integer_labels(
                forward(p, X), y)
            return (ce * w).sum() / jnp.maximum(w.sum(), 1.0)

        params = _stream_adam(loss, params, frame, self.featuresCol,
                              self.labelCol, lr=self.learningRate,
                              max_steps=self.maxIter,
                              batch_size=self.batchSize)
        model = MLPClassifierModel(featuresCol=self.featuresCol,
                                   labelCol=self.labelCol)
        model._state = {
            "layers": [{"w": np.asarray(l["w"]), "b": np.asarray(l["b"])}
                       for l in params],
            "mu": mu, "sigma": sigma, "n_classes": n_classes}
        return model


@register_stage
class MLPClassifierModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        layers = [{"w": jnp.asarray(l["w"]), "b": jnp.asarray(l["b"])}
                  for l in self._state["layers"]]
        mu = jnp.asarray(self._state["mu"])
        sigma = jnp.asarray(self._state["sigma"])

        @jax.jit
        def f(X):
            h = (X - mu) / sigma
            for layer in layers[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            logits = h @ layers[-1]["w"] + layers[-1]["b"]
            return logits, jax.nn.softmax(logits, axis=-1)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class NaiveBayes(HasBatchSize, JaxEstimator):
    """Multinomial naive Bayes via one batched count matmul (non-negative
    features, e.g. hashed term counts / one-hots)."""

    hints = FeaturizeHints(one_hot=True, num_features=1 << 18)
    smoothing = FloatParam("smoothing", "Laplace smoothing", 1.0)

    def fit(self, frame: Frame) -> "NaiveBayesModel":
        # d from the first row; class count from the observed label max AND
        # the label metadata (metadata alone can under-count when it was fit
        # elsewhere — a label beyond num_levels would silently one-hot to
        # zero and vanish from the counts). The label-only pass is cheap.
        d = self._peek_dim(frame)
        ymax = self._label_max(frame)
        n_classes = self._num_classes(frame, ymax)
        bs = self.batchSize

        @jax.jit
        def accum(counts, prior, X, y, w):
            onehot = jax.nn.one_hot(y, n_classes, dtype=jnp.float32) \
                * w[:, None]                                          # (b, C)
            return counts + onehot.T @ jnp.maximum(X, 0.0), \
                prior + onehot.sum(axis=0)

        counts = jnp.zeros((n_classes, d), jnp.float32)
        prior = jnp.zeros((n_classes,), jnp.float32)
        for hb in frame.batches(bs, cols=[self.featuresCol, self.labelCol]):
            x, y, w = _pad_xyw(hb, self.featuresCol, self.labelCol, bs,
                               np.int32)
            counts, prior = accum(counts, prior, x, y, w)

        @jax.jit
        def finalize(counts, prior):
            log_prior = jnp.log((prior + 1.0) / (prior.sum() + n_classes))
            smoothed = counts + self.smoothing
            log_cond = jnp.log(smoothed / smoothed.sum(axis=1, keepdims=True))
            return log_prior, log_cond

        log_prior, log_cond = finalize(counts, prior)
        model = NaiveBayesModel(featuresCol=self.featuresCol, labelCol=self.labelCol)
        model._state = {"log_prior": np.asarray(log_prior),
                        "log_cond": np.asarray(log_cond), "n_classes": n_classes}
        return model


@register_stage
class NaiveBayesModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        log_prior = jnp.asarray(self._state["log_prior"])
        log_cond = jnp.asarray(self._state["log_cond"])

        @jax.jit
        def f(X):
            logits = jnp.maximum(X, 0.0) @ log_cond.T + log_prior
            return logits, jax.nn.softmax(logits, axis=-1)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class LinearRegression(HasBatchSize, JaxEstimator):
    """Ridge regression by closed-form normal equations (exact, one solve)."""

    is_classifier = False
    regParam = FloatParam("regParam", "L2 regularization strength", 1e-6)

    def fit(self, frame: Frame) -> "LinearRegressionModel":
        d = self._peek_dim(frame)
        bs = self.batchSize

        # Streaming normal equations: accumulate the (d+1)x(d+1) Gram matrix
        # and moment vector per batch — exact solution in O(batch + d^2)
        # memory, one MXU matmul per chunk.
        @jax.jit
        def accum(A, by, X, y, w):
            Xb = jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)],
                                 axis=1)
            return A + (Xb * w[:, None]).T @ Xb, by + Xb.T @ (y * w)

        A = jnp.zeros((d + 1, d + 1), jnp.float32)
        by = jnp.zeros((d + 1,), jnp.float32)
        for hb in frame.batches(bs, cols=[self.featuresCol, self.labelCol]):
            x, y, w = _pad_xyw(hb, self.featuresCol, self.labelCol, bs,
                               np.float32)
            A, by = accum(A, by, x, y, w)

        @jax.jit
        def solve(A, by):
            return jnp.linalg.solve(
                A + self.regParam * jnp.eye(A.shape[0], dtype=A.dtype), by)

        wb = np.asarray(solve(A, by))
        model = LinearRegressionModel(featuresCol=self.featuresCol,
                                      labelCol=self.labelCol)
        model._state = {"w": wb[:-1], "b": float(wb[-1])}
        return model


@register_stage
class LinearRegressionModel(HasFeaturesCol, HasLabelCol, Model):
    def predict_fn(self):
        w = jnp.asarray(self._state["w"])
        b = self._state["b"]

        @jax.jit
        def f(X):
            return X @ w + b
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_regressor(self, frame)


@register_stage
class MLPRegressor(HasBatchSize, JaxEstimator):
    is_classifier = False
    hints = FeaturizeHints(one_hot=True, num_features=1 << 12)

    layers = ListParam("layers", "hidden layer sizes", [128])
    maxIter = IntParam("maxIter", "number of optimizer steps", 300)
    learningRate = FloatParam("learningRate", "Adam learning rate", 1e-2)
    seed = IntParam("seed", "PRNG seed", 0)

    def fit(self, frame: Frame) -> "MLPRegressorModel":
        n, d, mu, sigma, _, ymu, ysigma = self._streaming_stats(frame)
        sizes = [d] + [int(h) for h in self.layers] + [1]
        key = jax.random.PRNGKey(self.seed)
        params = []
        for i in range(len(sizes) - 1):
            key, k = jax.random.split(key)
            scale = float(np.sqrt(2.0 / sizes[i]))
            params.append({
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1]), jnp.float32) * scale,
                "b": jnp.zeros((sizes[i + 1],), jnp.float32)})

        mu_d, sigma_d = jnp.asarray(mu), jnp.asarray(sigma)

        def forward(p, X):
            h = (X - mu_d) / sigma_d
            for layer in p[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            return (h @ p[-1]["w"] + p[-1]["b"])[:, 0]

        def loss(p, X, y, w):
            se = (forward(p, X) - (y - ymu) / ysigma) ** 2
            return (se * w).sum() / jnp.maximum(w.sum(), 1.0)

        params = _stream_adam(loss, params, frame, self.featuresCol,
                              self.labelCol, lr=self.learningRate,
                              max_steps=self.maxIter,
                              batch_size=self.batchSize,
                              y_dtype=np.float32)
        model = MLPRegressorModel(featuresCol=self.featuresCol,
                                  labelCol=self.labelCol)
        model._state = {
            "layers": [{"w": np.asarray(l["w"]), "b": np.asarray(l["b"])}
                       for l in params],
            "mu": mu, "sigma": sigma, "ymu": ymu, "ysigma": ysigma}
        return model


@register_stage
class MLPRegressorModel(HasFeaturesCol, HasLabelCol, Model):
    def predict_fn(self):
        layers = [{"w": jnp.asarray(l["w"]), "b": jnp.asarray(l["b"])}
                  for l in self._state["layers"]]
        mu = jnp.asarray(self._state["mu"])
        sigma = jnp.asarray(self._state["sigma"])
        ymu, ysigma = self._state["ymu"], self._state["ysigma"]

        @jax.jit
        def f(X):
            h = (X - mu) / sigma
            for layer in layers[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            return (h @ layers[-1]["w"] + layers[-1]["b"])[:, 0] * ysigma + ymu
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_regressor(self, frame)


# --------------------------------------------------------------------------
# scoring helpers shared by all learner models
from mmlspark_tpu.core.schema import ColumnSchema, DType  # noqa: E402


def _pad_rows(x: np.ndarray, bs: int) -> np.ndarray:
    """Zero-pad a partial batch up to ``bs`` rows: ONE compiled shape for
    every batch of a stream (tail rows are sliced off after scoring)."""
    k = x.shape[0]
    if k == bs:
        return x
    return np.concatenate([x, np.zeros((bs - k,) + x.shape[1:], x.dtype)])


def _device_feature_batches(model, frame: Frame, bs: int):
    """Iterate (device_batch, valid_rows) for scoring. The coerced padded
    feature batches go through the residency registry, so re-scoring the
    SAME frame — K FindBestModel candidates, repeated evaluation passes —
    transfers the features to HBM once and slices on device; an
    over-budget frame streams a put per batch as before."""
    from mmlspark_tpu.models import residency
    n_rows = frame.count()

    def build() -> np.ndarray:
        return np.stack([
            _pad_rows(np.asarray(b[model.featuresCol], np.float32), bs)
            for b in frame.batches(bs, cols=[model.featuresCol])])

    dev = None
    # residency declines out-of-core frames itself; the hint rejects
    # over-budget frames BEFORE any materialization
    if n_rows:
        d = np.asarray(frame.head(1)[0][model.featuresCol]).size
        steps = int(np.ceil(n_rows / bs))
        dev = residency.resident_batches(
            frame, (model.featuresCol, bs, "learner-f32"), build,
            nbytes_hint=steps * bs * d * 4)
    if dev is not None:
        for i in range(dev.shape[0]):
            yield dev[i], min(bs, n_rows - i * bs)
        return
    for batch in frame.batches(bs, cols=[model.featuresCol]):
        x = np.asarray(batch[model.featuresCol], dtype=np.float32)
        yield jnp.asarray(_pad_rows(x, bs)), x.shape[0]


def _score_classifier(model, frame: Frame, batch_size: int = 65536) -> Frame:
    """Append prediction / raw scores / probabilities columns.

    Streams minibatches to device — the reference's buffered minibatch
    iterator (``CNTKModel.scala:50-104``) without per-element copies. The
    tail batch is padded to the compiled shape and sliced after, so a stream
    never retraces (``CNTKModel.scala:71-76`` semantics, XLA motivation).
    """
    f = model._cached_jit(model.scores_fn)
    n_rows = frame.count()
    bs = min(batch_size, max(n_rows, 1))
    preds, scores, probs = [], [], []
    for x, k in _device_feature_batches(model, frame, bs):
        logits, p = f(x)
        preds.append(np.asarray(jnp.argmax(logits, axis=-1))[:k])
        scores.append(np.asarray(logits)[:k])
        probs.append(np.asarray(p)[:k])
    pred = np.concatenate(preds) if preds else np.zeros(0, np.int64)
    out = frame.with_column_values(
        ColumnSchema("prediction", DType.FLOAT64), pred.astype(np.float64))
    out = out.with_column_values(
        ColumnSchema("rawPrediction", DType.VECTOR), np.concatenate(scores)
        if scores else np.zeros((0, 2), np.float32))
    out = out.with_column_values(
        ColumnSchema("probability", DType.VECTOR), np.concatenate(probs)
        if probs else np.zeros((0, 2), np.float32))
    return out


def _score_regressor(model, frame: Frame, batch_size: int = 65536) -> Frame:
    f = model._cached_jit(model.predict_fn)
    n_rows = frame.count()
    bs = min(batch_size, max(n_rows, 1))
    preds = []
    for x, k in _device_feature_batches(model, frame, bs):
        preds.append(np.asarray(f(x))[:k])
    pred = np.concatenate(preds) if preds else np.zeros(0, np.float64)
    return frame.with_column_values(
        ColumnSchema("prediction", DType.FLOAT64), pred.astype(np.float64))
