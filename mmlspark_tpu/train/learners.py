"""JAX learners: the XLA-compiled replacements for the reference's MLlib zoo.

The reference's TrainClassifier accepts {LogisticRegression, DecisionTree,
RandomForest, GBT, NaiveBayes, MLP} MLlib learners and TrainRegressor the
regression analogues (``train-classifier/src/main/scala/TrainClassifier.scala:94-168``).
Here each learner is an Estimator whose ``fit`` jits one training step (or a
closed form) to XLA and runs it on device; multiclass is handled natively by
a multinomial softmax head instead of the reference's OneVsRest wrapping
(``TrainClassifier.scala:94-106``) — one large batched matmul beats K wrapped
binary problems on the MXU.

Tree learners (DecisionTree/RandomForest/GBT) live in ``train/trees.py``.

Data-parallel training over a device mesh is layered on by
``mmlspark_tpu.parallel``: learners expose pure ``loss_fn``/``init_fn`` so the
trainer can pjit them over the ``data`` axis with psum allreduce over ICI —
the in-process replacement for the reference's `mpiexec ... parallelTrain=true`
CNTK launch (``cntk-train/src/main/scala/CommandBuilders.scala:73-93``).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    FloatParam, HasFeaturesCol, HasLabelCol, IntParam, ListParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.serialization import register_stage


# --------------------------------------------------------------------------
# featurize hints: how TrainClassifier should featurize for this learner
# (reference getFeaturizeParams, TrainClassifier.scala:170-185)
class FeaturizeHints:
    def __init__(self, one_hot: bool = True, num_features: int = 1 << 18):
        self.one_hot = one_hot
        self.num_features = num_features


class JaxEstimator(HasFeaturesCol, HasLabelCol, Estimator):
    """Base: pulls (X, y) host arrays from the frame, hands them to _train."""

    hints = FeaturizeHints()
    is_classifier = True

    def _collect_xy(self, frame: Frame) -> Tuple[np.ndarray, np.ndarray]:
        X = np.asarray(frame.column(self.featuresCol), dtype=np.float32)
        if X.ndim != 2:
            raise ValueError(f"features column {self.featuresCol!r} must be a "
                             "vector column")
        y = np.asarray(frame.column(self.labelCol))
        return X, y

    def _num_classes(self, frame: Frame, y: np.ndarray) -> int:
        """Class count from the label column's level metadata when present —
        rows of a class may have been dropped by NaN cleaning, so y.max()
        alone can under-count."""
        seen = int(y.max()) + 1 if len(y) else 2
        cmap = frame.schema[self.labelCol].categorical
        if cmap is not None:
            seen = max(seen, cmap.num_levels)
        return max(seen, 2)


def _full_batch_adam(loss_fn: Callable, params: Any, data: Tuple,
                     lr: float, steps: int) -> Any:
    """Full-batch Adam, the whole loop compiled as one XLA program."""
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    grad_fn = jax.grad(loss_fn)

    def body(_, carry):
        p, s = carry
        g = grad_fn(p, *data)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s

    @jax.jit
    def run(params, opt_state):
        return jax.lax.fori_loop(0, steps, body, (params, opt_state))

    params, _ = run(params, opt_state)
    return params


# --------------------------------------------------------------------------
@register_stage
class LogisticRegression(JaxEstimator):
    """Multinomial logistic regression, full-batch Adam, L2 regularization."""

    maxIter = IntParam("maxIter", "number of optimizer steps", 200)
    regParam = FloatParam("regParam", "L2 regularization strength", 1e-4)
    learningRate = FloatParam("learningRate", "Adam learning rate", 0.1)

    def fit(self, frame: Frame) -> "LinearClassifierModel":
        X, y = self._collect_xy(frame)
        y = y.astype(np.int32)
        n_classes = self._num_classes(frame, y)
        d = X.shape[1]
        mu, sigma = X.mean(axis=0), X.std(axis=0) + 1e-6

        params = {"w": jnp.zeros((d, n_classes), jnp.float32),
                  "b": jnp.zeros((n_classes,), jnp.float32)}
        Xd = (jnp.asarray(X) - mu) / sigma
        yd = jnp.asarray(y)
        reg = self.regParam

        def loss(p, X, y):
            logits = X @ p["w"] + p["b"]
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            return ce + reg * (p["w"] ** 2).sum()

        params = _full_batch_adam(loss, params, (Xd, yd),
                                  self.learningRate, self.maxIter)
        model = LinearClassifierModel(featuresCol=self.featuresCol,
                                      labelCol=self.labelCol)
        model._state = {"w": np.asarray(params["w"]), "b": np.asarray(params["b"]),
                        "mu": mu, "sigma": sigma, "n_classes": n_classes}
        return model


@register_stage
class LinearClassifierModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        w = jnp.asarray(self._state["w"])
        b = jnp.asarray(self._state["b"])
        mu = jnp.asarray(self._state["mu"])
        sigma = jnp.asarray(self._state["sigma"])

        @jax.jit
        def f(X):
            logits = ((X - mu) / sigma) @ w + b
            return logits, jax.nn.softmax(logits, axis=-1)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class MLPClassifier(JaxEstimator):
    """Multi-layer perceptron classifier (ReLU hidden layers, softmax head)."""

    hints = FeaturizeHints(one_hot=True, num_features=1 << 12)

    layers = ListParam("layers", "hidden layer sizes", [128])
    maxIter = IntParam("maxIter", "number of optimizer steps", 300)
    learningRate = FloatParam("learningRate", "Adam learning rate", 1e-2)
    seed = IntParam("seed", "PRNG seed", 0)

    def fit(self, frame: Frame) -> "MLPClassifierModel":
        X, y = self._collect_xy(frame)
        y = y.astype(np.int32)
        n_classes = self._num_classes(frame, y)
        mu, sigma = X.mean(axis=0), X.std(axis=0) + 1e-6
        sizes = [X.shape[1]] + [int(h) for h in self.layers] + [n_classes]
        key = jax.random.PRNGKey(self.seed)
        params = []
        for i in range(len(sizes) - 1):
            key, k = jax.random.split(key)
            scale = float(np.sqrt(2.0 / sizes[i]))
            params.append({
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1]), jnp.float32) * scale,
                "b": jnp.zeros((sizes[i + 1],), jnp.float32)})

        def forward(p, X):
            h = X
            for layer in p[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            return h @ p[-1]["w"] + p[-1]["b"]

        def loss(p, X, y):
            return optax.softmax_cross_entropy_with_integer_labels(
                forward(p, X), y).mean()

        Xd = (jnp.asarray(X) - mu) / sigma
        params = _full_batch_adam(loss, params, (Xd, jnp.asarray(y)),
                                  self.learningRate, self.maxIter)
        model = MLPClassifierModel(featuresCol=self.featuresCol,
                                   labelCol=self.labelCol)
        model._state = {
            "layers": [{"w": np.asarray(l["w"]), "b": np.asarray(l["b"])}
                       for l in params],
            "mu": mu, "sigma": sigma, "n_classes": n_classes}
        return model


@register_stage
class MLPClassifierModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        layers = [{"w": jnp.asarray(l["w"]), "b": jnp.asarray(l["b"])}
                  for l in self._state["layers"]]
        mu = jnp.asarray(self._state["mu"])
        sigma = jnp.asarray(self._state["sigma"])

        @jax.jit
        def f(X):
            h = (X - mu) / sigma
            for layer in layers[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            logits = h @ layers[-1]["w"] + layers[-1]["b"]
            return logits, jax.nn.softmax(logits, axis=-1)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class NaiveBayes(JaxEstimator):
    """Multinomial naive Bayes via one batched count matmul (non-negative
    features, e.g. hashed term counts / one-hots)."""

    hints = FeaturizeHints(one_hot=True, num_features=1 << 18)
    smoothing = FloatParam("smoothing", "Laplace smoothing", 1.0)

    def fit(self, frame: Frame) -> "NaiveBayesModel":
        X, y = self._collect_xy(frame)
        y = y.astype(np.int32)
        n_classes = self._num_classes(frame, y)

        @jax.jit
        def train(X, y):
            X = jnp.maximum(X, 0.0)
            onehot = jax.nn.one_hot(y, n_classes, dtype=jnp.float32)  # (n, C)
            counts = onehot.T @ X                                     # (C, d)
            prior = onehot.sum(axis=0)
            log_prior = jnp.log((prior + 1.0) / (prior.sum() + n_classes))
            smoothed = counts + self.smoothing
            log_cond = jnp.log(smoothed / smoothed.sum(axis=1, keepdims=True))
            return log_prior, log_cond

        log_prior, log_cond = train(jnp.asarray(X), jnp.asarray(y))
        model = NaiveBayesModel(featuresCol=self.featuresCol, labelCol=self.labelCol)
        model._state = {"log_prior": np.asarray(log_prior),
                        "log_cond": np.asarray(log_cond), "n_classes": n_classes}
        return model


@register_stage
class NaiveBayesModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        log_prior = jnp.asarray(self._state["log_prior"])
        log_cond = jnp.asarray(self._state["log_cond"])

        @jax.jit
        def f(X):
            logits = jnp.maximum(X, 0.0) @ log_cond.T + log_prior
            return logits, jax.nn.softmax(logits, axis=-1)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class LinearRegression(JaxEstimator):
    """Ridge regression by closed-form normal equations (exact, one solve)."""

    is_classifier = False
    regParam = FloatParam("regParam", "L2 regularization strength", 1e-6)

    def fit(self, frame: Frame) -> "LinearRegressionModel":
        X, y = self._collect_xy(frame)
        y = y.astype(np.float32)

        @jax.jit
        def solve(X, y):
            Xb = jnp.concatenate([X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)
            A = Xb.T @ Xb + self.regParam * jnp.eye(Xb.shape[1], dtype=X.dtype)
            return jnp.linalg.solve(A, Xb.T @ y)

        wb = np.asarray(solve(jnp.asarray(X), jnp.asarray(y)))
        model = LinearRegressionModel(featuresCol=self.featuresCol,
                                      labelCol=self.labelCol)
        model._state = {"w": wb[:-1], "b": float(wb[-1])}
        return model


@register_stage
class LinearRegressionModel(HasFeaturesCol, HasLabelCol, Model):
    def predict_fn(self):
        w = jnp.asarray(self._state["w"])
        b = self._state["b"]

        @jax.jit
        def f(X):
            return X @ w + b
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_regressor(self, frame)


@register_stage
class MLPRegressor(JaxEstimator):
    is_classifier = False
    hints = FeaturizeHints(one_hot=True, num_features=1 << 12)

    layers = ListParam("layers", "hidden layer sizes", [128])
    maxIter = IntParam("maxIter", "number of optimizer steps", 300)
    learningRate = FloatParam("learningRate", "Adam learning rate", 1e-2)
    seed = IntParam("seed", "PRNG seed", 0)

    def fit(self, frame: Frame) -> "MLPRegressorModel":
        X, y = self._collect_xy(frame)
        y = y.astype(np.float32)
        mu, sigma = X.mean(axis=0), X.std(axis=0) + 1e-6
        ymu, ysigma = float(y.mean()), float(y.std() + 1e-6)
        sizes = [X.shape[1]] + [int(h) for h in self.layers] + [1]
        key = jax.random.PRNGKey(self.seed)
        params = []
        for i in range(len(sizes) - 1):
            key, k = jax.random.split(key)
            scale = float(np.sqrt(2.0 / sizes[i]))
            params.append({
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1]), jnp.float32) * scale,
                "b": jnp.zeros((sizes[i + 1],), jnp.float32)})

        def forward(p, X):
            h = X
            for layer in p[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            return (h @ p[-1]["w"] + p[-1]["b"])[:, 0]

        def loss(p, X, y):
            return ((forward(p, X) - y) ** 2).mean()

        Xd = (jnp.asarray(X) - mu) / sigma
        yd = (jnp.asarray(y) - ymu) / ysigma
        params = _full_batch_adam(loss, params, (Xd, yd),
                                  self.learningRate, self.maxIter)
        model = MLPRegressorModel(featuresCol=self.featuresCol,
                                  labelCol=self.labelCol)
        model._state = {
            "layers": [{"w": np.asarray(l["w"]), "b": np.asarray(l["b"])}
                       for l in params],
            "mu": mu, "sigma": sigma, "ymu": ymu, "ysigma": ysigma}
        return model


@register_stage
class MLPRegressorModel(HasFeaturesCol, HasLabelCol, Model):
    def predict_fn(self):
        layers = [{"w": jnp.asarray(l["w"]), "b": jnp.asarray(l["b"])}
                  for l in self._state["layers"]]
        mu = jnp.asarray(self._state["mu"])
        sigma = jnp.asarray(self._state["sigma"])
        ymu, ysigma = self._state["ymu"], self._state["ysigma"]

        @jax.jit
        def f(X):
            h = (X - mu) / sigma
            for layer in layers[:-1]:
                h = jax.nn.relu(h @ layer["w"] + layer["b"])
            return (h @ layers[-1]["w"] + layers[-1]["b"])[:, 0] * ysigma + ymu
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_regressor(self, frame)


# --------------------------------------------------------------------------
# scoring helpers shared by all learner models
from mmlspark_tpu.core.schema import ColumnSchema, DType  # noqa: E402


def _score_classifier(model, frame: Frame, batch_size: int = 65536) -> Frame:
    """Append prediction / raw scores / probabilities columns.

    Streams minibatches to device — the reference's buffered minibatch
    iterator (``CNTKModel.scala:50-104``) without per-element copies.
    """
    f = model._cached_jit(model.scores_fn)
    preds, scores, probs = [], [], []
    for batch in frame.batches(batch_size, cols=[model.featuresCol]):
        logits, p = f(jnp.asarray(batch[model.featuresCol]))
        preds.append(np.asarray(jnp.argmax(logits, axis=-1)))
        scores.append(np.asarray(logits))
        probs.append(np.asarray(p))
    pred = np.concatenate(preds) if preds else np.zeros(0, np.int64)
    out = frame.with_column_values(
        ColumnSchema("prediction", DType.FLOAT64), pred.astype(np.float64))
    out = out.with_column_values(
        ColumnSchema("rawPrediction", DType.VECTOR), np.concatenate(scores)
        if scores else np.zeros((0, 2), np.float32))
    out = out.with_column_values(
        ColumnSchema("probability", DType.VECTOR), np.concatenate(probs)
        if probs else np.zeros((0, 2), np.float32))
    return out


def _score_regressor(model, frame: Frame, batch_size: int = 65536) -> Frame:
    f = model._cached_jit(model.predict_fn)
    preds = []
    for batch in frame.batches(batch_size, cols=[model.featuresCol]):
        preds.append(np.asarray(f(jnp.asarray(batch[model.featuresCol]))))
    pred = np.concatenate(preds) if preds else np.zeros(0, np.float64)
    return frame.with_column_values(
        ColumnSchema("prediction", DType.FLOAT64), pred.astype(np.float64))
