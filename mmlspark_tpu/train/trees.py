"""Tree learners: DecisionTree / RandomForest / GBT, classifier + regressor.

Capability parity with the MLlib tree learners the reference's
TrainClassifier/TrainRegressor accept (``TrainClassifier.scala:94-150``,
``TrainRegressor.scala:43-117``), re-designed TPU-first:

- MLlib grows trees with per-partition row iteration and driver-side split
  aggregation. Here a tree is grown LEVEL-WISE as a fixed-shape XLA program:
  one scatter-add builds the (node, feature, bin) histogram for the whole
  level, a cumulative sum turns it into every candidate split's left/right
  statistics, and an argmax picks the best split per node — no data-dependent
  control flow, so the whole fit jits.
- A random forest is ``vmap`` of that builder over per-tree bootstrap weights
  and feature masks: T trees build in ONE compiled program instead of T
  sequential passes.
- Features are quantile-binned once on host (LightGBM-style): edges from a
  streamed row sample, then a streaming pass bins every row into a uint8
  matrix (1 byte/cell host-side AND over the wire) — no fp32
  materialization, so trees fit DiskFrames bigger than RAM. The model
  stores real-valued thresholds so scoring needs no binning.

One histogram engine serves all six learners: statistics are C "value"
channels plus a weight channel; split gain is sum_c VL_c^2/(WL+lam) +
sum_c VR_c^2/(WR+lam), which specializes to gini gain (V=class one-hots),
variance reduction (V=y), and the XGBoost gradient gain (V=g, W=h).

Trees are perfect binary trees of static ``maxDepth``: a node that cannot
improve routes all rows left (threshold=+inf) and both children inherit its
leaf value — shape-static by construction, which is what lets XLA compile
one program for every tree in a forest.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    FloatParam, HasFeaturesCol, HasLabelCol, IntParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.train.learners import (
    FeaturizeHints, JaxEstimator, _score_classifier, _score_regressor,
)

_NEG = -1e30  # masked-gain sentinel (finite: -inf breaks argmax ties on XLA)


# --------------------------------------------------------------------------
# host-side quantile binning
def make_bin_edges(X: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature ascending split candidates, (F, max_bins-1) float32.

    Quantile edges over finite values; features with fewer distinct values
    pad with +inf (empty bins are harmless). Row bin b means
    ``edges[f, b-1] < x <= edges[f, b]``; going right at split b tests
    ``x > edges[f, b]``.
    """
    n, F = X.shape
    B = max_bins
    edges = np.full((F, B - 1), np.inf, dtype=np.float32)
    qs = np.linspace(0, 1, B + 1)[1:-1]
    for f in range(F):
        col = X[:, f]
        col = col[np.isfinite(col)]
        if col.size == 0:
            continue
        uniq = np.unique(col)
        if uniq.size <= 1:
            continue
        if uniq.size <= B - 1:
            # exact midpoints between consecutive distinct values
            mids = (uniq[:-1] + uniq[1:]) / 2.0
            edges[f, :mids.size] = mids
        else:
            cand = np.unique(np.quantile(col, qs))
            edges[f, :cand.size] = cand
    return edges


def bin_features(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin rows against edges; NaN maps to the left-most bin (scoring sends
    NaN left because ``NaN > t`` is False — keep fit consistent)."""
    Xc = np.nan_to_num(X, nan=-np.inf, posinf=np.finfo(np.float32).max)
    F = X.shape[1]
    out = np.empty(X.shape, dtype=np.int32)
    for f in range(F):
        out[:, f] = np.searchsorted(edges[f], Xc[:, f], side="left")
    return out


# --------------------------------------------------------------------------
# the level-wise builder (pure jax; vmap-able over trees)
def grow_tree(Xb: jnp.ndarray, V: jnp.ndarray, w: jnp.ndarray,
              feat_mask: jnp.ndarray, depth: int, n_bins: int,
              lam: float, min_child_weight: float,
              counts: Optional[jnp.ndarray] = None):
    """Grow one depth-``depth`` tree.

    Xb (n, F) int32 binned features; V (n, C) value channels; w (n,) weights
    (0-weight rows are ignored — that is how bootstrap/boosting masks rows);
    feat_mask (F,) bool selects splittable features. ``counts`` (n,), when
    given, is the channel the min-child test uses instead of ``w`` — needed
    by boosting, where w carries hessians (<=0.25/row for logistic loss) but
    minInstancesPerNode means ROWS.

    Returns (feats (2^depth-1,), bins (2^depth-1,), leaf_V (2^depth, C),
    leaf_w (2^depth,), node (n,) final leaf assignment).
    """
    n, F = Xb.shape
    C = V.shape[1]
    B = n_bins
    chans = [V, w[:, None]]
    if counts is not None:
        chans.append(counts[:, None])
    S = jnp.concatenate(chans, axis=1)                 # (n, C+1[+1])
    n_chan = S.shape[1]
    node = jnp.zeros(n, jnp.int32)
    feats_levels, bins_levels = [], []

    col_idx = jnp.arange(F, dtype=jnp.int32)[None, :]  # (1, F)
    for d in range(depth):
        n_nodes = 1 << d
        # histogram over (node, feature, bin) for all channels at once
        idx = ((node[:, None] * F + col_idx) * B + Xb).reshape(-1)
        vals = jnp.broadcast_to(S[:, None, :], (n, F, n_chan)).reshape(-1, n_chan)
        hist = jnp.zeros((n_nodes * F * B, n_chan), S.dtype).at[idx].add(vals)
        hist = hist.reshape(n_nodes, F, B, n_chan)

        cum = jnp.cumsum(hist, axis=2)                  # (N, F, B, n_chan)
        total = cum[:, :, -1:, :]                       # (N, F, 1, n_chan)
        SL, SR = cum, total - cum
        VL, WL = SL[..., :C], SL[..., C]
        VR, WR = SR[..., :C], SR[..., C]
        gain = ((VL ** 2).sum(-1) / (WL + lam)
                + (VR ** 2).sum(-1) / (WR + lam))       # (N, F, B)
        CL = SL[..., -1] if counts is not None else WL
        CR = SR[..., -1] if counts is not None else WR
        ok = ((CL >= min_child_weight) & (CR >= min_child_weight))
        ok &= feat_mask[None, :, None]
        ok = ok.at[:, :, B - 1].set(False)              # last bin: no split
        gain = jnp.where(ok, gain, _NEG)

        flat = gain.reshape(n_nodes, F * B)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        feat = (best // B).astype(jnp.int32)
        bin_ = (best % B).astype(jnp.int32)
        # No VALID candidate (all masked) -> dead-end: route everything left.
        # A valid split never loses gain (sum V^2/W is superadditive), and
        # zero-gain splits must stay allowed or XOR-like targets — where the
        # first cut alone looks useless — never get resolved by depth 2.
        splittable = best_gain > _NEG / 2
        feat = jnp.where(splittable, feat, 0)
        bin_ = jnp.where(splittable, bin_, B - 1)
        feats_levels.append(feat)
        bins_levels.append(bin_)

        row_feat = feat[node]
        row_bin = bin_[node]
        go_right = Xb[jnp.arange(n), row_feat] > row_bin
        node = 2 * node + go_right.astype(jnp.int32)

    n_leaves = 1 << depth
    leaf_S = jnp.zeros((n_leaves, n_chan), S.dtype).at[node].add(S)
    feats = jnp.concatenate(feats_levels) if depth else jnp.zeros(0, jnp.int32)
    bins = jnp.concatenate(bins_levels) if depth else jnp.zeros(0, jnp.int32)
    return feats, bins, leaf_S[:, :C], leaf_S[:, C], node


def bins_to_thresholds(feats: np.ndarray, bins: np.ndarray,
                       edges: np.ndarray) -> np.ndarray:
    """Split-bin indices -> real thresholds (+inf for dead-end nodes)."""
    B = edges.shape[1] + 1
    thr = np.where(bins >= B - 1, np.inf,
                   edges[feats, np.minimum(bins, B - 2)])
    return thr.astype(np.float32)


def predict_leaves(X: jnp.ndarray, feats: jnp.ndarray, thrs: jnp.ndarray,
                   depth: int) -> jnp.ndarray:
    """Leaf index per row for one tree (NaN routes left)."""
    n = X.shape[0]
    node = jnp.zeros(n, jnp.int32)
    rows = jnp.arange(n)
    for d in range(depth):
        offset = (1 << d) - 1
        f = feats[offset + node]
        t = thrs[offset + node]
        node = 2 * node + (X[rows, f] > t).astype(jnp.int32)
    return node


# --------------------------------------------------------------------------
# shared learner plumbing
_TREE_HINTS = FeaturizeHints(one_hot=False, num_features=1 << 12)


def _feature_masks(F: int, n_trees: int, strategy: str, is_classifier: bool,
                   rng: np.random.Generator) -> np.ndarray:
    """Per-tree boolean feature masks (Spark featureSubsetStrategy).

    'auto' resolves to 'all' for a single tree, else sqrt (classification) /
    onethird (regression); an EXPLICIT strategy is honored even for one tree.
    """
    if strategy == "auto":
        strategy = ("all" if n_trees == 1
                    else "sqrt" if is_classifier else "onethird")
    if strategy == "all":
        return np.ones((n_trees, F), bool)
    k = {"sqrt": max(1, int(np.sqrt(F))),
         "log2": max(1, int(np.log2(F))),
         "onethird": max(1, F // 3)}.get(strategy)
    if k is None:
        raise ValueError(f"unknown featureSubsetStrategy {strategy!r}")
    masks = np.zeros((n_trees, F), bool)
    for t in range(n_trees):
        masks[t, rng.choice(F, size=min(k, F), replace=False)] = True
    return masks


_BIN_SAMPLE_ROWS = 1 << 18  # rows sampled for quantile edges (LightGBM-style)


def _device_bins(Xb: np.ndarray) -> jnp.ndarray:
    """uint8 bin matrix -> int32 ON DEVICE: 1 byte/cell crosses host->HBM
    (grow_tree's index arithmetic needs int32, but the wire doesn't)."""
    return jnp.asarray(Xb).astype(jnp.int32)


class _TreeParams(JaxEstimator):
    maxDepth = IntParam("maxDepth", "maximum tree depth", 5,
                        validator=lambda v: 1 <= v <= 12)
    maxBins = IntParam("maxBins", "maximum feature histogram bins", 32,
                       validator=lambda v: 2 <= v <= 256)
    minInstancesPerNode = IntParam(
        "minInstancesPerNode", "minimum (weighted) rows per child", 1)
    lam = FloatParam("lam", "leaf/gain L2 regularization", 1e-6)
    hints = _TREE_HINTS

    def _prep(self, frame: Frame):
        """Streamed histogram prep: (y, edges, Xb-uint8).

        Histogram CART needs global quantile bins, but NOT the fp32 matrix:
        edges come from a seeded row SAMPLE streamed off the frame (exact
        below ``_BIN_SAMPLE_ROWS`` rows — golden-metric parity — sampled
        above), then a second streaming pass bins every row into a uint8
        matrix. Peak host memory is n*F BYTES plus one fp32 batch — 8x
        under the old collect-everything path (fp32 X + int32 bins),
        which is what lets trees fit DiskFrames bigger than RAM.
        """
        fcol, lcol = self.featuresCol, self.labelCol
        n = frame.count()
        if n == 0:
            raise ValueError(f"{type(self).__name__}: empty frame")
        take = min(1.0, _BIN_SAMPLE_ROWS / n)
        rng = np.random.default_rng(0)
        sample: List[np.ndarray] = []
        ys: List[np.ndarray] = []
        F = None
        for hb in frame.batches(1 << 16, cols=[fcol, lcol]):
            x = np.asarray(hb[fcol], np.float32)
            if x.ndim != 2:
                raise ValueError(f"features column {fcol!r} must be a "
                                 "vector column")
            F = x.shape[1]
            ys.append(np.asarray(hb[lcol]))
            sample.append(x if take >= 1.0
                          else x[rng.random(len(x)) < take])
        y = np.concatenate(ys)
        full = np.concatenate(sample) if len(sample) > 1 else sample[0]
        edges = make_bin_edges(full, self.maxBins)
        if take >= 1.0:
            # the "sample" IS the whole frame in order (bounded by the
            # sample cap) — bin it directly, no second streaming pass
            Xb = np.empty((n, F), np.uint8)
            Xb[:] = bin_features(full, edges)
            return y, edges, Xb
        # drop the fp32 sample BEFORE allocating the bin matrix: at the
        # RAM edge the two must not be resident together
        del sample, full
        Xb = np.empty((n, F), np.uint8)  # maxBins <= 256 -> bins fit uint8
        off = 0
        for hb in frame.batches(1 << 16, cols=[fcol]):
            x = np.asarray(hb[fcol], np.float32)
            Xb[off:off + len(x)] = bin_features(x, edges)
            off += len(x)
        return y, edges, Xb


def _leaf_probs(leaf_V: np.ndarray, leaf_w: np.ndarray,
                n_classes: int) -> np.ndarray:
    """Class distribution per leaf; empty leaves get the uniform prior."""
    w = leaf_w[..., None]
    probs = np.where(w > 0, leaf_V / np.maximum(w, 1e-12), 1.0 / n_classes)
    return probs.astype(np.float32)


# --------------------------------------------------------------------------
@register_stage
class DecisionTreeClassifier(_TreeParams):
    """Single CART tree: gini-gain splits, leaf = class distribution."""

    def fit(self, frame: Frame) -> "TreeClassifierModel":
        y, edges, Xb = self._prep(frame)
        y = y.astype(np.int32)
        K = self._num_classes(frame, y)
        n, F = Xb.shape
        V = np.eye(K, dtype=np.float32)[y]

        fn = jax.jit(grow_tree, static_argnums=(4, 5))
        feats, bins, leaf_V, leaf_w, _ = fn(
            _device_bins(Xb), jnp.asarray(V), jnp.ones(n, jnp.float32),
            jnp.ones(F, bool), self.maxDepth, self.maxBins,
            self.lam, float(self.minInstancesPerNode))
        feats, bins = np.asarray(feats), np.asarray(bins)
        model = TreeClassifierModel(featuresCol=self.featuresCol,
                                    labelCol=self.labelCol)
        model._state = {
            "feats": feats[None], "thrs": bins_to_thresholds(feats, bins, edges)[None],
            "leaf_probs": _leaf_probs(np.asarray(leaf_V), np.asarray(leaf_w), K)[None],
            "depth": self.maxDepth, "n_classes": K}
        return model


@register_stage
class RandomForestClassifier(_TreeParams):
    """Bootstrap forest of gini trees, built as ONE vmapped XLA program."""

    numTrees = IntParam("numTrees", "number of trees", 20,
                        validator=lambda v: v >= 1)
    featureSubsetStrategy = StringParam(
        "featureSubsetStrategy", "features considered per tree",
        "auto", domain=["auto", "all", "sqrt", "log2", "onethird"])
    subsamplingRate = FloatParam("subsamplingRate", "bootstrap sample rate", 1.0)
    seed = IntParam("seed", "random seed", 0)

    def fit(self, frame: Frame) -> "TreeClassifierModel":
        y, edges, Xb = self._prep(frame)
        y = y.astype(np.int32)
        K = self._num_classes(frame, y)
        n, F = Xb.shape
        T = self.numTrees
        rng = np.random.default_rng(self.seed)
        V = np.eye(K, dtype=np.float32)[y]
        # multinomial bootstrap as per-row weights (vmap-friendly resampling)
        draws = max(1, int(round(n * self.subsamplingRate)))
        weights = rng.multinomial(
            draws, np.full(n, 1.0 / n), size=T).astype(np.float32)
        masks = _feature_masks(F, T, self.featureSubsetStrategy, True, rng)

        grow = jax.vmap(
            lambda w, m: grow_tree(_device_bins(Xb), jnp.asarray(V) * w[:, None],
                                   w, m, self.maxDepth, self.maxBins,
                                   self.lam, float(self.minInstancesPerNode)))
        feats, bins, leaf_V, leaf_w, _ = jax.jit(grow)(
            jnp.asarray(weights), jnp.asarray(masks))
        feats, bins = np.asarray(feats), np.asarray(bins)
        thrs = np.stack([bins_to_thresholds(feats[t], bins[t], edges)
                         for t in range(T)])
        model = TreeClassifierModel(featuresCol=self.featuresCol,
                                    labelCol=self.labelCol)
        model._state = {
            "feats": feats, "thrs": thrs,
            "leaf_probs": _leaf_probs(np.asarray(leaf_V), np.asarray(leaf_w), K),
            "depth": self.maxDepth, "n_classes": K}
        return model


@register_stage
class TreeClassifierModel(HasFeaturesCol, HasLabelCol, Model):
    """Scores by averaging leaf class distributions over trees (T>=1)."""

    def scores_fn(self):
        feats = jnp.asarray(self._state["feats"])     # (T, 2^D-1)
        thrs = jnp.asarray(self._state["thrs"])
        probs = jnp.asarray(self._state["leaf_probs"])  # (T, 2^D, K)
        depth = int(self._state["depth"])

        @jax.jit
        def f(X):
            leaves = jax.vmap(lambda ft, th: predict_leaves(X, ft, th, depth))(
                feats, thrs)                           # (T, n)
            p = jax.vmap(lambda pr, lv: pr[lv])(probs, leaves)  # (T, n, K)
            p = p.mean(axis=0)
            return jnp.log(p + 1e-12), p
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


# --------------------------------------------------------------------------
@register_stage
class DecisionTreeRegressor(_TreeParams):
    """Single variance-reduction tree; leaf = mean target."""

    is_classifier = False

    def fit(self, frame: Frame) -> "TreeRegressorModel":
        y, edges, Xb = self._prep(frame)
        y = y.astype(np.float32)
        n, F = Xb.shape
        fn = jax.jit(grow_tree, static_argnums=(4, 5))
        feats, bins, leaf_V, leaf_w, _ = fn(
            _device_bins(Xb), jnp.asarray(y)[:, None], jnp.ones(n, jnp.float32),
            jnp.ones(F, bool), self.maxDepth, self.maxBins,
            self.lam, float(self.minInstancesPerNode))
        feats, bins = np.asarray(feats), np.asarray(bins)
        leaf_w = np.asarray(leaf_w)
        values = np.where(leaf_w > 0,
                          np.asarray(leaf_V)[:, 0] / np.maximum(leaf_w, 1e-12),
                          float(y.mean())).astype(np.float32)
        model = TreeRegressorModel(featuresCol=self.featuresCol,
                                   labelCol=self.labelCol)
        model._state = {
            "feats": feats[None], "thrs": bins_to_thresholds(feats, bins, edges)[None],
            "values": values[None], "depth": self.maxDepth,
            "base": 0.0, "scale": 1.0}
        return model


@register_stage
class RandomForestRegressor(_TreeParams):
    is_classifier = False
    numTrees = IntParam("numTrees", "number of trees", 20,
                        validator=lambda v: v >= 1)
    featureSubsetStrategy = StringParam(
        "featureSubsetStrategy", "features considered per tree",
        "auto", domain=["auto", "all", "sqrt", "log2", "onethird"])
    subsamplingRate = FloatParam("subsamplingRate", "bootstrap sample rate", 1.0)
    seed = IntParam("seed", "random seed", 0)

    def fit(self, frame: Frame) -> "TreeRegressorModel":
        y, edges, Xb = self._prep(frame)
        y = y.astype(np.float32)
        n, F = Xb.shape
        T = self.numTrees
        rng = np.random.default_rng(self.seed)
        draws = max(1, int(round(n * self.subsamplingRate)))
        weights = rng.multinomial(
            draws, np.full(n, 1.0 / n), size=T).astype(np.float32)
        masks = _feature_masks(F, T, self.featureSubsetStrategy, False, rng)

        grow = jax.vmap(
            lambda w, m: grow_tree(_device_bins(Xb),
                                   (jnp.asarray(y) * w)[:, None], w, m,
                                   self.maxDepth, self.maxBins,
                                   self.lam, float(self.minInstancesPerNode)))
        feats, bins, leaf_V, leaf_w, _ = jax.jit(grow)(
            jnp.asarray(weights), jnp.asarray(masks))
        feats, bins = np.asarray(feats), np.asarray(bins)
        leaf_w = np.asarray(leaf_w)
        values = np.where(leaf_w > 0,
                          np.asarray(leaf_V)[..., 0] / np.maximum(leaf_w, 1e-12),
                          float(y.mean())).astype(np.float32)
        thrs = np.stack([bins_to_thresholds(feats[t], bins[t], edges)
                         for t in range(T)])
        model = TreeRegressorModel(featuresCol=self.featuresCol,
                                   labelCol=self.labelCol)
        model._state = {"feats": feats, "thrs": thrs, "values": values,
                        "depth": self.maxDepth, "base": 0.0, "scale": 1.0 / T}
        return model


@register_stage
class TreeRegressorModel(HasFeaturesCol, HasLabelCol, Model):
    """prediction = base + scale * sum_t leaf_value_t(x); scale=1/T gives a
    forest mean, scale=learning-rate gives a boosted ensemble."""

    def predict_fn(self):
        feats = jnp.asarray(self._state["feats"])
        thrs = jnp.asarray(self._state["thrs"])
        values = jnp.asarray(self._state["values"])    # (T, 2^D)
        depth = int(self._state["depth"])
        base = float(self._state["base"])
        scale = float(self._state["scale"])

        @jax.jit
        def f(X):
            leaves = jax.vmap(lambda ft, th: predict_leaves(X, ft, th, depth))(
                feats, thrs)
            preds = jax.vmap(lambda v, lv: v[lv])(values, leaves)  # (T, n)
            return base + scale * preds.sum(axis=0)
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_regressor(self, frame)


# --------------------------------------------------------------------------
# gradient boosting
class _GBTBase(_TreeParams):
    maxIter = IntParam("maxIter", "boosting rounds", 20,
                       validator=lambda v: v >= 1)
    stepSize = FloatParam("stepSize", "shrinkage (learning rate)", 0.1)

    def _boost(self, Xb: np.ndarray, edges: np.ndarray, grad_fn,
               F0: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Generic Newton boosting loop. grad_fn(F) -> (g, h); the per-round
        tree fits -g/h via the gradient gain and its training-set leaf
        assignment (returned by grow_tree) updates F without a re-walk."""
        n, F_feats = Xb.shape
        depth, B = self.maxDepth, self.maxBins
        lam = max(self.lam, 1e-6)
        Xb_d = _device_bins(Xb)
        ones_mask = jnp.ones(F_feats, bool)
        min_w = float(self.minInstancesPerNode)

        @jax.jit
        def round_(Fcur):
            g, h = grad_fn(Fcur)
            feats, bins, leaf_V, leaf_w, node = grow_tree(
                Xb_d, (-g)[:, None], h, ones_mask, depth, B, lam, min_w,
                counts=jnp.ones_like(h))
            # Newton leaf: sum(-g)/(sum(h)+lam)
            value = leaf_V[:, 0] / (leaf_w + lam)
            Fnew = Fcur + self.stepSize * value[node]
            return Fnew, feats, bins, value

        Fcur = jnp.asarray(F0)
        all_feats, all_bins, all_values = [], [], []
        for _ in range(self.maxIter):
            Fcur, feats, bins, value = round_(Fcur)
            all_feats.append(np.asarray(feats))
            all_bins.append(np.asarray(bins))
            all_values.append(np.asarray(value))
        feats = np.stack(all_feats)
        thrs = np.stack([bins_to_thresholds(f, b, edges)
                         for f, b in zip(all_feats, all_bins)])
        return feats, thrs, np.stack(all_values).astype(np.float32)


@register_stage
class GBTClassifier(_GBTBase):
    """Binary gradient-boosted trees on logistic loss (Spark GBTClassifier
    is binary-only, ``TrainClassifier.scala:108-116``)."""

    def fit(self, frame: Frame) -> "GBTClassifierModel":
        y, edges, Xb = self._prep(frame)
        y = y.astype(np.int32)
        K = self._num_classes(frame, y)
        if K > 2:
            raise ValueError("GBTClassifier supports binary labels only "
                             "(parity with Spark GBTClassifier)")
        yf = jnp.asarray(y.astype(np.float32))
        p0 = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
        F0 = np.full(len(y), np.log(p0 / (1 - p0)), np.float32)

        def grad_fn(Fcur):
            p = jax.nn.sigmoid(Fcur)
            return p - yf, p * (1 - p)

        feats, thrs, values = self._boost(Xb, edges, grad_fn, F0)
        model = GBTClassifierModel(featuresCol=self.featuresCol,
                                   labelCol=self.labelCol)
        model._state = {"feats": feats, "thrs": thrs, "values": values,
                        "depth": self.maxDepth, "base": float(F0[0]),
                        "scale": self.stepSize, "n_classes": 2}
        return model


@register_stage
class GBTClassifierModel(HasFeaturesCol, HasLabelCol, Model):
    def scores_fn(self):
        feats = jnp.asarray(self._state["feats"])
        thrs = jnp.asarray(self._state["thrs"])
        values = jnp.asarray(self._state["values"])
        depth = int(self._state["depth"])
        base = float(self._state["base"])
        scale = float(self._state["scale"])

        @jax.jit
        def f(X):
            leaves = jax.vmap(lambda ft, th: predict_leaves(X, ft, th, depth))(
                feats, thrs)
            margin = base + scale * jax.vmap(lambda v, lv: v[lv])(
                values, leaves).sum(axis=0)
            p1 = jax.nn.sigmoid(margin)
            probs = jnp.stack([1 - p1, p1], axis=1)
            logits = jnp.stack([-margin / 2, margin / 2], axis=1)
            return logits, probs
        return f

    def transform(self, frame: Frame) -> Frame:
        return _score_classifier(self, frame)


@register_stage
class GBTRegressor(_GBTBase):
    """Gradient-boosted trees on squared loss."""

    is_classifier = False

    def fit(self, frame: Frame) -> "TreeRegressorModel":
        y, edges, Xb = self._prep(frame)
        y = y.astype(np.float32)
        yd = jnp.asarray(y)
        F0 = np.full(len(y), float(y.mean()), np.float32)

        def grad_fn(Fcur):
            return Fcur - yd, jnp.ones_like(Fcur)

        feats, thrs, values = self._boost(Xb, edges, grad_fn, F0)
        model = TreeRegressorModel(featuresCol=self.featuresCol,
                                   labelCol=self.labelCol)
        model._state = {"feats": feats, "thrs": thrs, "values": values,
                        "depth": self.maxDepth, "base": float(F0[0]),
                        "scale": self.stepSize}
        return model
