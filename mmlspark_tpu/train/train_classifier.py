"""TrainClassifier / TrainRegressor: one-line auto-featurize + train wrappers.

Re-expression of the reference AutoML path
(``train-classifier/src/main/scala/TrainClassifier.scala:81-337``,
``train-regressor/src/main/scala/TrainRegressor.scala:43-117``):

- label conversion: reindex labels through ValueIndexer (``convertLabel``,
  ``TrainClassifier.scala:187-233``), remember the levels;
- learner-dependent featurize params (``getFeaturizeParams`` ``:170-185``):
  tree/NN learners get a 2^12 hash space, trees skip one-hot — expressed
  here as a ``FeaturizeHints`` attribute on each learner;
- fit featurizer then learner; produce a model that re-featurizes at scoring
  time, renames prediction/rawPrediction/probability to
  scored_labels/scores/scored_probabilities, and stamps score metadata +
  label levels on the output columns (``TrainedClassifierModel.transform``
  ``:286-337``) so ComputeModelStatistics can discover them.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import AnyParam, HasLabelCol, IntParam, ListParam
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.schema import (
    CategoricalMap, ColumnSchema, DType, ScoreKind,
)
from mmlspark_tpu.core.serialization import register_stage
from mmlspark_tpu.feature.featurize import Featurize
from mmlspark_tpu.feature.value_indexer import ValueIndexer
from mmlspark_tpu.train.learners import FeaturizeHints, JaxEstimator


@register_stage
class TrainClassifier(HasLabelCol, Estimator):
    model = AnyParam("model", "the classifier learner to fit")
    numFeatures = IntParam("numFeatures", "override hash space size", 0)
    labels = ListParam("labels", "optional explicit label ordering", None)

    def fit(self, frame: Frame) -> "TrainedClassifierModel":
        learner = self.get("model")
        if learner is None:
            raise ValueError("TrainClassifier requires a `model` learner")
        label_col = self.labelCol

        # -- label conversion (reference convertLabel :187-233)
        frame = frame.na_drop([label_col])
        indexed_col = frame.schema.find_unused_name("_indexed_label")
        explicit = self.get("labels")
        if explicit:
            cmap = CategoricalMap(list(explicit))
            def to_index(p):
                return np.asarray(
                    [cmap.get_index(v.item() if isinstance(v, np.generic) else v)
                     for v in p[label_col]], dtype=np.int32)
            indexed = frame.with_column(
                ColumnSchema(indexed_col, DType.INT32,
                             metadata={"categorical": cmap.to_metadata()}),
                to_index)
            levels = list(explicit)
        else:
            vi = ValueIndexer(inputCol=label_col, outputCol=indexed_col).fit(frame)
            indexed = vi.transform(frame)
            levels = vi._state["levels"]

        # -- learner-dependent featurization (reference :170-185)
        hints: FeaturizeHints = getattr(type(learner), "hints", FeaturizeHints())
        num_features = self.numFeatures or hints.num_features
        feature_cols = [c for c in frame.schema.names if c != label_col]
        features_col = indexed.schema.find_unused_name("features")
        featurizer = Featurize(
            featureColumns={features_col: feature_cols},
            numberOfFeatures=num_features,
            oneHotEncodeCategoricals=hints.one_hot).fit(indexed)
        processed = featurizer.transform(indexed)

        # -- fit the learner on device
        learner = learner.copy()
        learner.set_params(featuresCol=features_col, labelCol=indexed_col)
        fitted = learner.fit(processed)

        model = TrainedClassifierModel(labelCol=label_col)
        model.set_params(featurizeModel=featurizer, learnerModel=fitted)
        model._state = {"levels": levels, "features_col": features_col}
        return model


@register_stage
class TrainedClassifierModel(HasLabelCol, Model):
    featurizeModel = AnyParam("featurizeModel", "fitted featurization pipeline")
    learnerModel = AnyParam("learnerModel", "fitted classifier model")

    @property
    def levels(self) -> List:
        return self._state["levels"]

    def transform(self, frame: Frame) -> Frame:
        return self.transform_featurized(
            self.get("featurizeModel").transform(frame))

    def transform_featurized(self, featurized: Frame) -> Frame:
        """Score a frame ALREADY transformed by this model's featurizeModel.

        FindBestModel featurizes once per distinct featurization and fans
        the candidate learners out over the shared featurized frame — K
        candidates cost ~1 featurize pass, not K (the reference re-ran the
        full pipeline per candidate, ``FindBestModel.scala:135-143``)."""
        scored = self.get("learnerModel").transform(featurized)
        features_col = self._state.get("features_col", "features")
        scored = scored.drop(features_col).rename({
            "prediction": ScoreKind.SCORED_LABELS,
            "rawPrediction": ScoreKind.SCORES,
            "probability": ScoreKind.SCORED_PROBABILITIES,
        })
        cmap = CategoricalMap(self.levels)
        meta = dict(score_value_kind=ScoreKind.CLASSIFICATION, model_uid=self.uid)
        scored = scored.with_metadata(
            ScoreKind.SCORED_LABELS, score_kind=ScoreKind.SCORED_LABELS,
            categorical=cmap.to_metadata(), **meta)
        scored = scored.with_metadata(
            ScoreKind.SCORES, score_kind=ScoreKind.SCORES, **meta)
        scored = scored.with_metadata(
            ScoreKind.SCORED_PROBABILITIES,
            score_kind=ScoreKind.SCORED_PROBABILITIES, **meta)
        if self.labelCol in scored.schema:
            scored = scored.with_metadata(
                self.labelCol, score_kind=ScoreKind.TRUE_LABELS,
                categorical=cmap.to_metadata(), **meta)
        return scored


@register_stage
class TrainRegressor(HasLabelCol, Estimator):
    """Same pattern minus label indexing; string labels rejected
    (reference TrainRegressor.scala:43-117)."""

    model = AnyParam("model", "the regressor learner to fit")
    numFeatures = IntParam("numFeatures", "override hash space size", 0)

    def fit(self, frame: Frame) -> "TrainedRegressorModel":
        learner = self.get("model")
        if learner is None:
            raise ValueError("TrainRegressor requires a `model` learner")
        label_col = self.labelCol
        if frame.schema[label_col].dtype == DType.STRING:
            raise ValueError(
                f"TrainRegressor: label column {label_col!r} is a string; "
                "cast it to numeric first (reference rejects string labels)")
        frame = frame.na_drop([label_col])

        hints: FeaturizeHints = getattr(type(learner), "hints", FeaturizeHints())
        num_features = self.numFeatures or hints.num_features
        feature_cols = [c for c in frame.schema.names if c != label_col]
        features_col = frame.schema.find_unused_name("features")
        featurizer = Featurize(
            featureColumns={features_col: feature_cols},
            numberOfFeatures=num_features,
            oneHotEncodeCategoricals=hints.one_hot).fit(frame)
        processed = featurizer.transform(frame)

        learner = learner.copy()
        learner.set_params(featuresCol=features_col, labelCol=label_col)
        fitted = learner.fit(processed)

        model = TrainedRegressorModel(labelCol=label_col)
        model.set_params(featurizeModel=featurizer, learnerModel=fitted)
        model._state = {"features_col": features_col}
        return model


@register_stage
class TrainedRegressorModel(HasLabelCol, Model):
    featurizeModel = AnyParam("featurizeModel", "fitted featurization pipeline")
    learnerModel = AnyParam("learnerModel", "fitted regressor model")

    def transform(self, frame: Frame) -> Frame:
        return self.transform_featurized(
            self.get("featurizeModel").transform(frame))

    def transform_featurized(self, featurized: Frame) -> Frame:
        """Score a pre-featurized frame (see TrainedClassifierModel)."""
        scored = self.get("learnerModel").transform(featurized)
        features_col = self._state.get("features_col", "features")
        scored = scored.drop(features_col).rename(
            {"prediction": ScoreKind.SCORES})
        meta = dict(score_value_kind=ScoreKind.REGRESSION, model_uid=self.uid)
        scored = scored.with_metadata(
            ScoreKind.SCORES, score_kind=ScoreKind.SCORES, **meta)
        if self.labelCol in scored.schema:
            scored = scored.with_metadata(
                self.labelCol, score_kind=ScoreKind.TRUE_LABELS, **meta)
        return scored
