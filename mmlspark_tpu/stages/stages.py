"""Data-plumbing pipeline stages (the reference's L5 surface).

TPU-first re-expression of:
- ``Repartition`` (``pipeline-stages/src/main/scala/Repartition.scala:15-41``)
- ``SelectColumns`` (``pipeline-stages/src/main/scala/SelectColumns.scala:22-63``)
- ``DataConversion`` (``data-conversion/src/main/scala/DataConversion.scala:22-165``)
- ``SummarizeData`` (``summarize-data/src/main/scala/SummarizeData.scala:55-189``)
- ``PartitionSample`` (``partition-sample/src/main/scala/PartitionSample.scala:81-117``)
- ``CheckpointData`` (``checkpoint-data/src/main/scala/CheckpointData.scala:31-70``)

These are host-side columnar ops on Frame partitions — no device round trip
(a repartition or type cast must not burn HBM bandwidth). Statistics in
SummarizeData are computed per-partition and merged, which is also the shape
the multi-host version takes (per-host partials + one small allreduce).
"""
from __future__ import annotations

import datetime as _dt
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import (
    BooleanParam, FloatParam, IntParam, ListParam, StringParam,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import ColumnSchema, DType, SchemaError
from mmlspark_tpu.core.serialization import register_stage


@register_stage
class Repartition(Transformer):
    """Change the Frame's partition count; ``disable`` passes through.

    Reference semantics (``Repartition.scala:15-41``): coalesce when
    shrinking, full repartition when growing.
    """

    n = IntParam("n", "number of partitions", validator=lambda v: v > 0)
    disable = BooleanParam("disable", "pass through unchanged", False)

    def transform(self, frame: Frame) -> Frame:
        if self.disable:
            return frame
        n = self.n
        if n < frame.num_partitions:
            return frame.coalesce(n)
        return frame.repartition(n)


@register_stage
class SelectColumns(Transformer):
    """Schema-verified column projection (``SelectColumns.scala:22-63``)."""

    cols = ListParam("cols", "names of the columns to keep")

    def transform(self, frame: Frame) -> Frame:
        self._verify(frame.schema.names)
        return frame.select(*self.cols)

    def transform_schema(self, schema):
        self._verify(schema.names)
        return schema.select(self.cols)

    def _verify(self, have: List[str]) -> None:
        missing = [c for c in self.cols if c not in have]
        if missing:
            raise SchemaError(f"frame does not contain columns: {missing}")


@register_stage
class DropColumns(Transformer):
    """Inverse of SelectColumns: drop the listed columns."""

    cols = ListParam("cols", "names of the columns to drop")

    def transform(self, frame: Frame) -> Frame:
        missing = [c for c in self.cols if c not in frame.schema.names]
        if missing:
            raise SchemaError(f"frame does not contain columns: {missing}")
        return frame.drop(*self.cols)

    def transform_schema(self, schema):
        return schema.drop(self.cols)


@register_stage
class RenameColumn(Transformer):
    """Rename a column, metadata preserved."""

    inputCol = StringParam("inputCol", "current column name")
    outputCol = StringParam("outputCol", "new column name")

    def transform(self, frame: Frame) -> Frame:
        return frame.rename({self.inputCol: self.outputCol})

    def transform_schema(self, schema):
        from mmlspark_tpu.core.schema import Schema
        return Schema([c.renamed(self.outputCol) if c.name == self.inputCol else c
                       for c in schema])


_NUMERIC_TARGETS = {
    "boolean": DType.BOOL, "integer": DType.INT32, "long": DType.INT64,
    "float": DType.FLOAT32, "double": DType.FLOAT64,
}


@register_stage
class DataConversion(Transformer):
    """Multi-column type conversion incl. categorical make/clear and dates.

    Reference dispatch (``DataConversion.scala:65-79``): numeric casts,
    ``toCategorical`` (ValueIndexer in place), ``clearCategorical``
    (IndexToValue in place), and date<->string/long conversions. Dates are
    held as INT64 epoch-milliseconds with a ``datetime`` metadata marker —
    a TPU-friendly representation (integer columns stream straight into
    device arrays), formatted only at the string boundary.
    """

    cols = ListParam("cols", "columns to convert")
    convertTo = StringParam(
        "convertTo", "target type", domain=sorted(
            list(_NUMERIC_TARGETS) + ["string", "toCategorical",
                                      "clearCategorical", "date"]))
    dateTimeFormat = StringParam(
        "dateTimeFormat", "strftime format for date<->string conversions",
        "%Y-%m-%d %H:%M:%S")

    def transform(self, frame: Frame) -> Frame:
        missing = [c for c in self.cols if c not in frame.schema.names]
        if missing:
            raise SchemaError(f"frame does not contain columns: {missing}")
        for col in self.cols:
            frame = self._convert(frame, col)
        return frame

    def _convert(self, frame: Frame, col: str) -> Frame:
        target = self.convertTo
        cs = frame.schema[col]
        if target == "toCategorical":
            from mmlspark_tpu.feature.value_indexer import ValueIndexer
            model = ValueIndexer(inputCol=col, outputCol=col).fit(frame)
            return model.transform(frame)
        if target == "clearCategorical":
            from mmlspark_tpu.feature.value_indexer import IndexToValue
            return IndexToValue(inputCol=col, outputCol=col).transform(frame)
        if target == "date":
            return self._to_date(frame, col, cs)
        if target == "string":
            return self._to_string(frame, col, cs)
        dtype = _NUMERIC_TARGETS[target]
        if cs.dtype == DType.STRING and dtype == DType.BOOL:
            raise SchemaError("string to boolean conversion is not supported")
        if cs.metadata.get("datetime"):  # date -> numeric: epoch millis
            if dtype != DType.INT64:
                raise SchemaError("date only converts to long or string")
            md = {k: v for k, v in cs.metadata.items() if k != "datetime"}
            return Frame(frame.schema.add(ColumnSchema(col, DType.INT64, None, md)),
                        frame.partitions)

        def cast(p):
            arr = p[col]
            if arr.dtype == np.object_:  # strings -> numeric
                out = np.empty(len(arr), np.float64)
                for i, v in enumerate(arr):
                    out[i] = np.nan if v is None or v == "" else float(v)
                arr = out
            if np.issubdtype(arr.dtype, np.floating) \
                    and np.issubdtype(dtype.numpy_dtype, np.integer) \
                    and np.isnan(arr).any():
                raise SchemaError(f"column {col!r} has missing values; cannot "
                                  f"cast to {target}")
            return arr.astype(dtype.numpy_dtype)

        return frame.with_column(ColumnSchema(col, dtype), cast)

    def _to_string(self, frame: Frame, col: str, cs: ColumnSchema) -> Frame:
        fmt = self.dateTimeFormat
        is_date = bool(cs.metadata.get("datetime"))

        def conv(p):
            arr = p[col]
            out = np.empty(len(arr), np.object_)
            for i, v in enumerate(arr):
                if is_date:
                    t = _dt.datetime.fromtimestamp(int(v) / 1000.0, _dt.timezone.utc)
                    out[i] = t.strftime(fmt)
                elif isinstance(v, (np.bool_, bool)):
                    out[i] = str(bool(v)).lower()
                elif isinstance(v, (np.integer, int)):
                    out[i] = str(int(v))
                else:
                    out[i] = str(v)
            return out

        return frame.with_column(ColumnSchema(col, DType.STRING), conv)

    def _to_date(self, frame: Frame, col: str, cs: ColumnSchema) -> Frame:
        fmt = self.dateTimeFormat
        if cs.dtype not in (DType.STRING, DType.INT64):
            raise SchemaError("can only convert string or long to date")

        def conv(p):
            arr = p[col]
            out = np.empty(len(arr), np.int64)
            for i, v in enumerate(arr):
                if cs.dtype == DType.STRING:
                    t = _dt.datetime.strptime(v, fmt).replace(
                        tzinfo=_dt.timezone.utc)
                    out[i] = int(t.timestamp() * 1000)
                else:
                    out[i] = int(v)
            return out

        return frame.with_column(
            ColumnSchema(col, DType.INT64, None, {"datetime": True}), conv)


@register_stage
class SummarizeData(Transformer):
    """Per-column statistics as a new Frame keyed by ``Feature``.

    Reference (``SummarizeData.scala:55-189``): counts (count / unique /
    missing), basic quantiles (min/quartiles/max), sample moments
    (variance/std/skew/kurtosis), tail percentiles. Sub-tables toggle via
    params and join on the feature name. Non-numeric columns yield NaN for
    numeric stats, matching the reference's ``allNaNs`` fill.
    """

    counts = BooleanParam("counts", "include count statistics", True)
    basic = BooleanParam("basic", "include basic quantile statistics", True)
    sample = BooleanParam("sample", "include sample moment statistics", True)
    percentiles = BooleanParam("percentiles", "include tail percentiles", True)
    errorThreshold = FloatParam(
        "errorThreshold", "quantile approximation error (0 = exact)", 0.0)

    _BASIC_Q = [0.0, 0.25, 0.5, 0.75, 1.0]
    _BASIC_NAMES = ["Min", "1st Quartile", "Median", "3rd Quartile", "Max"]
    _PERC_Q = [0.005, 0.01, 0.05, 0.95, 0.99, 0.995]
    _PERC_NAMES = ["P0.5", "P1", "P5", "P95", "P99", "P99.5"]

    def transform(self, frame: Frame) -> Frame:
        out: Dict[str, List[Any]] = {"Feature": []}
        tables: List[List[str]] = []
        if self.counts:
            tables.append(["Count", "Unique Value Count", "Missing Value Count"])
        if self.basic:
            tables.append(self._BASIC_NAMES)
        if self.sample:
            tables.append(["Sample Variance", "Sample Standard Deviation",
                           "Sample Skewness", "Sample Kurtosis"])
        if self.percentiles:
            tables.append(self._PERC_NAMES)
        for names in tables:
            for n in names:
                out[n] = []

        for cs in frame.schema:
            out["Feature"].append(cs.name)
            arr = frame.column(cs.name)
            numeric = self._numeric_view(arr, cs)
            if self.counts:
                self._append(out, ["Count", "Unique Value Count",
                                   "Missing Value Count"],
                             self._counts(arr, cs))
            if self.basic:
                self._append(out, self._BASIC_NAMES,
                             self._quantiles(numeric, self._BASIC_Q))
            if self.sample:
                self._append(out, ["Sample Variance",
                                   "Sample Standard Deviation",
                                   "Sample Skewness", "Sample Kurtosis"],
                             self._moments(numeric))
            if self.percentiles:
                self._append(out, self._PERC_NAMES,
                             self._quantiles(numeric, self._PERC_Q))
        return Frame.from_dict(out)

    @staticmethod
    def _append(out, names, vals):
        for n, v in zip(names, vals):
            out[n].append(v)

    @staticmethod
    def _numeric_view(arr: np.ndarray, cs: ColumnSchema) -> Optional[np.ndarray]:
        if not cs.dtype.is_numeric or arr.ndim > 1:
            return None
        vals = arr.astype(np.float64)
        return vals[~np.isnan(vals)]

    @staticmethod
    def _counts(arr: np.ndarray, cs: ColumnSchema) -> List[float]:
        n = len(arr)
        if arr.dtype == np.object_:
            missing = sum(1 for v in arr if v is None)
            uniq = len({v for v in arr if v is not None})
        elif arr.ndim > 1:
            missing = int(np.isnan(arr).any(axis=1).sum())
            uniq = len({tuple(r) for r in arr})
        elif np.issubdtype(arr.dtype, np.floating):
            nan = np.isnan(arr)
            missing = int(nan.sum())
            uniq = len(np.unique(arr[~nan]))
        else:
            missing = 0
            uniq = len(np.unique(arr))
        return [float(n - missing), float(uniq), float(missing)]

    def _quantiles(self, numeric: Optional[np.ndarray], qs: List[float]) -> List[float]:
        if numeric is None or len(numeric) == 0:
            return [float("nan")] * len(qs)
        return [float(v) for v in np.quantile(numeric, qs)]

    @staticmethod
    def _moments(numeric: Optional[np.ndarray]) -> List[float]:
        if numeric is None or len(numeric) < 2:
            return [float("nan")] * 4
        n = len(numeric)
        mean = numeric.mean()
        d = numeric - mean
        m2 = float((d ** 2).sum())
        var = m2 / (n - 1)  # sample variance, Spark semantics
        std = float(np.sqrt(var))
        pop_std = float(np.sqrt(m2 / n))
        if pop_std == 0:
            skew = kurt = float("nan")
        else:
            # Spark's skewness/kurtosis are population-style (no bias correction)
            skew = float((d ** 3).mean() / pop_std ** 3)
            kurt = float((d ** 4).mean() / pop_std ** 4 - 3.0)
        return [var, std, skew, kurt]


@register_stage
class PartitionSample(Transformer):
    """head / random sample / assign-to-partition.

    Reference (``PartitionSample.scala:81-117``); its AssignToPartition mode
    is a broken stub — here it actually stamps a partition-index column.
    """

    mode = StringParam("mode", "sampling mode", "RandomSample",
                       domain=["RandomSample", "Head", "AssignToPartition"])
    rsMode = StringParam("rsMode", "random-sample sizing", "Percentage",
                         domain=["Percentage", "Absolute"])
    seed = IntParam("seed", "random seed", -1)
    percent = FloatParam("percent", "fraction of rows to keep", 0.01)
    count = IntParam("count", "absolute number of rows", 1000)
    newColName = StringParam("newColName", "partition column name", "Partition")
    numParts = IntParam("numParts", "partitions for AssignToPartition", 10)

    def transform(self, frame: Frame) -> Frame:
        mode = self.mode
        if mode == "Head":
            return self._head(frame, self.count)
        if mode == "RandomSample":
            total = frame.count()
            frac = self.percent if self.rsMode == "Percentage" \
                else min(1.0, self.count / max(total, 1))
            seed = self.seed if self.seed >= 0 else 0
            rng = np.random.default_rng(seed)
            # Bernoulli per row (Spark .sample semantics: approximate size)
            return frame.filter(
                lambda p: rng.random(len(p[frame.schema.names[0]])) < frac)
        # AssignToPartition
        seed = self.seed if self.seed >= 0 else 0
        rng = np.random.default_rng(seed)
        col = ColumnSchema(self.newColName, DType.INT32)
        return frame.with_column(
            col, lambda p: rng.integers(
                0, self.numParts, len(p[frame.schema.names[0]]),
                dtype=np.int32))

    @staticmethod
    def _head(frame: Frame, n: int) -> Frame:
        parts, taken = [], 0
        for p in frame.partitions:
            size = len(p[frame.schema.names[0]])
            take = min(n - taken, size)
            if take > 0:
                parts.append({k: v[:take] for k, v in p.items()})
                taken += take
            if taken >= n:
                break
        return Frame(frame.schema, parts or None)


@register_stage
class CheckpointData(Transformer):
    """Persist/unpersist stage (``CheckpointData.scala:31-70``).

    MEMORY_ONLY semantics are a no-op here — Frame partitions are already
    materialized host arrays. ``diskIncluded=True`` is the
    MEMORY_AND_DISK analogue done the out-of-core way: the frame is
    STAGED as memory-mapped chunks (``core/disk.py``) and a DiskFrame
    over them is returned, so everything downstream streams with page
    eviction instead of holding the arrays in RAM. Numeric/vector
    columns only (the DiskFrame contract); ``removeCheckpoint`` on a
    DiskFrame re-materializes it in memory.
    """

    diskIncluded = BooleanParam("diskIncluded", "also spill to disk", False)
    removeCheckpoint = BooleanParam("removeCheckpoint", "unpersist instead", False)
    checkpointDir = StringParam(
        "checkpointDir", "directory for diskIncluded chunk staging "
        "('' = a fresh temp dir)", "")

    def transform(self, frame: Frame) -> Frame:
        import shutil
        from mmlspark_tpu.core.disk import DiskFrame, write_frame
        if self.removeCheckpoint:
            if isinstance(frame, DiskFrame):
                # np.array (not ascontiguousarray): a REAL writable copy —
                # a zero-copy view would still page from (and pin) the
                # chunk files this branch is about to reclaim
                out = Frame(frame.schema,
                            [{n: np.array(p[n])
                              for n in frame.schema.names}
                             for p in frame.partitions])
                staged = getattr(frame, "_checkpoint_dir", None)
                if staged:  # self-created staging only; user dirs are theirs
                    shutil.rmtree(staged, ignore_errors=True)
                return out
            return frame.unpersist()
        if not self.diskIncluded:
            return frame.cache()
        directory = self.checkpointDir or tempfile.mkdtemp(
            prefix="mmlspark_ckpt_")
        write_frame(frame, directory)
        out = DiskFrame.open(directory)
        if not self.checkpointDir:
            out._checkpoint_dir = directory  # removeCheckpoint reclaims it
        return out
