from mmlspark_tpu.stages.stages import (  # noqa: F401
    CheckpointData,
    DataConversion,
    DropColumns,
    PartitionSample,
    RenameColumn,
    Repartition,
    SelectColumns,
    SummarizeData,
)
