"""Image codecs: decode bytes -> uint8 HWC BGR arrays.

The reference decodes via OpenCV's ``Imgcodecs.imdecode`` behind JNI
(``readers/src/main/scala/ImageReader.scala:25-40``). Here:

- BMP and PNG decode in pure numpy/zlib (always available, used by tests);
- JPEG decodes through the native C++ bridge (libjpeg) when built
  (``mmlspark_tpu/native``), mirroring the reference's native fast path;
- undecodable bytes return None and the caller drops the row, matching the
  reference's silent-drop semantics (``ImageReader.scala:55-59``) — but we
  count drops so callers CAN surface them.

Channel order is BGR row-major uint8, the reference ImageSchema convention
(``core/schema/src/main/scala/ImageSchema.scala:18-23``).
"""
from __future__ import annotations

import struct
import zlib
from typing import Optional

import numpy as np


# -- BMP (24bpp uncompressed) ------------------------------------------------
def decode_bmp(data: bytes) -> Optional[np.ndarray]:
    try:
        if data[:2] != b"BM":
            return None
        pixel_off = struct.unpack_from("<I", data, 10)[0]
        header_size = struct.unpack_from("<I", data, 14)[0]
        if header_size < 40:
            return None
        w, h = struct.unpack_from("<ii", data, 18)
        planes, bpp = struct.unpack_from("<HH", data, 26)
        compression = struct.unpack_from("<I", data, 30)[0]
        if compression != 0 or bpp not in (24, 32):
            return None
        flip = h > 0
        h = abs(h)
        nch = bpp // 8
        row_size = (w * nch + 3) & ~3
        img = np.frombuffer(data, np.uint8, row_size * h, pixel_off)
        img = img.reshape(h, row_size)[:, :w * nch].reshape(h, w, nch)
        if flip:
            img = img[::-1]
        return np.ascontiguousarray(img[:, :, :3])  # already BGR in BMP
    except (struct.error, ValueError, IndexError):
        return None


def encode_bmp(img: np.ndarray) -> bytes:
    """uint8 HWC BGR -> 24bpp BMP (for tests/fixtures)."""
    h, w, c = img.shape
    assert c == 3
    row_size = (w * 3 + 3) & ~3
    pad = row_size - w * 3
    rows = b"".join(
        img[y].tobytes() + b"\x00" * pad for y in range(h - 1, -1, -1))
    pixel_off = 14 + 40
    size = pixel_off + len(rows)
    header = struct.pack("<2sIHHI", b"BM", size, 0, 0, pixel_off)
    info = struct.pack("<IiiHHIIiiII", 40, w, h, 1, 24, 0, len(rows),
                       2835, 2835, 0, 0)
    return header + info + rows


# -- PNG (8-bit gray/RGB/RGBA, non-interlaced) -------------------------------
_PNG_SIG = b"\x89PNG\r\n\x1a\n"


def decode_png(data: bytes) -> Optional[np.ndarray]:
    try:
        if data[:8] != _PNG_SIG:
            return None
        pos, w = 8, None
        idat = b""
        while pos < len(data):
            length, ctype = struct.unpack_from(">I4s", data, pos)
            chunk = data[pos + 8:pos + 8 + length]
            if ctype == b"IHDR":
                w, h, depth, color, comp, filt, interlace = \
                    struct.unpack(">IIBBBBB", chunk)
                if depth != 8 or interlace != 0 or color not in (0, 2, 6):
                    return None
                nch = {0: 1, 2: 3, 6: 4}[color]
            elif ctype == b"IDAT":
                idat += chunk
            elif ctype == b"IEND":
                break
            pos += 12 + length
        if w is None:
            return None
        raw = zlib.decompress(idat)
        stride = w * nch
        out = np.empty((h, stride), np.uint8)
        prev = np.zeros(stride, np.uint16)
        off = 0
        for y in range(h):
            ftype = raw[off]
            row = np.frombuffer(raw, np.uint8, stride, off + 1).astype(np.uint16)
            off += 1 + stride
            if ftype == 0:
                cur = row
            elif ftype == 1:  # Sub
                cur = row.copy()
                for i in range(nch, stride):
                    cur[i] = (cur[i] + cur[i - nch]) & 0xFF
            elif ftype == 2:  # Up
                cur = (row + prev) & 0xFF
            elif ftype == 3:  # Average
                cur = row.copy()
                for i in range(stride):
                    left = cur[i - nch] if i >= nch else 0
                    cur[i] = (cur[i] + ((left + prev[i]) >> 1)) & 0xFF
            elif ftype == 4:  # Paeth
                cur = row.copy()
                for i in range(stride):
                    a = int(cur[i - nch]) if i >= nch else 0
                    b = int(prev[i])
                    c = int(prev[i - nch]) if i >= nch else 0
                    p = a + b - c
                    pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                    pred = a if (pa <= pb and pa <= pc) else (b if pb <= pc else c)
                    cur[i] = (cur[i] + pred) & 0xFF
            else:
                return None
            out[y] = cur.astype(np.uint8)
            prev = cur
        img = out.reshape(h, w, nch)
        if nch == 1:
            img = np.repeat(img, 3, axis=2)
        elif nch == 4:
            img = img[:, :, :3]
        return np.ascontiguousarray(img[:, :, ::-1])  # RGB(A) -> BGR
    except (struct.error, ValueError, IndexError, zlib.error):
        return None


def encode_png(img: np.ndarray) -> bytes:
    """uint8 HWC BGR -> PNG RGB, filter 0 (for tests/fixtures)."""
    h, w, _ = img.shape
    rgb = img[:, :, ::-1]
    raw = b"".join(b"\x00" + rgb[y].tobytes() for y in range(h))

    def chunk(ctype: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + ctype + payload
                + struct.pack(">I", zlib.crc32(ctype + payload)))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    return (_PNG_SIG + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw))
            + chunk(b"IEND", b""))


# -- dispatch ----------------------------------------------------------------
def decode_image(data: bytes) -> Optional[np.ndarray]:
    """bytes -> uint8 HWC BGR, or None if undecodable."""
    if not data or len(data) < 8:
        return None
    if data[:2] == b"BM":
        return decode_bmp(data)
    if data[:8] == _PNG_SIG:
        # native libpng first (the python Paeth/Sub loops are slow);
        # fall back to the pure-python decoder when the .so is absent
        try:
            from mmlspark_tpu.utils.native_loader import native_decode_png
            out = native_decode_png(data)
            if out is not None:
                return out
        except (ImportError, OSError, RuntimeError):
            pass  # no native build; the pure-python decoder below covers it
        return decode_png(data)
    if data[:3] == b"\xff\xd8\xff":  # JPEG via native bridge
        try:
            from mmlspark_tpu.utils.native_loader import native_decode_jpeg
            return native_decode_jpeg(data)
        except Exception:
            return None
    return None
